"""Extended Keras-1.2 layer zoo (reference parity breadth).

Reference (SURVEY.md §2.3): zoo/.../pipeline/api/keras/layers/ carried the
full Keras-1.2 layer set (~120 classes) plus BigDL extras (Highway,
MaxoutDense, SReLU, ...).  layers.py holds the core set the model zoo
uses; this module widens coverage to the rest of the commonly-used API so
reference models port without rewrites.  All NHWC / NDHWC (TPU-native
layouts), pure functions of variables, jit/shard_map-composable.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from . import activations, initializers
from .layers import Conv2D, _pair
from .module import Module, Scope


def _triple(v: Union[int, Sequence[int]]) -> Tuple[int, int, int]:
    return (v, v, v) if isinstance(v, int) else tuple(v)  # type: ignore


def _norm_cropping(cropping: Union[int, Sequence[Any]], ndim: int
                   ) -> Tuple[Tuple[int, int], ...]:
    """int → symmetric per-dim; per-dim entries may be int or (lo, hi)."""
    if isinstance(cropping, int):
        return ((cropping, cropping),) * ndim
    return tuple((c, c) if isinstance(c, int) else tuple(c)
                 for c in cropping)


# -- convolution variants ------------------------------------------------------

class Conv3D(Module):
    """3-D convolution, NDHWC (reference: Convolution3D)."""

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "same", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "he_normal",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = _triple(kernel_size)
        self.strides = _triple(strides)
        self.padding = padding.upper()
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        kd, kh, kw = self.kernel_size
        w = scope.param("kernel", self.kernel_init,
                        (kd, kh, kw, x.shape[-1], self.filters))
        y = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), window_strides=self.strides,
            padding=self.padding,
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC")).astype(x.dtype)
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.filters,))
            y = y + b.astype(y.dtype)
        return self.activation(y)


class Conv2DTranspose(Module):
    """Transposed conv (reference: Deconvolution2D), NHWC."""

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "same", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "he_normal",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.upper()
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        w = scope.param("kernel", self.kernel_init,
                        (kh, kw, x.shape[-1], self.filters))
        # exact keras Conv2DTranspose semantics (gradient-of-conv); lax's
        # own conv_transpose distributes SAME padding differently
        from .layers_zoo import _deconv
        y = _deconv(x, w, self.strides, self.padding,
                    ("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.filters,))
            y = y + b.astype(y.dtype)
        return self.activation(y)


class DepthwiseConv2D(Module):
    """Per-channel conv (reference: the depthwise half of
    SeparableConvolution2D); feature_group_count = in_channels maps straight
    onto the XLA grouped-conv path."""

    def __init__(self, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "same", depth_multiplier: int = 1,
                 use_bias: bool = True, kernel_init: Any = "he_normal",
                 activation: Any = None, name: Optional[str] = None):
        super().__init__(name)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.upper()
        self.depth_multiplier = depth_multiplier
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)
        self.activation = activations.get(activation)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        ch = x.shape[-1]
        out_ch = ch * self.depth_multiplier
        w = scope.param("kernel", self.kernel_init, (kh, kw, 1, out_ch))
        y = jax.lax.conv_general_dilated(
            x, w.astype(x.dtype), window_strides=self.strides,
            padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=ch).astype(x.dtype)
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"), (out_ch,))
            y = y + b.astype(y.dtype)
        return self.activation(y)


class SeparableConv2D(Module):
    """Depthwise + pointwise (reference: SeparableConvolution2D)."""

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "same", depth_multiplier: int = 1,
                 activation: Any = None, use_bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.depthwise = DepthwiseConv2D(kernel_size, strides, padding,
                                         depth_multiplier, use_bias=False)
        self.pointwise = Conv2D(filters, 1, 1, "same", activation, use_bias)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        h = scope.child(self.depthwise, x, name="depthwise")
        return scope.child(self.pointwise, h, name="pointwise")


class LocallyConnected1D(Module):
    """Unshared-weights 1-D conv (reference: LocallyConnected1D): one
    kernel per output position, expressed as a single batched einsum so
    the MXU sees one big contraction instead of a position loop."""

    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 activation: Any = None, use_bias: bool = True,
                 kernel_init: Any = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = kernel_size
        self.strides = strides
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        b, t, c = x.shape
        out_t = (t - self.kernel_size) // self.strides + 1
        # windows [B, out_t, k*c] via gather of a static index grid
        starts = jnp.arange(out_t) * self.strides
        idx = starts[:, None] + jnp.arange(self.kernel_size)[None, :]
        win = x[:, idx]                           # [B, out_t, k, C]
        win = win.reshape(b, out_t, self.kernel_size * c)
        w = scope.param("kernel", self.kernel_init,
                        (out_t, self.kernel_size * c, self.filters))
        y = jnp.einsum("btk,tkf->btf", win, w.astype(win.dtype))
        if self.use_bias:
            bias = scope.param("bias", initializers.get("zeros"),
                               (out_t, self.filters))
            y = y + bias.astype(y.dtype)
        return self.activation(y)


# -- pooling variants ----------------------------------------------------------

class _Pool1D(Module):
    kind = "max"

    def __init__(self, pool_size: int = 2, strides: Optional[int] = None,
                 padding: str = "valid", name: Optional[str] = None):
        super().__init__(name)
        from .layers import MaxPooling2D, AveragePooling2D
        cls = MaxPooling2D if self.kind == "max" else AveragePooling2D
        self.pool = cls((1, pool_size),
                        (1, strides if strides is not None else pool_size),
                        padding)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return scope.child(self.pool, x[:, None], name="pool")[:, 0]


class MaxPooling1D(_Pool1D):
    kind = "max"


class AveragePooling1D(_Pool1D):
    kind = "avg"


class _Pool3D(Module):
    kind = "max"

    def __init__(self, pool_size: Union[int, Sequence[int]] = 2,
                 strides: Optional[Union[int, Sequence[int]]] = None,
                 padding: str = "valid", name: Optional[str] = None):
        super().__init__(name)
        self.pool_size = _triple(pool_size)
        self.strides = (_triple(strides) if strides is not None
                        else self.pool_size)
        self.padding = padding.upper()

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        dims = (1,) + self.pool_size + (1,)
        strd = (1,) + self.strides + (1,)
        if self.kind == "max":
            return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                         strd, self.padding)
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd,
                                  self.padding)
        ones = jnp.ones_like(x[..., :1])
        cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strd,
                                    self.padding)
        return s / cnt


class MaxPooling3D(_Pool3D):
    kind = "max"


class AveragePooling3D(_Pool3D):
    kind = "avg"


class GlobalAveragePooling3D(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.mean(axis=(1, 2, 3))


class GlobalMaxPooling3D(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.max(axis=(1, 2, 3))


# -- resizing / padding / cropping ---------------------------------------------

class UpSampling1D(Module):
    def __init__(self, size: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.size = size

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.repeat(x, self.size, axis=1)


class UpSampling2D(Module):
    def __init__(self, size: Union[int, Sequence[int]] = 2,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = _pair(size)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        y = jnp.repeat(x, self.size[0], axis=1)
        return jnp.repeat(y, self.size[1], axis=2)


class UpSampling3D(Module):
    def __init__(self, size: Union[int, Sequence[int]] = 2,
                 name: Optional[str] = None):
        super().__init__(name)
        self.size = _triple(size)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        y = jnp.repeat(x, self.size[0], axis=1)
        y = jnp.repeat(y, self.size[1], axis=2)
        return jnp.repeat(y, self.size[2], axis=3)


class ZeroPadding1D(Module):
    def __init__(self, padding: Union[int, Sequence[int]] = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        p = (padding, padding) if isinstance(padding, int) else tuple(padding)
        self.padding = p

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.pad(x, ((0, 0), self.padding, (0, 0)))


class ZeroPadding3D(Module):
    def __init__(self, padding: Union[int, Sequence[int]] = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.padding = _triple(padding)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        pd, ph, pw = self.padding
        return jnp.pad(x, ((0, 0), (pd, pd), (ph, ph), (pw, pw), (0, 0)))


class Cropping1D(Module):
    def __init__(self, cropping: Union[int, Sequence[int]] = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        c = ((cropping, cropping) if isinstance(cropping, int)
             else tuple(cropping))
        self.cropping = c

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        a, b = self.cropping
        return x[:, a:x.shape[1] - b]


class Cropping3D(Module):
    def __init__(self, cropping: Union[int, Sequence[Any]] = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cropping = _norm_cropping(cropping, 3)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        (d0, d1), (h0, h1), (w0, w1) = self.cropping
        return x[:, d0:x.shape[1] - d1, h0:x.shape[2] - h1,
                 w0:x.shape[3] - w1]


class Cropping2D(Module):
    def __init__(self, cropping: Union[int, Sequence[Any]] = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cropping = _norm_cropping(cropping, 2)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        (t, b), (l, r) = self.cropping
        return x[:, t:x.shape[1] - b, l:x.shape[2] - r]


# -- shape / sequence utilities ------------------------------------------------

class RepeatVector(Module):
    """[B, D] → [B, n, D] (reference: RepeatVector)."""

    def __init__(self, n: int, name: Optional[str] = None):
        super().__init__(name)
        self.n = n

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.repeat(x[:, None, :], self.n, axis=1)


class Permute(Module):
    """Permute non-batch dims, 1-indexed like Keras (reference: Permute)."""

    def __init__(self, dims: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.dims = tuple(dims)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.transpose(x, (0,) + tuple(d for d in self.dims))


class Masking(Module):
    """Zero out timesteps equal to mask_value (reference: Masking; the
    downstream consumer sees zeros — explicit mask tensors travel
    separately in this framework)."""

    def __init__(self, mask_value: float = 0.0, name: Optional[str] = None):
        super().__init__(name)
        self.mask_value = mask_value

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        keep = jnp.any(x != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, x, 0.0)


# -- stochastic regularization -------------------------------------------------

class SpatialDropout1D(Module):
    """Drop whole channels (reference: SpatialDropout1D)."""

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = float(rate)

    def _mask_shape(self, x: jax.Array) -> Tuple[int, ...]:
        return (x.shape[0], 1, x.shape[-1])

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if not scope.training or self.rate <= 0.0:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(scope.make_rng(), keep,
                                    self._mask_shape(x))
        return jnp.where(mask, x / keep, 0.0)


class SpatialDropout2D(SpatialDropout1D):
    def _mask_shape(self, x: jax.Array) -> Tuple[int, ...]:
        return (x.shape[0], 1, 1, x.shape[-1])


class SpatialDropout3D(SpatialDropout1D):
    def _mask_shape(self, x: jax.Array) -> Tuple[int, ...]:
        return (x.shape[0], 1, 1, 1, x.shape[-1])


class GaussianNoise(Module):
    """Additive zero-mean noise at train time (reference: GaussianNoise)."""

    def __init__(self, stddev: float, name: Optional[str] = None):
        super().__init__(name)
        self.stddev = float(stddev)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if not scope.training or self.stddev <= 0.0:
            return x
        return x + self.stddev * jax.random.normal(scope.make_rng(),
                                                   x.shape, x.dtype)


class GaussianDropout(Module):
    """Multiplicative 1-mean noise (reference: GaussianDropout)."""

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = float(rate)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if not scope.training or self.rate <= 0.0:
            return x
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(scope.make_rng(), x.shape,
                                              x.dtype)
        return x * noise


# -- parametric activations ----------------------------------------------------

class LeakyReLU(Module):
    def __init__(self, alpha: float = 0.3, name: Optional[str] = None):
        super().__init__(name)
        self.alpha = alpha

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jax.nn.leaky_relu(x, self.alpha)


class ELU(Module):
    def __init__(self, alpha: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.alpha = alpha

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jax.nn.elu(x, self.alpha)


class ThresholdedReLU(Module):
    def __init__(self, theta: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.theta = theta

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.where(x > self.theta, x, 0.0)


class SReLU(Module):
    """S-shaped ReLU with four learnable params per channel (reference:
    BigDL/keras-1 SReLU): piecewise-linear with learned thresholds/slopes
    at both tails."""

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        c = (x.shape[-1],)
        zeros = initializers.get("zeros")
        ones = initializers.get("ones")
        tl = scope.param("t_left", zeros, c).astype(x.dtype)
        al = scope.param("a_left", zeros, c).astype(x.dtype)
        tr = scope.param("t_right", ones, c).astype(x.dtype)
        ar = scope.param("a_right", ones, c).astype(x.dtype)
        below = tl + al * (x - tl)
        above = tr + ar * (x - tr)
        mid = x
        return jnp.where(x < tl, below, jnp.where(x > tr, above, mid))


# -- BigDL tensor-op layers (reference: zoo keras layers wrapping BigDL
#    Select/Narrow/Squeeze/Permute-style tensor utilities) -------------------

class Select(Module):
    """Pick index ``index`` along ``dim`` (reference: BigDL Select)."""

    def __init__(self, dim: int, index: int, name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim
        self.index = index

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        size = x.shape[self.dim]
        if not -size <= self.index < size:
            # fail fast: jnp.take's default OOB mode fills NaN silently
            raise ValueError(
                f"Select index {self.index} out of range for dim "
                f"{self.dim} of size {size}")
        return jnp.take(x, self.index, axis=self.dim)


class Narrow(Module):
    """Slice ``length`` elements from ``offset`` along ``dim``;
    ``length=-1`` means "to the end" (reference: BigDL Narrow)."""

    def __init__(self, dim: int, offset: int, length: int = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim
        self.offset = offset
        self.length = length

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        stop = (x.shape[self.dim] if self.length == -1
                else self.offset + self.length)
        return jax.lax.slice_in_dim(x, self.offset, stop, axis=self.dim)


class Squeeze(Module):
    """Drop size-1 dims; the batch dim (axis 0) is never squeezed —
    a batch of one must stay a batch (reference: BigDL Squeeze, which
    operated on per-sample tensors without a batch axis)."""

    def __init__(self, dim: Optional[Union[int, Sequence[int]]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if self.dim is not None:
            return jnp.squeeze(x, axis=self.dim)
        axes = tuple(i for i in range(1, x.ndim) if x.shape[i] == 1)
        return jnp.squeeze(x, axis=axes) if axes else x


class PReLU(Module):
    """Learnable leaky slope, shared over all but the channel dim
    (reference: PReLU)."""

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        alpha = scope.param("alpha", initializers.get("zeros"),
                            (x.shape[-1],))
        a = alpha.astype(x.dtype)
        return jnp.where(x >= 0, x, a * x)


# -- merge layers --------------------------------------------------------------

class Average(Module):
    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        return sum(xs) / len(xs)


class Maximum(Module):
    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        out = xs[0]
        for x in xs[1:]:
            out = jnp.maximum(out, x)
        return out


class Minimum(Module):
    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        out = xs[0]
        for x in xs[1:]:
            out = jnp.minimum(out, x)
        return out


class Subtract(Module):
    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        if len(xs) != 2:
            raise ValueError("Subtract takes exactly 2 inputs")
        return xs[0] - xs[1]


class Dot(Module):
    """Batched dot over given axes (reference: keras-1 merge mode='dot' /
    batch_dot): contract a's axis i with b's axis j, dim 0 stays the shared
    batch dim, remaining dims concatenate (a's first, then b's)."""

    def __init__(self, axes: Union[int, Sequence[int]] = -1,
                 normalize: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.axes = (axes, axes) if isinstance(axes, int) else tuple(axes)
        self.normalize = normalize

    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        a, b = xs
        ia = self.axes[0] % a.ndim
        ib = self.axes[1] % b.ndim
        if ia == 0 or ib == 0:
            raise ValueError("Dot cannot contract the batch dim (axis 0)")
        if self.normalize:
            a = a / (jnp.linalg.norm(a, axis=ia, keepdims=True) + 1e-12)
            b = b / (jnp.linalg.norm(b, axis=ib, keepdims=True) + 1e-12)
        # einsum: batch letter shared, one contraction letter, the rest pass
        letters = "abcdefghijklmnopqrstuvwxy"
        sub_a = ["z"] + [letters[i - 1] for i in range(1, a.ndim)]
        sub_b = ["z"] + [letters[a.ndim - 1 + i - 1]
                         for i in range(1, b.ndim)]
        sub_a[ia] = "K"
        sub_b[ib] = "K"
        out = [c for c in sub_a[1:] if c != "K"] + \
              [c for c in sub_b[1:] if c != "K"]
        spec = f"z{''.join(sub_a[1:])},z{''.join(sub_b[1:])}->z" \
               f"{''.join(out)}"
        return jnp.einsum(spec, a, b)


# -- BigDL/zoo extras ----------------------------------------------------------

class Highway(Module):
    """y = T(x) * H(x) + (1 - T(x)) * x (reference: keras-1 Highway, also a
    BigDL extra)."""

    def __init__(self, activation: Any = "relu",
                 name: Optional[str] = None):
        super().__init__(name)
        self.activation = activations.get(activation)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        wh = scope.param("kernel", initializers.get("glorot_uniform"),
                         (d, d))
        bh = scope.param("bias", initializers.get("zeros"), (d,))
        wt = scope.param("gate_kernel", initializers.get("glorot_uniform"),
                         (d, d))
        # negative gate bias: start mostly carry, the standard highway init
        bt = scope.param("gate_bias",
                         lambda key, shape, dtype=jnp.float32:
                         jnp.full(shape, -1.0, dtype), (d,))
        h = self.activation(x @ wh.astype(x.dtype) + bh.astype(x.dtype))
        t = jax.nn.sigmoid(x @ wt.astype(x.dtype) + bt.astype(x.dtype))
        return t * h + (1.0 - t) * x


class Remat(Module):
    """Gradient checkpointing wrapper: recompute the wrapped module's
    forward during backward instead of storing its activations
    (jax.checkpoint).  The TPU memory/FLOPs trade for long-sequence or
    deep models — HBM is the usual bottleneck (SURVEY.md §7); the
    reference had no analog because BigDL kept all activations.

    ``Remat(TransformerLayer(8))`` drops the block's activation footprint
    to its inputs + outputs at ~1.3x compute."""

    def __init__(self, inner: Module, name: Optional[str] = None):
        super().__init__(name or (inner.name and f"remat_{inner.name}"))
        self.inner = inner

    def forward(self, scope: Scope, x: jax.Array, **kwargs: Any) -> jax.Array:
        name = self.inner.name or "inner"
        if scope.init_mode:
            return scope.child(self.inner, x, name=name, **kwargs)
        import zlib as _zlib
        params = scope.params.get(name, {})
        state_in = scope.state.get(name, {})
        rng = (jax.random.fold_in(scope.rng,
                                  _zlib.crc32(name.encode()))
               if scope.rng is not None else None)
        training = scope.training
        inner = self.inner

        def fn(p, xv):
            out, new_state = inner.apply({"params": p, "state": state_in},
                                         xv, training=training, rng=rng,
                                         **kwargs)
            return out, new_state

        out, new_state = jax.checkpoint(fn)(params, x)
        if new_state or state_in:
            scope.state[name] = new_state
        return out


class MaxoutDense(Module):
    """max over k linear pieces (reference: keras-1 MaxoutDense / BigDL
    Maxout)."""

    def __init__(self, units: int, nb_feature: int = 4,
                 use_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self.units = units
        self.nb_feature = nb_feature
        self.use_bias = use_bias

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        w = scope.param("kernel", initializers.get("glorot_uniform"),
                        (self.nb_feature, x.shape[-1], self.units))
        y = jnp.einsum("bd,kdu->bku", x, w.astype(x.dtype))
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.nb_feature, self.units))
            y = y + b.astype(y.dtype)
        return y.max(axis=1)
