"""Attention layers: MultiHeadAttention + Transformer encoder block.

Reference (SURVEY.md §2.3, §5.7): the Scala Keras zoo's TransformerLayer/BERT
self-attention layers (zoo/.../pipeline/api/keras/layers/ self-attention
area), replicated per-worker with seq≤512 on CPU.

TPU-native: batched einsum attention that XLA fuses onto the MXU, with an
optional fused-kernel hook — ``analytics_zoo_tpu.ops.flash_attention``
(Pallas) is used when available for long sequences, and ring attention over a
``seq`` mesh axis lives in ``analytics_zoo_tpu.parallel.ring_attention``
(capability the reference lacked; SURVEY.md §5.7 'post-parity stretch').
"""

from __future__ import annotations

from typing import Any, Optional, Union

import jax
import jax.numpy as jnp

from . import initializers
from .layers import Dense, Dropout, LayerNormalization
from .module import Module, Scope


# use_flash="auto" switches to the Pallas flash kernel at this kv length.
# Measured crossover (BERT-base, v5e, fixed global batch, ms/step best):
#   seq  512: dense+remat  99.9 vs flash 124.6  -> dense wins
#   seq 1024: dense+remat  67.1 vs flash  82.0  -> dense wins
#   seq 2048: dense+remat 314.5 vs flash 201.6  -> flash 1.56x
#   seq 4096: dense+remat 764.6 vs flash 377.0  -> flash 2.03x
# Below ~2k the kernel's blocked-backward overhead exceeds the saved
# T x T traffic; above it, not materializing the maps dominates.
FLASH_AUTO_MIN_SEQ = 2048


def causal_mask(tq: int, tk: Optional[int] = None) -> jax.Array:
    """[1, 1, Tq, Tk] lower-triangular attend-mask (shared by the dense path
    and ring_attention's no-seq-axis fallback); handles Tq != Tk
    (cross-attention) by comparing absolute positions."""
    tk = tq if tk is None else tk
    return (jnp.arange(tq)[:, None] >= jnp.arange(tk)[None, :])[None, None]


def dot_product_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          mask: Optional[jax.Array] = None,
                          ) -> jax.Array:
    """Plain attention: q,k,v [B, T, H, D] → [B, T, H, D].

    mask: broadcastable to [B, H, Tq, Tk]; 1 = attend, 0 = masked.
    """
    d = q.shape[-1]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits / jnp.sqrt(jnp.asarray(d, logits.dtype))
    if mask is not None:
        logits = jnp.where(mask.astype(bool), logits, -1e30)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


class MultiHeadAttention(Module):
    def __init__(self, num_heads: int, head_dim: Optional[int] = None,
                 dropout: float = 0.0,
                 use_flash: Union[bool, str] = False,
                 use_ring: bool = False, causal: bool = False,
                 remat: bool = False, dtype: Optional[Any] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        if use_flash not in (True, False, "auto"):
            raise ValueError(
                f"use_flash must be True, False, or 'auto'; got "
                f"{use_flash!r}")
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.dropout = dropout
        self.use_flash = use_flash
        self.use_ring = use_ring  # sequence-parallel ring attention (seq axis)
        self.causal = causal
        # remat: rematerialize the attention core (logits/softmax) in the
        # backward pass instead of saving residuals — trades ~2*T^2*d
        # recompute FLOPs per head for the T x T probability maps' HBM
        # round-trip.  Measured on BERT-base seq 512 / micro-batch 8:
        # 110.0 -> 99.9 ms/step, 53.5% -> 58.9% MFU — without the fixed
        # overhead that made the Pallas flash kernel a net LOSS there
        # (124.6 ms); XLA was materializing per-layer probability maps
        # for the backward.  Exact: same math, recomputed.
        self.remat = remat
        # use_flash: True | False | "auto" — "auto" picks the flash
        # kernel when the kv length reaches FLASH_AUTO_MIN_SEQ (the
        # measured crossover) and there is no explicit mask; below it,
        # the dense path (+ remat if set) wins.  Same math either way.
        if remat and (use_flash is True or use_ring):
            # the flash/ring kernels already avoid materializing the
            # T x T maps — remat would silently be a no-op there; make
            # the conflicting config an error, not a wrong measurement.
            # ("auto" composes: remat applies when auto picks dense.)
            raise ValueError(
                "remat=True applies to the dense attention path only; "
                "use_flash/use_ring kernels already rematerialize — "
                "pick one (use_flash='auto' composes with remat)")
        self.dtype = dtype

    def forward(self, scope: Scope, x: jax.Array,
                kv: Optional[jax.Array] = None,
                mask: Optional[jax.Array] = None) -> jax.Array:
        kv = x if kv is None else kv
        d_model = x.shape[-1]
        h = self.num_heads
        d_head = self.head_dim or d_model // h
        init = initializers.get("glorot_uniform")

        def proj(name: str, src: jax.Array) -> jax.Array:
            w = scope.param(name, init, (src.shape[-1], h * d_head))
            # same-dtype dot: an f32-preferred output downcast right after
            # would make both vjp matmuls mixed f32 x bf16 (see Dense)
            y = jnp.dot(src, w.astype(src.dtype))
            return y.reshape(src.shape[:-1] + (h, d_head))

        q = proj("wq", x)
        k = proj("wk", kv)
        v = proj("wv", kv)

        use_flash = self.use_flash
        if use_flash == "auto":
            use_flash = (mask is None
                         and kv.shape[1] >= FLASH_AUTO_MIN_SEQ)
        if self.use_ring and mask is None:
            from analytics_zoo_tpu.parallel import ring_self_attention
            ctx = ring_self_attention(q, k, v, causal=self.causal)
        elif use_flash and mask is None:
            from analytics_zoo_tpu.ops import flash_attention
            ctx = flash_attention(q, k, v, causal=self.causal)
        else:
            # explicit mask: dense path (flash/ring kernels take no mask);
            # causal still applies — combine, never silently drop it
            if self.causal:
                cm = causal_mask(x.shape[1], kv.shape[1])
                mask = cm if mask is None else (mask.astype(bool) & cm)
            attn = (jax.checkpoint(dot_product_attention) if self.remat
                    else dot_product_attention)
            ctx = attn(q, k, v, mask)

        wo = scope.param("wo", init, (h * d_head, d_model))
        out = jnp.dot(ctx.reshape(x.shape[:-1] + (h * d_head,)),
                      wo.astype(x.dtype))
        return scope.child(Dropout(self.dropout), out, name="drop")


class TransformerLayer(Module):
    """Pre/post-LN transformer encoder block (reference: keras/layers
    TransformerLayer)."""

    def __init__(self, num_heads: int, hidden_mult: int = 4,
                 dropout: float = 0.0, pre_ln: bool = False,
                 use_flash: Union[bool, str] = False,
                 use_ring: bool = False,
                 causal: bool = False, remat_attention: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.mha = MultiHeadAttention(num_heads, dropout=dropout,
                                      use_flash=use_flash, use_ring=use_ring,
                                      causal=causal, remat=remat_attention)
        self.hidden_mult = hidden_mult
        self.dropout = dropout
        self.pre_ln = pre_ln

    def forward(self, scope: Scope, x: jax.Array,
                mask: Optional[jax.Array] = None) -> jax.Array:
        d_model = x.shape[-1]
        ln1 = LayerNormalization(name="ln1")
        ln2 = LayerNormalization(name="ln2")
        ffn1 = Dense(d_model * self.hidden_mult, activation="gelu", name="ffn1")
        ffn2 = Dense(d_model, name="ffn2")
        drop = Dropout(self.dropout, name="drop")

        if self.pre_ln:
            a = scope.child(self.mha, scope.child(ln1, x, name="ln1"),
                            mask=mask, name="mha")
            x = x + scope.child(drop, a, name="drop1")
            f = scope.child(ln2, x, name="ln2")
            f = scope.child(ffn2, scope.child(ffn1, f, name="ffn1"),
                            name="ffn2")
            return x + scope.child(drop, f, name="drop2")
        a = scope.child(self.mha, x, mask=mask, name="mha")
        x = scope.child(ln1, x + scope.child(drop, a, name="drop1"),
                        name="ln1")
        f = scope.child(ffn2, scope.child(ffn1, x, name="ffn1"), name="ffn2")
        return scope.child(ln2, x + scope.child(drop, f, name="drop2"),
                           name="ln2")
