"""Weight initializers (reference: BigDL InitializationMethod zoo exposed
through the Keras layers' ``init=`` argument)."""

from __future__ import annotations

from typing import Callable, Union

import jax

INITIALIZERS = {
    "glorot_uniform": jax.nn.initializers.glorot_uniform(),
    "glorot_normal": jax.nn.initializers.glorot_normal(),
    "he_uniform": jax.nn.initializers.he_uniform(),
    "he_normal": jax.nn.initializers.he_normal(),
    "lecun_uniform": jax.nn.initializers.lecun_uniform(),
    "lecun_normal": jax.nn.initializers.lecun_normal(),
    "zeros": jax.nn.initializers.zeros,
    "ones": jax.nn.initializers.ones,
    "uniform": jax.nn.initializers.uniform(0.05),
    "normal": jax.nn.initializers.normal(0.05),
    "orthogonal": jax.nn.initializers.orthogonal(),
}


def get(init: Union[str, Callable]) -> Callable:
    if callable(init):
        return init
    try:
        return INITIALIZERS[init]
    except KeyError:
        raise ValueError(f"unknown initializer {init!r}; known: "
                         f"{sorted(INITIALIZERS)}") from None
