"""Functional Model API: ``Input`` → layer calls → ``Model(inputs, outputs)``.

Reference (SURVEY.md §2.3): the Keras-1.2 graph API —
``zoo/.../pipeline/api/keras/models/Topology.scala`` ``Model`` and its py4j
mirror ``pyzoo/zoo/pipeline/api/keras/models.py`` — was the reference's
primary model-building surface: multi-input/multi-output DAGs
(``Model([input1, input2], output)``), layer reuse (shared embeddings),
KNRM/W&D-style two-tower graphs.

TPU-native: calling a layer on a ``SymbolicTensor`` records a graph node
instead of computing; ``Model`` topologically executes the recorded DAG
inside one scope, so the whole graph jit-compiles like any Module.  A
layer object called twice becomes ONE parameter subtree executed twice —
weight sharing by object identity, the Keras semantic.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from .module import Module, Scope, _snake


class _Node:
    """One recorded layer application."""

    def __init__(self, layer: Optional[Module], args: Tuple[Any, ...],
                 kwargs: Dict[str, Any]):
        self.layer = layer          # None for Input placeholders
        self.args = args            # may contain SymbolicTensors (nested)
        self.kwargs = kwargs
        self.name: Optional[str] = None  # assigned by Model


class SymbolicTensor:
    """Placeholder flowing through layer calls at graph-build time.  A
    layer returning a tuple stores it whole — split components with a
    ``Lambda(lambda t: t[i])`` node."""

    def __init__(self, node: _Node,
                 shape: Optional[Tuple[int, ...]] = None,
                 dtype: Any = None):
        self.node = node
        self.shape = shape
        self.dtype = dtype

    # arithmetic sugar: x + y etc. become Lambda nodes
    def _binop(self, other: Any, fn, name: str) -> "SymbolicTensor":
        from .layers import Lambda
        lam = Lambda(fn, name=name)
        return lam(self, other) if isinstance(other, SymbolicTensor) \
            else lam(self)

    def __add__(self, other):
        if isinstance(other, SymbolicTensor):
            return self._binop(other, lambda a, b: a + b, "add")
        return self._binop(other, lambda a, o=other: a + o, "add_const")

    def __sub__(self, other):
        if isinstance(other, SymbolicTensor):
            return self._binop(other, lambda a, b: a - b, "sub")
        return self._binop(other, lambda a, o=other: a - o, "sub_const")

    def __mul__(self, other):
        if isinstance(other, SymbolicTensor):
            return self._binop(other, lambda a, b: a * b, "mul")
        return self._binop(other, lambda a, o=other: a * o, "mul_const")

    # constant-on-the-left forms (1.0 + x, 2 * h, 1 - gate)
    def __radd__(self, other):
        return self._binop(other, lambda a, o=other: o + a, "radd_const")

    def __rsub__(self, other):
        return self._binop(other, lambda a, o=other: o - a, "rsub_const")

    def __rmul__(self, other):
        return self._binop(other, lambda a, o=other: o * a, "rmul_const")


def Input(shape: Sequence[int], dtype: Any = jnp.float32,
          name: Optional[str] = None) -> SymbolicTensor:
    """A graph input placeholder; ``shape`` excludes the batch dim
    (reference: keras Input)."""
    node = _Node(None, (), {"name": name})
    return SymbolicTensor(node, tuple(shape), dtype)


def _contains_symbolic(x: Any) -> bool:
    if isinstance(x, SymbolicTensor):
        return True
    if isinstance(x, (list, tuple)):
        return any(_contains_symbolic(v) for v in x)
    if isinstance(x, dict):
        return any(_contains_symbolic(v) for v in x.values())
    return False


def _map_symbolic(x: Any, fn) -> Any:
    if isinstance(x, SymbolicTensor):
        return fn(x)
    if isinstance(x, (list, tuple)):
        return type(x)(_map_symbolic(v, fn) for v in x)
    if isinstance(x, dict):
        return {k: _map_symbolic(v, fn) for k, v in x.items()}
    return x


def symbolic_call(layer: Module, *args: Any, **kwargs: Any
                  ) -> SymbolicTensor:
    """Record ``layer(*args)`` as a graph node (invoked by
    ``Module.__call__`` when any arg is symbolic)."""
    return SymbolicTensor(_Node(layer, args, kwargs))


class Model(Module):
    """Execute a recorded DAG (reference: keras Model graph topology).

    ``inputs``: SymbolicTensor or list; ``outputs``: SymbolicTensor or
    list.  ``forward`` takes the concrete arrays in ``inputs`` order (a
    single list/tuple argument also works) and returns the outputs
    (tuple when several)."""

    def __init__(self, inputs: Any, outputs: Any,
                 name: Optional[str] = None):
        super().__init__(name)
        self.inputs: List[SymbolicTensor] = (
            list(inputs) if isinstance(inputs, (list, tuple)) else [inputs])
        self.outputs: List[SymbolicTensor] = (
            list(outputs) if isinstance(outputs, (list, tuple))
            else [outputs])
        self._order = self._toposort()
        self._assign_names()

    def _toposort(self) -> List[_Node]:
        order: List[_Node] = []
        seen: set = set()
        input_nodes = {id(s.node) for s in self.inputs}

        def visit(node: _Node, stack: set) -> None:
            if id(node) in seen:
                return
            if id(node) in stack:
                raise ValueError("cycle in model graph")
            if id(node) not in input_nodes:
                if node.layer is None:
                    raise ValueError(
                        "graph references an Input that is not in "
                        "Model(inputs=...)")
                stack = stack | {id(node)}
                for sym in self._deps(node):
                    visit(sym.node, stack)
            seen.add(id(node))
            order.append(node)

        for out in self.outputs:
            visit(out.node, set())
        return order

    @staticmethod
    def _deps(node: _Node) -> List[SymbolicTensor]:
        deps: List[SymbolicTensor] = []
        _map_symbolic((node.args, node.kwargs), deps.append)
        return deps

    def _assign_names(self) -> None:
        # one name per LAYER OBJECT: calling a layer twice shares weights
        by_layer: Dict[int, str] = {}
        counts: Dict[str, int] = {}
        for node in self._order:
            if node.layer is None:
                continue
            key = id(node.layer)
            if key not in by_layer:
                base = node.layer.name or _snake(type(node.layer).__name__)
                idx = counts.get(base, 0)
                counts[base] = idx + 1
                by_layer[key] = base if idx == 0 else f"{base}_{idx}"
            node.name = by_layer[key]

    def forward(self, scope: Scope, *xs: Any, **kwargs: Any) -> Any:
        if len(xs) == 1 and isinstance(xs[0], (list, tuple)) \
                and len(self.inputs) > 1:
            xs = tuple(xs[0])
        if len(xs) != len(self.inputs):
            raise ValueError(
                f"model takes {len(self.inputs)} inputs, got {len(xs)}")
        values: Dict[int, Any] = {}
        for sym, x in zip(self.inputs, xs):
            values[id(sym.node)] = x

        def resolve(sym: SymbolicTensor) -> Any:
            return values[id(sym.node)]

        for node in self._order:
            if node.layer is None or id(node) in values:
                continue  # input placeholder / already computed
            args = _map_symbolic(node.args, resolve)
            kw = _map_symbolic(node.kwargs, resolve)
            values[id(node)] = scope.child(node.layer, *args,
                                           name=node.name, **kw)
        outs = tuple(resolve(s) for s in self.outputs)
        return outs[0] if len(outs) == 1 else outs
