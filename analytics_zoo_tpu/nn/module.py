"""Minimal functional module system: the base of the Keras-style layer API.

Reference (SURVEY.md §2.3): the Keras-1.2-style API was ~25k LoC of Scala
layers over BigDL's imperative module graph (zoo/src/main/scala/com/intel/
analytics/zoo/pipeline/api/keras/) plus 10k LoC of py4j mirrors
(pyzoo/zoo/pipeline/api/keras/).  Layers held mutable weights; training
mutated them in place inside the JVM.

TPU-native redesign: layers are *pure functions* of an explicit variables
pytree, the form XLA wants — ``init`` builds {"params", "state"} by tracing
the layer once over example inputs; ``apply`` is referentially transparent
(jit/grad/vmap/shard_map compose over it).  A small ``Scope`` object threads
parameter creation, RNG splitting, and BatchNorm-style mutable state through
nested submodules, so layer code reads like Keras but compiles like JAX.

No flax dependency: the whole mechanism is this file.
"""

from __future__ import annotations

import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]


class Scope:
    """Threads variable access through one ``init`` or ``apply`` trace."""

    def __init__(self, params: Params, state: Params, rng: Optional[jax.Array],
                 training: bool, init_mode: bool, path: Tuple[str, ...] = (),
                 taps: Optional[Dict[str, Any]] = None,
                 quant: Optional[Any] = None):
        self.params = params
        self.state = state
        self.rng = rng
        self.training = training
        self.init_mode = init_mode
        self.path = path
        self.taps = taps  # shared dict: child outputs recorded by path
        self.quant = quant  # int8 serving context (nn.quant), or None
        self._rng_count = 0
        self._child_counts: Dict[str, int] = {}
        # name → module object.  The object itself (not id()) is kept so the
        # identity check can't false-positive when CPython reuses a freed
        # module's address for a new one.
        self._child_seen: Dict[str, "Module"] = {}
        self._reuse = False  # re-executing a shared layer: params exist

    # -- variables ------------------------------------------------------------

    def param(self, name: str, initializer: Callable, shape: Sequence[int],
              dtype: Any = jnp.float32) -> jax.Array:
        if self.init_mode:
            if name in self.params:
                if self._reuse:  # shared layer re-executed: same weights
                    return self.params[name]
                raise ValueError(f"duplicate param {name!r} at {self.path}")
            self.params[name] = initializer(self.make_rng(), tuple(shape), dtype)
        if name not in self.params:
            raise KeyError(f"missing param {name!r} at {'/'.join(self.path)}")
        return self.params[name]

    def variable(self, name: str, init_fn: Callable[[], jax.Array]) -> jax.Array:
        """Non-trainable state (e.g. BatchNorm running stats)."""
        if self.init_mode and name not in self.state:
            self.state[name] = init_fn()
        return self.state[name]

    def put_variable(self, name: str, value: jax.Array) -> None:
        """Record a state update (visible in the new_state returned by apply).
        No-op during init: init captures initial values, not updates."""
        if not self.init_mode:
            self.state[name] = value

    # -- rng ------------------------------------------------------------------

    def make_rng(self) -> jax.Array:
        if self.rng is None:
            raise ValueError(
                f"layer at {'/'.join(self.path)} needs an rng (pass rng= to "
                "init/apply, required for dropout in training mode)")
        self._rng_count += 1
        return jax.random.fold_in(self.rng, self._rng_count)

    # -- submodules -----------------------------------------------------------

    def child(self, module: "Module", *args: Any, name: Optional[str] = None,
              **kwargs: Any) -> Any:
        """Run a submodule under a nested scope."""
        if name is None:
            base = module.name or _snake(type(module).__name__)
            idx = self._child_counts.get(base, 0)
            self._child_counts[base] = idx + 1
            name = base if idx == 0 else f"{base}_{idx}"
        sub_params = self.params.setdefault(name, {}) if self.init_mode else \
            self.params.get(name, {})
        sub_state_in = self.state.get(name, {})
        sub_state = dict(sub_state_in) if not self.init_mode else \
            self.state.setdefault(name, {})
        # zlib.crc32 (not hash()): stable across processes so every SPMD host
        # derives identical init RNGs for identically-named layers.
        sub = Scope(sub_params, sub_state,
                    jax.random.fold_in(self.rng, zlib.crc32(name.encode()))
                    if self.rng is not None else None,
                    self.training, self.init_mode, self.path + (name,),
                    taps=self.taps, quant=self.quant)
        # weight sharing: re-executing the SAME layer object under the same
        # name (a shared layer in a functional graph) reuses its params; a
        # DIFFERENT module under an already-used name is a naming bug and
        # keeps the duplicate-param guard
        prev = self._child_seen.get(name)
        if prev is not None and prev is not module and self.init_mode \
                and not self._reuse:
            raise ValueError(
                f"two different modules share the child name {name!r} at "
                f"{'/'.join(self.path) or '<root>'}; give them distinct "
                "names (weight sharing requires the same layer object)")
        sub._reuse = self._reuse or prev is module
        self._child_seen[name] = module
        out = module.forward(sub, *args, **kwargs)
        if not self.init_mode and (sub.state or sub_state_in):
            self.state[name] = sub.state
        if self.taps is not None:
            key = base_key = "/".join(self.path + (name,))
            i = 1
            while key in self.taps:  # shared layer: one tap per application
                key = f"{base_key}#{i}"
                i += 1
            self.taps[key] = out
        return out


class Module:
    """Base class for all layers.  Subclasses implement ``forward(scope, ...)``."""

    def __init__(self, name: Optional[str] = None):
        self.name = name

    def forward(self, scope: Scope, *args: Any, **kwargs: Any) -> Any:
        raise NotImplementedError

    # -- public API -----------------------------------------------------------

    def init(self, rng: jax.Array, *args: Any, training: bool = False,
             **kwargs: Any) -> Params:
        """Trace once over example inputs; returns {"params", "state"}."""
        args = tuple(_as_jax(a) for a in args)
        scope = Scope({}, {}, rng, training, init_mode=True)
        self.forward(scope, *args, **kwargs)
        return {"params": scope.params, "state": scope.state}

    def apply(self, variables: Params, *args: Any, training: bool = False,
              rng: Optional[jax.Array] = None, quant: Optional[Any] = None,
              **kwargs: Any) -> Tuple[Any, Params]:
        """Pure application: returns (output, new_state).  ``quant``: an
        nn.quant context for int8 serving (calibration or apply mode)."""
        state_in = variables.get("state", {})
        scope = Scope(variables.get("params", {}), dict(state_in), rng,
                      training, init_mode=False, quant=quant)
        out = self.forward(scope, *args, **kwargs)
        return out, scope.state

    def apply_with_taps(self, variables: Params, *args: Any,
                        training: bool = False,
                        rng: Optional[jax.Array] = None, **kwargs: Any
                        ) -> Tuple[Any, Params, Dict[str, Any]]:
        """Like ``apply`` but also returns every submodule's output keyed by
        its scope path ("block0/mha", ...) — the functional analog of the
        reference's GraphNet intermediate-output surgery
        (zoo/.../pipeline/api/net/GraphNet.scala ``newGraph``)."""
        state_in = variables.get("state", {})
        taps: Dict[str, Any] = {}
        scope = Scope(variables.get("params", {}), dict(state_in), rng,
                      training, init_mode=False, taps=taps)
        out = self.forward(scope, *args, **kwargs)
        return out, scope.state, taps

    def __call__(self, scope_or_vars: Any, *args: Any, **kwargs: Any) -> Any:
        """Inside another module's forward: ``layer(scope, x)`` delegates via
        the parent scope (auto-named child).  On SymbolicTensors: records a
        functional-graph node (nn.functional).  Outside: alias for apply."""
        if isinstance(scope_or_vars, Scope):  # the hot path: no import
            return scope_or_vars.child(self, *args, **kwargs)
        # a symbolic arg can only be the input itself or a (nested) list of
        # inputs — never inside a variables dict, so dicts are not walked
        from .functional import _contains_symbolic, symbolic_call
        maybe = (scope_or_vars,) + args
        if any(not isinstance(m, dict) and _contains_symbolic(m)
               for m in maybe):
            return symbolic_call(self, scope_or_vars, *args, **kwargs)
        return self.apply(scope_or_vars, *args, **kwargs)

    # convenience
    def init_apply(self, rng: jax.Array, *args: Any, **kwargs: Any
                   ) -> Tuple[Params, Any]:
        variables = self.init(rng, *args, **kwargs)
        out, _ = self.apply(variables, *args, **kwargs)
        return variables, out

    def summary(self, variables: Params, *args: Any,
                print_fn: Optional[Callable[[str], None]] = print,
                **kwargs: Any) -> str:
        """Keras-style layer table: path, output shape, param count
        (reference: KerasNet.summary — Topology.scala).  Shapes come from
        an abstract trace (jax.eval_shape) — no compute, no activation
        memory."""
        exec_order: List[str] = []

        def traced(v, *a):
            out, state, taps = self.apply_with_taps(v, *a, **kwargs)
            # pytree round-trips sort dict keys; execution order must be
            # captured as a trace side effect (the trace runs exactly once)
            exec_order.extend(taps.keys())
            return out, state, taps

        _, _, taps = jax.eval_shape(traced, variables, *args)

        def count(tree: Any) -> int:
            return sum(int(np.prod(l.shape)) for l in
                       jax.tree_util.tree_leaves(tree)
                       if hasattr(l, "shape"))

        def shape_of(out: Any) -> str:
            leaves = [l for l in jax.tree_util.tree_leaves(out)
                      if hasattr(l, "shape")]
            if not leaves:
                return "-"
            s = ", ".join(str(tuple(l.shape)) for l in leaves[:3])
            return s + (", ..." if len(leaves) > 3 else "")

        params = variables.get("params", {})
        rows = [("layer (path)", "output shape", "params")]
        for path in exec_order:
            # param counts are reported on top-level rows only (nested rows
            # would double-count their parent's subtree)
            top_level = "/" not in path and "#" not in path
            sub = params.get(path, {}) if top_level else None
            rows.append((path, shape_of(taps[path]),
                         str(count(sub)) if sub is not None else ""))
        total = count(params)
        widths = [max(len(r[i]) for r in rows) for i in range(3)]
        lines = ["  ".join(c.ljust(w) for c, w in zip(r, widths))
                 for r in rows]
        lines.insert(1, "-" * (sum(widths) + 4))
        lines.append("-" * (sum(widths) + 4))
        lines.append(f"total params: {total:,}")
        text = "\n".join(lines)
        if print_fn:
            print_fn(text)
        return text


def _snake(s: str) -> str:
    out = []
    for i, c in enumerate(s):
        if c.isupper() and i and (not s[i - 1].isupper()):
            out.append("_")
        out.append(c.lower())
    return "".join(out)


def _as_jax(a: Any) -> Any:
    if isinstance(a, (np.ndarray, np.generic, float, int)):
        return jnp.asarray(a)
    return a


def param_count(variables: Params) -> int:
    leaves = jax.tree_util.tree_leaves(variables.get("params", variables))
    return sum(int(np.prod(l.shape)) for l in leaves if hasattr(l, "shape"))
