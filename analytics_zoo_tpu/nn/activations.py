"""Activation registry (reference: Keras-zoo activation layers,
zoo/.../pipeline/api/keras/layers/ activation classes)."""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

ACTIVATIONS = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softmax": jax.nn.softmax,
    "log_softmax": jax.nn.log_softmax,
    "softplus": jax.nn.softplus,
    "elu": jax.nn.elu,
    "leaky_relu": jax.nn.leaky_relu,
    "hard_sigmoid": jax.nn.hard_sigmoid,
    "linear": lambda x: x,
    None: lambda x: x,
}


def get(act: Union[str, Callable, None]) -> Callable:
    if callable(act):
        return act
    try:
        return ACTIVATIONS[act]
    except KeyError:
        raise ValueError(
            f"unknown activation {act!r}; known: "
            f"{sorted(k for k in ACTIVATIONS if k)}") from None
