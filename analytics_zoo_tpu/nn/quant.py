"""Static int8 activation quantization for serving.

Reference (SURVEY.md §2.8): the OpenVINO path ran INT8 inference with
activation scales derived from an offline CALIBRATION pass
(``OpenVinoInferenceSupportive`` model-optimizer INT8 calibration).  The
TPU-native analog: a quant context threaded through the module ``Scope``
— a calibration pass records each participating layer's input absolute
maximum (static, per-tensor), then serving-time layers quantize
activations with those frozen scales and run the contraction as
int8 x int8 -> int32 on the MXU, rescaling per output channel.

Participating layers: ``nn.Dense`` (the transformer/recommender serving
hot path) and plain ``nn.Conv2D`` (the CNN serving path — the reference's
OpenVINO INT8 calibrated whole CNNs; int8 x int8 -> int32
``conv_general_dilated`` is exact on the v5e MXU, probe-verified).
``ScaledWSConv2D`` and other kernel-transforming subclasses stay
weight-only (their weight math needs the float kernel).
``InferenceModel.load(dtype="int8", calibrate=batch)`` wires it up.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


class Calibrator:
    """Collect mode: observe per-layer activation ranges on a (concrete,
    unjitted) calibration forward; layers still compute in float."""

    def __init__(self):
        self.amax: Dict[str, float] = {}

    mode = "collect"

    def observe(self, path: Tuple[str, ...], x: jax.Array) -> None:
        key = "/".join(path)
        # concreteness check, not a tracer-type check: tracers subclass
        # jax.Array and jax.core.Tracer is deprecated as a public name,
        # so the durable test is whether the value converts to a host
        # float — a tracer raises a concretization error here on ANY
        # jax version
        try:
            val = float(jnp.max(jnp.abs(x.astype(jnp.float32))))
        except jax.errors.ConcretizationTypeError:
            raise RuntimeError(
                "int8 calibration must run UNJITTED: the Calibrator reads "
                "concrete activation ranges back to the host, which is "
                "impossible under jit/scan/vmap tracing (layer "
                f"{key} saw a tracer). Run the calibration "
                "forward outside jax.jit — InferenceModel.load("
                "calibrate=batch) does this for you.") from None
        self.amax[key] = max(self.amax.get(key, 0.0), val)


class QuantApply:
    """Apply mode: frozen per-tensor activation scales (baked into the
    jitted executable as constants) + per-channel int8 weights."""

    mode = "apply"

    def __init__(self, amax: Dict[str, float], compute_dtype=jnp.bfloat16):
        self.amax = dict(amax)
        self.compute_dtype = compute_dtype

    def scale_for(self, path: Tuple[str, ...]) -> Optional[float]:
        a = self.amax.get("/".join(path))
        if a is None or a <= 0.0:
            return None
        return a / 127.0


def _quantize_activation(ctx, path, x):
    """Shared preamble of the int8 paths: the frozen static scale (or
    None for the float fallback) and the symmetrically quantized input
    (zero-point 0, so "SAME" zero-padding stays exact)."""
    s_in = ctx.scale_for(path)
    if s_in is None:
        return None, None  # layer never seen in calibration
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) * (1.0 / s_in)),
                  -127, 127).astype(jnp.int8)
    return s_in, xq


def _rescale(y32, w_scale, s_in, compute_dtype):
    """Shared postamble: one fused (s_in * s_w[channel]) rescale."""
    scale = jnp.asarray(w_scale, jnp.float32).reshape(-1) * s_in
    return (y32.astype(jnp.float32) * scale).astype(compute_dtype)


def conv_quantized(ctx, path, x, wq, w_scale, strides, padding, dilation,
                   groups, compute_dtype):
    """int8 convolution with a static activation scale: q(x) conv wq ->
    int32 on the MXU, then one fused per-output-channel rescale."""
    s_in, xq = _quantize_activation(ctx, path, x)
    if s_in is None:
        return None
    y32 = jax.lax.conv_general_dilated(
        xq, wq, window_strides=strides, padding=padding,
        rhs_dilation=dilation,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups,
        preferred_element_type=jnp.int32)
    return _rescale(y32, w_scale, s_in, compute_dtype)


def dense_quantized(ctx, path, x, wq, w_scale, compute_dtype):
    """int8 GEMM with static activation scale: q(x) @ wq -> int32, then
    one fused per-output-channel rescale."""
    s_in, xq = _quantize_activation(ctx, path, x)
    if s_in is None:
        return None
    y32 = jax.lax.dot_general(
        xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return _rescale(y32, w_scale, s_in, compute_dtype)
