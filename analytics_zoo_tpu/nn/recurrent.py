"""Recurrent layers: LSTM/GRU/SimpleRNN + Bidirectional/TimeDistributed.

Reference (SURVEY.md §2.3): keras/layers recurrent classes in the Scala zoo
(LSTM, GRU, SimpleRNN, Bidirectional, TimeDistributed) executed step-by-step
on BigDL's CPU engine.  TPU-native: the time loop is a single ``lax.scan`` —
compiled control flow, no Python loop, weights fetched once; the gate matmuls
are fused into one [F, 4U] product per step (MXU-friendly).
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from . import activations, initializers
from .module import Module, Scope


class _RNNBase(Module):
    def __init__(self, units: int, return_sequences: bool = False,
                 return_state: bool = False, go_backwards: bool = False,
                 kernel_init: Any = "glorot_uniform",
                 recurrent_init: Any = "orthogonal",
                 name: Optional[str] = None):
        super().__init__(name)
        self.units = units
        self.return_sequences = return_sequences
        self.return_state = return_state
        self.go_backwards = go_backwards
        self.kernel_init = initializers.get(kernel_init)
        self.recurrent_init = initializers.get(recurrent_init)

    n_gates = 1

    def _weights(self, scope: Scope, in_dim: int):
        u, g = self.units, self.n_gates
        wi = scope.param("kernel", self.kernel_init, (in_dim, g * u))
        wh = scope.param("recurrent_kernel", self.recurrent_init, (u, g * u))
        b = scope.param("bias", initializers.get("zeros"), (g * u,))
        return wi, wh, b

    def _init_carry(self, batch: int) -> Any:
        raise NotImplementedError

    def _step(self, weights, carry, x_t):
        raise NotImplementedError

    def forward(self, scope: Scope, x: jax.Array):
        weights = self._weights(scope, x.shape[-1])
        carry0 = self._init_carry(x.shape[0])
        xs = jnp.swapaxes(x, 0, 1)  # [T, B, F] for scan
        if self.go_backwards:
            xs = xs[::-1]

        def step(carry, x_t):
            carry, out = self._step(weights, carry, x_t)
            return carry, out

        carry, outs = jax.lax.scan(step, carry0, xs)
        # final output = last *scan* step (for go_backwards that is the end
        # of the backward pass, NOT the last input-time frame)
        last = outs[-1]
        if self.go_backwards:
            outs = outs[::-1]
        seq = jnp.swapaxes(outs, 0, 1)  # [B, T, U]
        out = seq if self.return_sequences else last
        if self.return_state:
            return out, carry
        return out


class LSTM(_RNNBase):
    n_gates = 4

    def _init_carry(self, batch: int):
        z = jnp.zeros((batch, self.units))
        return (z, z)  # (h, c)

    def _step(self, weights, carry, x_t):
        wi, wh, b = weights
        h, c = carry
        z = x_t @ wi + h @ wh + b
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f + 1.0), jax.nn.sigmoid(o)
        c = f * c + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return (h, c), h


class GRU(_RNNBase):
    n_gates = 3

    def _init_carry(self, batch: int):
        return jnp.zeros((batch, self.units))

    def _step(self, weights, carry, x_t):
        wi, wh, b = weights
        h = carry
        xz = x_t @ wi + b
        hz = h @ wh
        u = self.units
        r = jax.nn.sigmoid(xz[:, :u] + hz[:, :u])
        z = jax.nn.sigmoid(xz[:, u:2 * u] + hz[:, u:2 * u])
        n = jnp.tanh(xz[:, 2 * u:] + r * hz[:, 2 * u:])
        h = (1 - z) * n + z * h
        return h, h


class SimpleRNN(_RNNBase):
    n_gates = 1

    def _init_carry(self, batch: int):
        return jnp.zeros((batch, self.units))

    def _step(self, weights, carry, x_t):
        wi, wh, b = weights
        h = jnp.tanh(x_t @ wi + carry @ wh + b)
        return h, h


class Bidirectional(Module):
    """Run a recurrent layer forward and backward, merge outputs
    (reference: keras/layers Bidirectional; merge modes concat/sum/mul/ave)."""

    def __init__(self, layer: _RNNBase, merge_mode: str = "concat",
                 name: Optional[str] = None):
        super().__init__(name)
        import copy
        self.fwd = layer
        self.bwd = copy.copy(layer)
        self.bwd.go_backwards = not layer.go_backwards
        self.merge_mode = merge_mode

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        yf = scope.child(self.fwd, x, name="forward")
        yb = scope.child(self.bwd, x, name="backward")
        if self.merge_mode == "concat":
            return jnp.concatenate([yf, yb], axis=-1)
        if self.merge_mode == "sum":
            return yf + yb
        if self.merge_mode == "mul":
            return yf * yb
        if self.merge_mode == "ave":
            return (yf + yb) / 2
        raise ValueError(f"unknown merge_mode {self.merge_mode!r}")


class TimeDistributed(Module):
    """Apply a layer independently to every timestep via vmap
    (reference: keras/layers TimeDistributed — a Python loop there; one
    batched trace here)."""

    def __init__(self, layer: Module, name: Optional[str] = None):
        super().__init__(name)
        self.layer = layer

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        b, t = x.shape[:2]
        flat = x.reshape((b * t,) + x.shape[2:])
        y = scope.child(self.layer, flat, name="inner")
        return y.reshape((b, t) + y.shape[1:])
