"""Metrics (reference: BigDL ValidationMethods wrapped by Orca metrics,
pyzoo/zoo/orca/learn/metrics.py — Accuracy, Top5Accuracy, Loss, MAE, MSE, AUC).

Design: a metric is a pair of pure functions so it jit-compiles inside the
eval step and aggregates exactly across sharded batches:

- ``update(y_pred, y_true, mask=None) -> stats``: per-batch sufficient
  statistics (e.g. (correct_count, total)); summed across batches/devices
  by the estimator (a psum when sharded).  ``mask`` [batch] weights each
  example (0.0 = padding row) so the estimator can evaluate a padded final
  batch exactly — required for static shapes under jit.
- ``result(stats) -> float``: final value from summed statistics.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple, Union

import jax
import jax.numpy as jnp


def _ones_mask(y_pred: jax.Array, mask) -> jax.Array:
    if mask is None:
        return jnp.ones((y_pred.shape[0],), jnp.float32)
    return mask.astype(jnp.float32)


class Metric:
    name: str = "metric"

    def update(self, y_pred: jax.Array, y_true: jax.Array,
               mask: jax.Array = None) -> jax.Array:
        raise NotImplementedError

    def result(self, stats: jax.Array) -> jax.Array:
        raise NotImplementedError


class Accuracy(Metric):
    """argmax accuracy for class outputs; threshold accuracy for 1-d sigmoid
    outputs (reference: BigDL Top1Accuracy semantics)."""

    name = "accuracy"

    def update(self, y_pred, y_true, mask=None):
        m = _ones_mask(y_pred, mask)
        if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            true = (jnp.argmax(y_true, axis=-1)
                    if y_true.ndim == y_pred.ndim else y_true)
        else:
            pred = (y_pred.reshape(y_pred.shape[0], -1)[:, 0] > 0).astype(
                jnp.int32)
            true = y_true.reshape(y_true.shape[0], -1)[:, 0]
        hit = (pred.astype(jnp.int32) == true.astype(jnp.int32))
        # multi-position outputs (e.g. [B, T] token predictions) score each
        # example by its fraction of correct positions
        hit = hit.reshape(hit.shape[0], -1).mean(axis=-1,
                                                 dtype=jnp.float32)
        return jnp.stack([(hit * m).sum(), m.sum()])

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class TopKAccuracy(Metric):
    def __init__(self, k: int = 5):
        self.k = k
        self.name = f"top{k}_accuracy"

    def update(self, y_pred, y_true, mask=None):
        m = _ones_mask(y_pred, mask)
        _, topk = jax.lax.top_k(y_pred, self.k)
        true = (jnp.argmax(y_true, axis=-1)
                if y_true.ndim == y_pred.ndim else y_true)
        hit = (topk == true[..., None].astype(topk.dtype)).any(-1)
        hit = hit.reshape(hit.shape[0], -1).mean(axis=-1,
                                                 dtype=jnp.float32)
        return jnp.stack([(hit * m).sum(), m.sum()])

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class _ElementwiseError(Metric):
    def _err(self, y_pred, y_true):
        raise NotImplementedError

    def update(self, y_pred, y_true, mask=None):
        m = _ones_mask(y_pred, mask)
        if y_true.shape != y_pred.shape and y_true.size == y_pred.size:
            # [B] labels vs [B, 1] outputs: align rather than broadcast to
            # a [B, B] cross matrix
            y_true = y_true.reshape(y_pred.shape)
        per_elem = self._err(y_pred, y_true).reshape(y_pred.shape[0], -1)
        per_row = per_elem.sum(axis=-1)
        elems_per_row = per_elem.shape[-1]
        return jnp.stack([(per_row * m).sum().astype(jnp.float32),
                          m.sum() * elems_per_row])


class MeanAbsoluteError(_ElementwiseError):
    name = "mae"

    def _err(self, y_pred, y_true):
        return jnp.abs(y_pred - y_true)

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class MeanSquaredError(_ElementwiseError):
    name = "mse"

    def _err(self, y_pred, y_true):
        return jnp.square(y_pred - y_true)

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class BinaryAUC(Metric):
    """Streaming AUC via fixed-bin score histograms (jit-friendly; the
    reference used BigDL's AUC ValidationMethod with threshold bins too)."""

    name = "auc"

    def __init__(self, num_bins: int = 200):
        self.num_bins = num_bins

    def update(self, y_pred, y_true, mask=None):
        m = _ones_mask(y_pred, mask)
        # per-example weight broadcast over any extra output dims
        w = jnp.broadcast_to(m.reshape(-1, *([1] * (y_pred.ndim - 1))),
                             y_pred.shape).reshape(-1)
        p = jax.nn.sigmoid(y_pred.reshape(-1))  # y_pred is logits, like losses
        p = jnp.clip(p, 0.0, 1.0 - 1e-7)
        t = y_true.reshape(-1).astype(jnp.float32)
        bins = jnp.floor(p * self.num_bins).astype(jnp.int32)
        pos = jnp.zeros(self.num_bins).at[bins].add(t * w)
        neg = jnp.zeros(self.num_bins).at[bins].add((1.0 - t) * w)
        return jnp.stack([pos, neg])

    def result(self, stats):
        pos, neg = stats[0], stats[1]
        # sweep thresholds high→low: trapezoidal AUC over the ROC curve
        tp = jnp.cumsum(pos[::-1])
        fp = jnp.cumsum(neg[::-1])
        tpr = tp / jnp.maximum(tp[-1], 1.0)
        fpr = fp / jnp.maximum(fp[-1], 1.0)
        tpr = jnp.concatenate([jnp.zeros(1), tpr])
        fpr = jnp.concatenate([jnp.zeros(1), fpr])
        return jnp.trapezoid(tpr, fpr)


METRICS: Dict[str, Callable[[], Metric]] = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "top5": lambda: TopKAccuracy(5),
    "top5_accuracy": lambda: TopKAccuracy(5),
    "mae": MeanAbsoluteError,
    "mse": MeanSquaredError,
    "auc": BinaryAUC,
}


def get(metric: Union[str, Metric]) -> Metric:
    if isinstance(metric, Metric):
        return metric
    try:
        return METRICS[metric]()
    except KeyError:
        raise ValueError(
            f"unknown metric {metric!r}; known: {sorted(METRICS)}") from None
