"""Metrics (reference: BigDL ValidationMethods wrapped by Orca metrics,
pyzoo/zoo/orca/learn/metrics.py — Accuracy, Top5Accuracy, Loss, MAE, MSE, AUC).

Design: a metric is a pair of pure functions so it jit-compiles inside the
eval step and aggregates exactly across sharded batches:

- ``update(y_pred, y_true) -> stats``: per-batch sufficient statistics
  (e.g. (correct_count, total)); summed across batches/devices by the
  estimator (a psum when sharded).
- ``result(stats) -> float``: final value from summed statistics.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple, Union

import jax
import jax.numpy as jnp


class Metric:
    name: str = "metric"

    def update(self, y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
        raise NotImplementedError

    def result(self, stats: jax.Array) -> jax.Array:
        raise NotImplementedError


class Accuracy(Metric):
    """argmax accuracy for class outputs; threshold accuracy for 1-d sigmoid
    outputs (reference: BigDL Top1Accuracy semantics)."""

    name = "accuracy"

    def update(self, y_pred, y_true):
        if y_pred.ndim > 1 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            true = (jnp.argmax(y_true, axis=-1)
                    if y_true.ndim == y_pred.ndim else y_true)
        else:
            pred = (y_pred.reshape(y_pred.shape[0], -1)[:, 0] > 0).astype(
                jnp.int32)
            true = y_true.reshape(y_true.shape[0], -1)[:, 0]
        correct = (pred.astype(jnp.int32) == true.astype(jnp.int32)).sum()
        total = jnp.asarray(pred.shape[0], jnp.int32)
        return jnp.stack([correct.astype(jnp.float32),
                          total.astype(jnp.float32)])

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class TopKAccuracy(Metric):
    def __init__(self, k: int = 5):
        self.k = k
        self.name = f"top{k}_accuracy"

    def update(self, y_pred, y_true):
        _, topk = jax.lax.top_k(y_pred, self.k)
        true = (jnp.argmax(y_true, axis=-1)
                if y_true.ndim == y_pred.ndim else y_true)
        correct = (topk == true[..., None].astype(topk.dtype)).any(-1).sum()
        return jnp.stack([correct.astype(jnp.float32),
                          jnp.asarray(y_pred.shape[0], jnp.float32)])

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class MeanAbsoluteError(Metric):
    name = "mae"

    def update(self, y_pred, y_true):
        err = jnp.abs(y_pred - y_true).sum()
        return jnp.stack([err.astype(jnp.float32),
                          jnp.asarray(y_pred.size, jnp.float32)])

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class MeanSquaredError(Metric):
    name = "mse"

    def update(self, y_pred, y_true):
        err = jnp.square(y_pred - y_true).sum()
        return jnp.stack([err.astype(jnp.float32),
                          jnp.asarray(y_pred.size, jnp.float32)])

    def result(self, stats):
        return stats[0] / jnp.maximum(stats[1], 1.0)


class BinaryAUC(Metric):
    """Streaming AUC via fixed-bin score histograms (jit-friendly; the
    reference used BigDL's AUC ValidationMethod with threshold bins too)."""

    name = "auc"

    def __init__(self, num_bins: int = 200):
        self.num_bins = num_bins

    def update(self, y_pred, y_true):
        p = jax.nn.sigmoid(y_pred.reshape(-1))  # y_pred is logits, like losses
        p = jnp.clip(p, 0.0, 1.0 - 1e-7)
        t = y_true.reshape(-1).astype(jnp.float32)
        bins = jnp.floor(p * self.num_bins).astype(jnp.int32)
        pos = jnp.zeros(self.num_bins).at[bins].add(t)
        neg = jnp.zeros(self.num_bins).at[bins].add(1.0 - t)
        return jnp.stack([pos, neg])

    def result(self, stats):
        pos, neg = stats[0], stats[1]
        # sweep thresholds high→low: trapezoidal AUC over the ROC curve
        tp = jnp.cumsum(pos[::-1])
        fp = jnp.cumsum(neg[::-1])
        tpr = tp / jnp.maximum(tp[-1], 1.0)
        fpr = fp / jnp.maximum(fp[-1], 1.0)
        tpr = jnp.concatenate([jnp.zeros(1), tpr])
        fpr = jnp.concatenate([jnp.zeros(1), fpr])
        return jnp.trapezoid(tpr, fpr)


METRICS: Dict[str, Callable[[], Metric]] = {
    "accuracy": Accuracy,
    "acc": Accuracy,
    "top5": lambda: TopKAccuracy(5),
    "top5_accuracy": lambda: TopKAccuracy(5),
    "mae": MeanAbsoluteError,
    "mse": MeanSquaredError,
    "auc": BinaryAUC,
}


def get(metric: Union[str, Metric]) -> Metric:
    if isinstance(metric, Metric):
        return metric
    try:
        return METRICS[metric]()
    except KeyError:
        raise ValueError(
            f"unknown metric {metric!r}; known: {sorted(METRICS)}") from None
