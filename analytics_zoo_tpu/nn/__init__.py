"""Keras-style NN layer API on a minimal JAX module system (reference L5)."""

from . import activations, initializers, losses, metrics
from .attention import (MultiHeadAttention, TransformerLayer,
                        dot_product_attention)
from .layers import (Activation, Add, AveragePooling2D, BatchNormalization,
                     Concatenate, Conv1D, Conv2D, Dense, Dropout, Embedding,
                     Flatten, GlobalAveragePooling1D, GlobalAveragePooling2D,
                     GlobalMaxPooling1D, GlobalMaxPooling2D, Lambda,
                     LayerNormalization, MaxPooling2D, Multiply, Reshape,
                     ScaledWSConv2D, Sequential, ZeroPadding2D)
from .layers_extra import (AveragePooling1D, AveragePooling3D, Average,
                           Conv2DTranspose, Conv3D, Cropping1D, Cropping2D,
                           Cropping3D, DepthwiseConv2D, Dot, ELU,
                           GaussianDropout,
                           GaussianNoise, GlobalAveragePooling3D,
                           GlobalMaxPooling3D, Highway, LeakyReLU,
                           LocallyConnected1D, Masking, MaxoutDense,
                           MaxPooling1D, MaxPooling3D, Maximum, Minimum,
                           Narrow, Permute, PReLU, Remat, RepeatVector,
                           Select, SeparableConv2D, SReLU, Squeeze,
                           SpatialDropout1D, SpatialDropout2D,
                           SpatialDropout3D, Subtract, ThresholdedReLU,
                           UpSampling1D, UpSampling2D, UpSampling3D,
                           ZeroPadding1D, ZeroPadding3D)
from .layers_zoo import (ActivityRegularization, AddConstant, AlphaDropout,
                         CAdd, CMul, Conv1DTranspose, Conv3DTranspose,
                         ConvLSTM2D, ConvLSTM3D, Cos, Exp, GaussianSampler,
                         HardShrink, HardTanh, Identity, LocallyConnected2D,
                         Log, LRN2D, MulConstant, Negative, Power,
                         ResizeBilinear, Scale, SeparableConv1D, Softmax,
                         SoftShrink, Sqrt, Square, Threshold, WordEmbedding,
                         Merge, merge)
from .functional import Input, Model, SymbolicTensor
from .module import Module, Scope, param_count
from .recurrent import (GRU, LSTM, Bidirectional, SimpleRNN, TimeDistributed)

# keras-1 naming aliases (reference: zoo keras-1.2 class names) so ported
# scripts keep their spellings
Convolution1D = Conv1D
Convolution2D = Conv2D
Convolution3D = Conv3D
Deconvolution2D = Conv2DTranspose
Deconvolution3D = Conv3DTranspose
AtrousConvolution1D = Conv1D   # dilation= covers the atrous variants
AtrousConvolution2D = Conv2D
# BigDL ShareConvolution was a memory-sharing twin of SpatialConvolution;
# functionally identical, and XLA owns buffer reuse here
ShareConvolution2D = Conv2D
SeparableConvolution2D = SeparableConv2D
# zoo's Sparse* layers existed for sparse-gradient CPU training; XLA's
# scatter/gather handles the same access pattern on dense TPU arrays
SparseEmbedding = Embedding
SparseDense = Dense

__all__ = [
    "activations", "initializers", "losses", "metrics",
    "Module", "Scope", "param_count",
    "Dense", "Embedding", "Dropout", "Flatten", "Reshape", "Activation",
    "Lambda", "Conv1D", "Conv2D", "MaxPooling2D", "AveragePooling2D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D", "GlobalAveragePooling1D",
    "GlobalMaxPooling1D", "ZeroPadding2D", "BatchNormalization",
    "LayerNormalization", "Concatenate", "Add", "Multiply", "Sequential",
    "LSTM", "GRU", "SimpleRNN", "Bidirectional", "TimeDistributed",
    "MultiHeadAttention", "TransformerLayer", "dot_product_attention",
    # extended Keras-1.2 zoo (layers_extra)
    "Conv3D", "Conv2DTranspose", "DepthwiseConv2D", "SeparableConv2D",
    "LocallyConnected1D", "MaxPooling1D", "AveragePooling1D",
    "MaxPooling3D", "AveragePooling3D", "GlobalAveragePooling3D",
    "GlobalMaxPooling3D", "UpSampling1D", "UpSampling2D", "UpSampling3D",
    "ZeroPadding1D", "ZeroPadding3D", "Cropping1D", "Cropping2D",
    "RepeatVector", "Permute", "Masking", "SpatialDropout1D",
    "SpatialDropout2D", "SpatialDropout3D", "GaussianNoise",
    "GaussianDropout", "LeakyReLU", "ELU", "ThresholdedReLU", "PReLU",
    "Average", "Maximum", "Minimum", "Subtract", "Dot", "Highway",
    "MaxoutDense",
    # functional graph API
    "Input", "Model", "SymbolicTensor",
    "Remat",
    "Cropping3D", "SReLU", "Select", "Narrow", "Squeeze",
    # layer-zoo backfill (layers_zoo)
    "ConvLSTM2D", "LocallyConnected2D", "Conv3DTranspose", "Conv1DTranspose",
    "SeparableConv1D", "AlphaDropout", "Softmax", "ActivityRegularization",
    "LRN2D", "Cos", "Identity", "Exp", "Log", "Sqrt", "Square", "Power",
    "Negative", "AddConstant", "MulConstant", "Scale", "Threshold",
    "HardShrink", "SoftShrink", "WordEmbedding", "Merge", "merge",
    "ConvLSTM3D", "CAdd", "CMul", "HardTanh", "GaussianSampler",
    "ResizeBilinear",
    # keras-1 naming aliases
    "Convolution1D", "Convolution2D", "Convolution3D", "Deconvolution2D",
    "Deconvolution3D", "AtrousConvolution1D", "AtrousConvolution2D",
    "ShareConvolution2D", "SeparableConvolution2D", "SparseEmbedding",
    "SparseDense",
]
