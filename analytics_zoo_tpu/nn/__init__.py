"""Keras-style NN layer API on a minimal JAX module system (reference L5)."""

from . import activations, initializers, losses, metrics
from .attention import (MultiHeadAttention, TransformerLayer,
                        dot_product_attention)
from .layers import (Activation, Add, AveragePooling2D, BatchNormalization,
                     Concatenate, Conv1D, Conv2D, Dense, Dropout, Embedding,
                     Flatten, GlobalAveragePooling1D, GlobalAveragePooling2D,
                     GlobalMaxPooling1D, GlobalMaxPooling2D, Lambda,
                     LayerNormalization, MaxPooling2D, Multiply, Reshape,
                     Sequential, ZeroPadding2D)
from .module import Module, Scope, param_count
from .recurrent import (GRU, LSTM, Bidirectional, SimpleRNN, TimeDistributed)

__all__ = [
    "activations", "initializers", "losses", "metrics",
    "Module", "Scope", "param_count",
    "Dense", "Embedding", "Dropout", "Flatten", "Reshape", "Activation",
    "Lambda", "Conv1D", "Conv2D", "MaxPooling2D", "AveragePooling2D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D", "GlobalAveragePooling1D",
    "GlobalMaxPooling1D", "ZeroPadding2D", "BatchNormalization",
    "LayerNormalization", "Concatenate", "Add", "Multiply", "Sequential",
    "LSTM", "GRU", "SimpleRNN", "Bidirectional", "TimeDistributed",
    "MultiHeadAttention", "TransformerLayer", "dot_product_attention",
]
