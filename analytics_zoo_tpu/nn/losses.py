"""Loss functions (reference: Keras-zoo objectives,
zoo/.../pipeline/api/keras/objectives/ — SparseCategoricalCrossEntropy,
CategoricalCrossEntropy, BinaryCrossEntropy, MSE/MAE, Hinge, …).

Every loss is ``fn(y_pred, y_true) -> scalar`` (mean over the batch), pure
and jit-safe.  ``get`` resolves Keras-style string names.
"""

from __future__ import annotations

from typing import Callable, Union

import jax
import jax.numpy as jnp


def sparse_categorical_crossentropy(y_pred: jax.Array, y_true: jax.Array,
                                    from_logits: bool = True) -> jax.Array:
    y_true = y_true.astype(jnp.int32)
    if from_logits:
        # mixed-precision recipe: matmuls in bf16, softmax math in f32.
        # logsumexp - gather instead of log_softmax + gather: identical
        # math, but never materializes the full [.., vocab] f32 log-prob
        # array — one HBM round trip saved on large-vocab LM heads
        # (measured ~+1% MFU on the BERT-base bench).
        logits = y_pred.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, y_true[..., None], axis=-1)[..., 0]
        return (lse - tgt).mean()
    logp = jnp.log(jnp.clip(y_pred, 1e-7, 1.0))
    nll = -jnp.take_along_axis(logp, y_true[..., None], axis=-1)[..., 0]
    return nll.mean()


def categorical_crossentropy(y_pred: jax.Array, y_true: jax.Array,
                             from_logits: bool = True) -> jax.Array:
    if from_logits:
        logp = jax.nn.log_softmax(y_pred.astype(jnp.float32), axis=-1)
    else:
        logp = jnp.log(jnp.clip(y_pred, 1e-7, 1.0))
    return -(y_true * logp).sum(axis=-1).mean()


def binary_crossentropy(y_pred: jax.Array, y_true: jax.Array,
                        from_logits: bool = True) -> jax.Array:
    y_true = y_true.astype(y_pred.dtype)
    if from_logits:
        # numerically stable log-sigmoid form
        return jnp.mean(jnp.clip(y_pred, 0) - y_pred * y_true +
                        jnp.log1p(jnp.exp(-jnp.abs(y_pred))))
    p = jnp.clip(y_pred, 1e-7, 1 - 1e-7)
    return -(y_true * jnp.log(p) + (1 - y_true) * jnp.log(1 - p)).mean()


def mean_squared_error(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    return jnp.square(y_pred - y_true).mean()


def mean_absolute_error(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    return jnp.abs(y_pred - y_true).mean()


def huber(y_pred: jax.Array, y_true: jax.Array, delta: float = 1.0
          ) -> jax.Array:
    err = jnp.abs(y_pred - y_true)
    quad = jnp.minimum(err, delta)
    return (0.5 * quad**2 + delta * (err - quad)).mean()


def hinge(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    return jnp.maximum(0.0, 1.0 - y_true * y_pred).mean()


def squared_hinge(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    return jnp.square(jnp.maximum(0.0, 1.0 - y_true * y_pred)).mean()


def mean_absolute_percentage_error(y_pred: jax.Array, y_true: jax.Array
                                   ) -> jax.Array:
    diff = jnp.abs((y_true - y_pred) /
                   jnp.clip(jnp.abs(y_true), 1e-7, None))
    return 100.0 * diff.mean()


def mean_squared_logarithmic_error(y_pred: jax.Array, y_true: jax.Array
                                   ) -> jax.Array:
    a = jnp.log1p(jnp.clip(y_pred, 0.0, None))
    b = jnp.log1p(jnp.clip(y_true, 0.0, None))
    return jnp.square(a - b).mean()


def poisson(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    return (y_pred - y_true * jnp.log(jnp.clip(y_pred, 1e-7, None))).mean()


def kld(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    p = jnp.clip(y_true, 1e-7, 1.0)
    q = jnp.clip(y_pred, 1e-7, 1.0)
    return (p * jnp.log(p / q)).sum(axis=-1).mean()


def cosine_proximity(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    yp = y_pred / (jnp.linalg.norm(y_pred, axis=-1, keepdims=True) + 1e-8)
    yt = y_true / (jnp.linalg.norm(y_true, axis=-1, keepdims=True) + 1e-8)
    return -(yp * yt).sum(axis=-1).mean()


LOSSES = {
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "binary_crossentropy": binary_crossentropy,
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "huber": huber,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "poisson": poisson,
    "kld": kld,
    "cosine_proximity": cosine_proximity,
}


def get(loss: Union[str, Callable]) -> Callable:
    if callable(loss):
        return loss
    try:
        return LOSSES[loss]
    except KeyError:
        raise ValueError(
            f"unknown loss {loss!r}; known: {sorted(LOSSES)}") from None
