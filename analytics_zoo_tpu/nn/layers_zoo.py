"""Keras-1.2 / BigDL layer-zoo backfill: the breadth tail of the reference
layer set (VERDICT r2 missing #3).

Reference (SURVEY.md §2.3): zoo/.../pipeline/api/keras/layers/ plus the
BigDL tensor-op layers its py4j mirrors exposed, and the keras2 namespace
(pyzoo/zoo/pipeline/api/keras2/).  layers.py + layers_extra.py carry the
core ~75; this module adds the remaining commonly-ported classes:
ConvLSTM2D, LocallyConnected2D, transpose-conv variants, separable-1D,
keras-2 extras (AlphaDropout, Softmax), LRN, the "cos" merge mode, and the
BigDL element-op layers (Exp/Log/Power/Scale/...).  All TPU-native: NHWC /
NDHWC layouts, pure functions of variables, lax.scan for recurrence,
jit/shard_map-composable.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from . import activations, initializers
from .layers import _norm_padding, _pair
from .layers_extra import _triple
from .module import Module, Scope


# -- recurrent convolution -----------------------------------------------------

def _hard_sigmoid_k1(x: jax.Array) -> jax.Array:
    """keras-1/BigDL hard_sigmoid: clip(0.2*x + 0.5, 0, 1).  jax.nn's (and
    keras 3's) hard_sigmoid is relu6(x+3)/6 — a different slope."""
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


class _ConvLSTMND(Module):
    """Shared convolutional-LSTM recurrence over [B, T, *spatial, C]
    frames (reference: zoo keras ConvLSTM2D; BigDL ConvLSTM2D/3D): gates
    are convs of the frame and the hidden state, recurrence via lax.scan
    (compiler-friendly: one compiled step body, no Python loop).
    Rank-specific subclasses supply the conv dimension numbers.

    keras-1 defaults: tanh cell activation, hard_sigmoid gates (the
    LEGACY piecewise-linear clip(0.2x + 0.5) — keras 3 redefined
    "hard_sigmoid" as relu6(x+3)/6, which is NOT the reference's), and
    unit forget-gate bias."""

    _rank: int
    _dims: Tuple[str, str, str]

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: Any = "same",
                 activation: Any = "tanh",
                 recurrent_activation: Any = "hard_sigmoid",
                 unit_forget_bias: bool = True,
                 return_sequences: bool = False, go_backwards: bool = False,
                 kernel_init: Any = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        norm = _pair if self._rank == 2 else _triple
        self.filters = filters
        self.kernel_size = norm(kernel_size)
        self.strides = norm(strides)
        self.padding = _norm_padding(padding)
        self.activation = (_hard_sigmoid_k1 if activation == "hard_sigmoid"
                           else activations.get(activation))
        self.recurrent_activation = (
            _hard_sigmoid_k1 if recurrent_activation == "hard_sigmoid"
            else activations.get(recurrent_activation))
        self.unit_forget_bias = unit_forget_bias
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        r = self._rank
        if x.ndim != r + 3:
            spatial_names = "D,H,W" if r == 3 else "H,W"
            raise ValueError(f"{type(self).__name__} wants "
                             f"[B,T,{spatial_names},C], got {x.shape}")
        b, t = x.shape[:2]
        spatial, c = x.shape[2:-1], x.shape[-1]
        f = self.filters
        wx = scope.param("kernel", self.kernel_init,
                         self.kernel_size + (c, 4 * f))
        wh = scope.param("recurrent_kernel", self.kernel_init,
                         self.kernel_size + (f, 4 * f))

        def bias_init(key, shape, dtype=jnp.float32):
            bval = jnp.zeros(shape, dtype)
            if self.unit_forget_bias:  # gate order i,f,g,o
                bval = bval.at[f:2 * f].set(1.0)
            return bval

        bias = scope.param("bias", bias_init, (4 * f,))

        def conv(inp, kern, strides, padding):
            return jax.lax.conv_general_dilated(
                inp, kern, window_strides=strides, padding=padding,
                dimension_numbers=self._dims)

        # spatial grid after the (possibly strided/valid) input conv; the
        # recurrent conv is ALWAYS stride-1 SAME over that grid (keras
        # semantics — it must preserve the hidden-state shape)
        grid = jax.eval_shape(
            lambda a: conv(a, wx, self.strides, self.padding),
            jax.ShapeDtypeStruct((b,) + spatial + (c,), x.dtype)).shape[1:-1]
        ones = (1,) * r

        def step(carry, xt):
            hid, cell = carry
            z = (conv(xt, wx, self.strides, self.padding)
                 + conv(hid, wh, ones, "SAME") + bias)
            i, fg, g, o = jnp.split(z, 4, axis=-1)
            act, rec = self.activation, self.recurrent_activation
            cell = rec(fg) * cell + rec(i) * act(g)
            hid = rec(o) * act(cell)
            return (hid, cell), hid

        seq = jnp.moveaxis(x, 1, 0)  # [T, B, *spatial, C]
        init = (jnp.zeros((b,) + grid + (f,), x.dtype),
                jnp.zeros((b,) + grid + (f,), x.dtype))
        (hid, _), outs = jax.lax.scan(step, init, seq,
                                      reverse=self.go_backwards)
        if self.return_sequences:
            outs = jnp.moveaxis(outs, 0, 1)  # [B, T, *grid, F]
            return outs[:, ::-1] if self.go_backwards else outs
        return hid


class ConvLSTM2D(_ConvLSTMND):
    """Convolutional LSTM over [B, T, H, W, C] NHWC frames."""
    _rank = 2
    _dims = ("NHWC", "HWIO", "NHWC")


# -- unshared convolution ------------------------------------------------------

class LocallyConnected2D(Module):
    """Conv2D with UNSHARED weights per output position (reference:
    LocallyConnected2D).  Patch extraction + per-position einsum — the
    contraction maps onto the MXU as a batched matmul."""

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "valid", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        if isinstance(padding, str) and padding.lower() != "valid":
            raise ValueError(
                "LocallyConnected2D supports padding='valid' only (keras "
                "semantics)")
        self.filters = filters
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        sh, sw = self.strides
        b, h, w, c = x.shape
        oh = (h - kh) // sh + 1
        ow = (w - kw) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            x, (kh, kw), (sh, sw), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))  # [B,OH,OW,C*kh*kw]
        kern = scope.param("kernel", self.kernel_init,
                           (oh, ow, patches.shape[-1], self.filters))
        y = jnp.einsum("bhwk,hwkf->bhwf", patches,
                       kern.astype(patches.dtype))
        if self.use_bias:
            bias = scope.param("bias", initializers.get("zeros"),
                               (oh, ow, self.filters))
            y = y + bias.astype(y.dtype)
        return self.activation(y)


# -- transpose / separable variants -------------------------------------------

def _deconv_pads(k: int, s: int, padding: str) -> Tuple[int, int]:
    """Explicit pad pairs expressing a keras ConvTranspose as a
    fractionally-strided (lhs-dilated) direct conv over a FLIPPED kernel —
    the gradient-of-conv formulation, which is exactly keras/torch
    deconvolution semantics (lax.conv_transpose's own SAME differs)."""
    if padding == "VALID":
        return (k - 1, k - 1)
    pt = max(k - s, 0)  # forward SAME conv total padding
    return (k - 1 - pt // 2, k - 1 - (pt - pt // 2) + max(s - k, 0))


def _deconv(x: jax.Array, w: jax.Array, strides: Sequence[int],
            padding: str, dn: Tuple[str, str, str]) -> jax.Array:
    nd = len(strides)
    flipped = w[(slice(None, None, -1),) * nd]
    pads = [_deconv_pads(w.shape[i], strides[i], padding)
            for i in range(nd)]
    return jax.lax.conv_general_dilated(
        x, flipped.astype(x.dtype), window_strides=(1,) * nd,
        padding=pads, lhs_dilation=tuple(strides),
        dimension_numbers=dn)


class Conv3DTranspose(Module):
    """3-D transposed convolution, NDHWC (reference: Deconvolution3D) —
    exact keras Conv3DTranspose semantics via ``_deconv``."""

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: str = "same", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = _triple(kernel_size)
        self.strides = _triple(strides)
        self.padding = padding.upper()
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        kd, kh, kw = self.kernel_size
        w = scope.param("kernel", self.kernel_init,
                        (kd, kh, kw, x.shape[-1], self.filters))
        y = _deconv(x, w, self.strides, self.padding,
                    ("NDHWC", "DHWIO", "NDHWC"))
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.filters,))
            y = y + b.astype(y.dtype)
        return self.activation(y)


class Conv1DTranspose(Module):
    """1-D transposed convolution, NWC (keras2: Conv1DTranspose)."""

    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "same", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = kernel_size
        self.strides = strides
        self.padding = padding.upper()
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        w = scope.param("kernel", self.kernel_init,
                        (self.kernel_size, x.shape[-1], self.filters))
        y = _deconv(x, w, (self.strides,), self.padding,
                    ("NWC", "WIO", "NWC"))
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.filters,))
            y = y + b.astype(y.dtype)
        return self.activation(y)


class SeparableConv1D(Module):
    """Depthwise-then-pointwise 1-D convolution (keras2: SeparableConv1D)."""

    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "same", depth_multiplier: int = 1,
                 activation: Any = None, use_bias: bool = True,
                 kernel_init: Any = "glorot_uniform",
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = kernel_size
        self.strides = strides
        self.padding = padding.upper()
        self.depth_multiplier = depth_multiplier
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        c = x.shape[-1]
        dw = scope.param("depthwise_kernel", self.kernel_init,
                         (self.kernel_size, 1, c * self.depth_multiplier))
        y = jax.lax.conv_general_dilated(
            x, dw.astype(x.dtype), window_strides=(self.strides,),
            padding=self.padding, dimension_numbers=("NWC", "WIO", "NWC"),
            feature_group_count=c)
        pw = scope.param("pointwise_kernel", self.kernel_init,
                         (1, c * self.depth_multiplier, self.filters))
        y = jax.lax.conv_general_dilated(
            y, pw.astype(y.dtype), window_strides=(1,), padding="VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.filters,))
            y = y + b.astype(y.dtype)
        return self.activation(y)


# -- keras-2 extras ------------------------------------------------------------

class AlphaDropout(Module):
    """SELU-compatible dropout: keeps self-normalizing mean/variance
    (keras2: AlphaDropout; Klambauer et al. 2017)."""

    _ALPHA_P = -1.7580993408473766  # -alpha * lambda of SELU

    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = float(rate)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if not scope.training or self.rate <= 0.0:
            return x
        keep = 1.0 - self.rate
        a = ((keep + self._ALPHA_P ** 2 * keep * (1 - keep)) ** -0.5)
        b = -a * self._ALPHA_P * (1 - keep)
        mask = jax.random.bernoulli(scope.make_rng(), keep, x.shape)
        return a * jnp.where(mask, x, self._ALPHA_P) + b


class Softmax(Module):
    """Softmax as a layer with an axis argument (keras2: Softmax)."""

    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jax.nn.softmax(x, axis=self.axis)


class ActivityRegularization(Module):
    """Identity layer adding an L1/L2 activity penalty to the training
    loss (reference: ActivityRegularization).  The penalty rides the
    framework's aux-loss channel: recorded under ``aux_loss`` in state,
    summed into the loss by the estimator (same mechanism as the MoE
    load-balance loss)."""

    def __init__(self, l1: float = 0.0, l2: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.l1 = float(l1)
        self.l2 = float(l2)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        scope.variable("aux_loss", lambda: jnp.zeros((), jnp.float32))
        pen = (self.l1 * jnp.abs(x).sum()
               + self.l2 * jnp.square(x).sum()).astype(jnp.float32)
        scope.put_variable("aux_loss", pen)
        return x


class WordEmbedding(Module):
    """Pre-trained word embeddings, frozen by default (reference:
    WordEmbedding — zoo keras layers; loaded GloVe txt files for the text
    models).  ``weights``: [vocab, dim] array, or a GloVe-format txt path
    via :meth:`from_glove`.

    Freeze mechanism: a frozen table lives in the STATE collection (like
    BatchNorm running stats), so the optimizer never sees it — gradient
    stopping alone would not survive weight-decay optimizers like adamw,
    whose decoupled decay shrinks parameters even at zero gradient."""

    def __init__(self, weights: Any, trainable: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        import numpy as np
        self.weights = np.asarray(weights, np.float32)
        if self.weights.ndim != 2:
            raise ValueError(
                f"weights must be [vocab, dim], got {self.weights.shape}")
        self.trainable = trainable

    @staticmethod
    def from_glove(path: str, word_index: dict,
                   trainable: bool = False) -> "WordEmbedding":
        """Build from a GloVe-format text file ("word v1 v2 ...": one token
        per line) and a {word: idx} vocabulary (idx 0 = padding).  Words
        missing from the file stay zero.  Malformed lines (multi-token
        words, truncated tails, fastText "count dim" headers) are
        skipped."""
        import numpy as np
        vectors = {}
        dim = None
        with open(path, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 3:  # also skips fastText "count dim" header
                    continue
                try:
                    vec = np.asarray(parts[1:], np.float32)
                except ValueError:
                    continue  # word containing spaces etc.
                if dim is None:
                    dim = len(vec)
                if len(vec) != dim:
                    continue  # truncated/odd line
                vectors[parts[0]] = vec
        if dim is None:
            raise ValueError(f"no vectors found in {path}")
        table = np.zeros((max(word_index.values()) + 1, dim), np.float32)
        for word, idx in word_index.items():
            v = vectors.get(word)
            if v is not None:
                table[idx] = v
        return WordEmbedding(table, trainable=trainable)

    def forward(self, scope: Scope, ids: jax.Array) -> jax.Array:
        if self.trainable:
            table = scope.param(
                "embeddings", lambda rng, shape, dtype:
                jnp.asarray(self.weights, dtype), self.weights.shape)
        else:
            # state, not params: invisible to the optimizer entirely
            table = scope.variable(
                "embeddings", lambda: jnp.asarray(self.weights))
            table = jax.lax.stop_gradient(table)
        return jnp.take(table, ids, axis=0)


# -- normalization -------------------------------------------------------------

class LRN2D(Module):
    """Cross-channel local response normalization, NHWC (reference: the
    AlexNet-era LRN layer BigDL exposed through the keras set)."""

    def __init__(self, alpha: float = 1e-4, k: float = 1.0, beta: float = 0.75,
                 n: int = 5, name: Optional[str] = None):
        super().__init__(name)
        self.alpha, self.k, self.beta, self.n = alpha, k, beta, n

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        half = self.n // 2
        sq = jnp.square(x)
        c = x.shape[-1]
        pad = jnp.pad(sq, [(0, 0)] * (x.ndim - 1) + [(half, half)])
        window = sum(pad[..., i:i + c] for i in range(self.n))
        # caffe/keras-1 LRN divides alpha by the window size
        return x / jnp.power(self.k + (self.alpha / self.n) * window,
                             self.beta)


# -- merge variants ------------------------------------------------------------

class Cos(Module):
    """Cosine-proximity merge over the last axis (reference: keras-1
    ``merge(mode="cos")``); output keeps a trailing singleton axis."""

    def forward(self, scope: Scope, inputs: Sequence[jax.Array]) -> jax.Array:
        a, b = inputs
        num = jnp.sum(a * b, axis=-1, keepdims=True)
        den = (jnp.linalg.norm(a, axis=-1, keepdims=True)
               * jnp.linalg.norm(b, axis=-1, keepdims=True))
        return num / jnp.maximum(den, 1e-12)


# -- BigDL element-op layers ---------------------------------------------------

class Identity(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x


class Exp(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.exp(x)


class Log(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.log(x)


class Sqrt(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.sqrt(x)


class Square(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.square(x)


class Power(Module):
    """x ** power, with optional pre-scale/shift: (a*x + b) ** p (BigDL
    Power semantics)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.power(self.scale * x + self.shift, self.power)


class Negative(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return -x


class AddConstant(Module):
    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x + self.constant


class MulConstant(Module):
    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x * self.constant


class Scale(Module):
    """Learnable per-channel affine: gamma * x + beta over the last axis
    (BigDL Scale / CAddTable+CMulTable idiom)."""

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        dim = x.shape[-1]
        gamma = scope.param("gamma", initializers.get("ones"), (dim,))
        beta = scope.param("beta", initializers.get("zeros"), (dim,))
        return x * gamma.astype(x.dtype) + beta.astype(x.dtype)


class Threshold(Module):
    """x if x > th else value (BigDL Threshold)."""

    def __init__(self, th: float = 1e-6, value: float = 0.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.th, self.value = th, value

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.where(x > self.th, x, jnp.asarray(self.value, x.dtype))


class HardShrink(Module):
    def __init__(self, lam: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.lam = lam

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.where(jnp.abs(x) > self.lam, x, 0.0).astype(x.dtype)


class SoftShrink(Module):
    def __init__(self, lam: float = 0.5, name: Optional[str] = None):
        super().__init__(name)
        self.lam = lam

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return (jnp.sign(x)
                * jnp.maximum(jnp.abs(x) - self.lam, 0.0)).astype(x.dtype)


class CAdd(Module):
    """Trainable bias of an explicit shape, broadcast-added (BigDL CAdd;
    zoo keras-1 exposed it directly)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        b = scope.param("bias", initializers.get("zeros"), self.size)
        return x + b.astype(x.dtype)


class CMul(Module):
    """Trainable scale of an explicit shape, broadcast-multiplied (BigDL
    CMul)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        w = scope.param("weight", initializers.get("ones"), self.size)
        return x * w.astype(x.dtype)


class HardTanh(Module):
    """clip(x, min_value, max_value) (BigDL HardTanh)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.min_value, self.max_value = min_value, max_value

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return jnp.clip(x, self.min_value, self.max_value)


class GaussianSampler(Module):
    """VAE reparameterization: input [mean, log_var] -> mean + eps*std
    (BigDL GaussianSampler; zoo keras-1's VAE building block).  Sampling
    uses the scope rng in training mode; eval returns the mean (the
    deterministic serving behavior)."""

    def forward(self, scope: Scope, inputs: Sequence[jax.Array]) -> jax.Array:
        mean, log_var = inputs
        if not scope.training:
            return mean
        eps = jax.random.normal(scope.make_rng(), mean.shape,
                                dtype=mean.dtype)
        return mean + jnp.exp(0.5 * log_var) * eps


class ResizeBilinear(Module):
    """Bilinear resize of NHWC maps to (output_height, output_width)
    (BigDL/zoo ResizeBilinear).  Sampling matches the reference's legacy-
    TF1 grid — ``src = dst * scale`` from the corner origin (and the
    ``align_corners=True`` variant) — NOT the half-pixel-center grid of
    jax.image.resize / TF2, which yields different pixels."""

    def __init__(self, output_height: int, output_width: int,
                 align_corners: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.out_hw = (output_height, output_width)
        self.align_corners = align_corners

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        oh, ow = self.out_hw

        def grid(o_size, i_size):
            if self.align_corners and o_size > 1:
                scale = (i_size - 1) / (o_size - 1)
            else:
                scale = i_size / o_size
            src = jnp.arange(o_size, dtype=jnp.float32) * scale
            lo = jnp.clip(jnp.floor(src).astype(jnp.int32), 0, i_size - 1)
            hi = jnp.minimum(lo + 1, i_size - 1)
            return lo, hi, (src - lo).astype(jnp.float32)

        y0, y1, wy = grid(oh, h)
        x0, x1, wx = grid(ow, w)
        xf = x.astype(jnp.float32)

        def cols(rows):  # rows: [b, oh, w, c] -> [b, oh, ow, c]
            return (rows[:, :, x0] * (1.0 - wx)[None, None, :, None]
                    + rows[:, :, x1] * wx[None, None, :, None])

        out = (cols(xf[:, y0]) * (1.0 - wy)[None, :, None, None]
               + cols(xf[:, y1]) * wy[None, :, None, None])
        return out.astype(x.dtype)


class ConvLSTM3D(_ConvLSTMND):
    """Volumetric convolutional LSTM over [B, T, D, H, W, C] (BigDL
    ConvLSTM3D)."""
    _rank = 3
    _dims = ("NDHWC", "DHWIO", "NDHWC")


# -- keras-1 merge API ---------------------------------------------------------

class Merge(Module):
    """keras-1 ``Merge(mode=...)`` layer over a LIST of inputs (reference:
    the zoo keras-1 API's merge modes: sum/mul/ave/max/min/concat/dot/cos).
    A thin dispatcher over the canonical merge layers (Add, Multiply,
    Dot, ...) so keras-1-era scripts port verbatim."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 dot_axes: Any = -1, name: Optional[str] = None):
        super().__init__(name)
        from .layers import Add, Concatenate, Multiply
        from .layers_extra import Average, Dot, Maximum, Minimum
        mode = mode.lower()
        table = {"sum": Add, "mul": Multiply, "ave": Average,
                 "max": Maximum, "min": Minimum}
        if mode in table:
            self.impl: Module = table[mode]()
        elif mode == "concat":
            self.impl = Concatenate(axis=concat_axis)
        elif mode == "dot":
            # keras-1 merge(mode='dot', dot_axes=...) == batch_dot
            self.impl = Dot(axes=dot_axes)
        elif mode == "cos":
            self.impl = Cos()
        else:
            raise ValueError(f"unknown merge mode {mode!r}")
        self.mode = mode

    def forward(self, scope: Scope, inputs: Sequence[jax.Array]) -> jax.Array:
        out = scope.child(self.impl, list(inputs), name=self.mode)
        if self.mode == "dot" and out.ndim == 1:
            out = out[:, None]  # keras batch_dot keeps >= 2 dims
        return out


def merge(inputs: Sequence[Any], mode: str = "sum",
          concat_axis: int = -1, dot_axes: Any = -1):
    """keras-1 functional spelling: ``merge([a, b], mode="sum")`` — works
    on SymbolicTensors inside an ``nn.Model`` graph and on arrays."""
    layer = Merge(mode=mode, concat_axis=concat_axis, dot_axes=dot_axes)
    from .functional import _contains_symbolic
    if _contains_symbolic(list(inputs)):
        return layer(inputs)
    out, _ = layer.apply({"params": {}, "state": {}}, list(inputs))
    return out
