"""Core + convolution + normalization layers (Keras-style, TPU-native).

Reference (SURVEY.md §2.3): the Keras-1.2 layer zoo in
zoo/src/main/scala/com/intel/analytics/zoo/pipeline/api/keras/layers/ with
py4j mirrors in pyzoo/zoo/pipeline/api/keras/layers/.  Scoped here to the
subset used by zoo.models + the BASELINE configs (SURVEY.md §7 "Keras-1.2 API
breadth"), with TPU-idiomatic choices:

- NHWC image layout (TPU conv layout; the reference used NCHW for MKL-DNN),
- optional bfloat16 compute dtype on matmul/conv (MXU native) with float32
  params and accumulation,
- everything jit/vmap/shard_map-composable (pure functions of variables).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import activations, initializers
from .module import Module, Scope


def _pair(v: Union[int, Sequence[int]]) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)  # type: ignore


def _cast_for_compute(x: jax.Array, dtype: Optional[Any]) -> jax.Array:
    return x.astype(dtype) if dtype is not None else x


def _norm_padding(p: Any) -> Any:
    """'same'/'valid' → upper string; int / (h, w) / ((lo,hi),(lo,hi)) →
    explicit per-dimension pad pairs (torch-style numeric padding)."""
    if isinstance(p, str):
        return p.upper()
    if isinstance(p, int):
        return ((p, p), (p, p))
    p = tuple(p)
    if all(isinstance(e, int) for e in p):
        return tuple((e, e) for e in p)
    return tuple((int(a), int(b)) for a, b in p)


class Dense(Module):
    """Fully connected layer (reference: keras/layers Dense)."""

    def __init__(self, units: int, activation: Any = None, use_bias: bool = True,
                 kernel_init: Any = "glorot_uniform", bias_init: Any = "zeros",
                 dtype: Optional[Any] = None, name: Optional[str] = None):
        super().__init__(name)
        self.units = units
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)
        self.bias_init = initializers.get(bias_init)
        self.dtype = dtype

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        w = scope.param("kernel", self.kernel_init, (x.shape[-1], self.units))
        q = scope.quant
        if q is not None and q.mode == "collect":
            q.observe(scope.path, x)
        y = None
        if isinstance(w, dict):  # int8 serving: {marker, q, scale} kernel
            from . import quant as _quant
            if q is not None and q.mode == "apply":
                y = _quant.dense_quantized(q, scope.path, x, w["q"],
                                           w["scale"], q.compute_dtype)
                if y is not None:
                    y = y.astype(x.dtype)
            if y is None:  # weight-only: dequant fuses into the matmul
                w = (w["q"].astype(x.dtype)
                     * w["scale"].astype(x.dtype))
        if y is None:
            xc = _cast_for_compute(x, self.dtype)
            # No preferred_element_type=f32: the MXU accumulates bf16
            # matmuls in f32 internally, and an f32-typed output whose
            # only consumer downcasts would poison the WHOLE backward —
            # the f32 cotangent turns both vjp matmuls into mixed
            # f32 x bf16 dots (measured: the dominant BERT bwd cost).
            y = jnp.dot(xc, _cast_for_compute(w, self.dtype).astype(xc.dtype))
            y = y.astype(x.dtype) if x.dtype != y.dtype else y
        if self.use_bias:
            b = scope.param("bias", self.bias_init, (self.units,))
            y = y + b.astype(y.dtype)  # don't promote bf16 back to f32
        return self.activation(y)


class Embedding(Module):
    """Token embedding (reference: keras/layers Embedding)."""

    def __init__(self, input_dim: int, output_dim: int,
                 embeddings_init: Any = "normal", name: Optional[str] = None):
        super().__init__(name)
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.embeddings_init = initializers.get(embeddings_init)

    def forward(self, scope: Scope, ids: jax.Array) -> jax.Array:
        table = scope.param("embeddings", self.embeddings_init,
                            (self.input_dim, self.output_dim))
        return jnp.take(table, ids, axis=0)


class Dropout(Module):
    def __init__(self, rate: float, name: Optional[str] = None):
        super().__init__(name)
        self.rate = float(rate)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if not scope.training or self.rate <= 0.0:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(scope.make_rng(), keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)


class Flatten(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.reshape(x.shape[0], -1)


class Reshape(Module):
    def __init__(self, target_shape: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.target_shape = tuple(target_shape)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.reshape((x.shape[0],) + self.target_shape)


class Activation(Module):
    def __init__(self, activation: Any, name: Optional[str] = None):
        super().__init__(name)
        self.fn = activations.get(activation)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return self.fn(x)


class Lambda(Module):
    """Wrap an arbitrary pure function as a layer (reference: autograd Lambda,
    pyzoo/zoo/pipeline/api/autograd.py)."""

    def __init__(self, fn: Callable, name: Optional[str] = None):
        super().__init__(name)
        self.fn = fn

    def forward(self, scope: Scope, *args: Any) -> Any:
        return self.fn(*args)


# -- convolution / pooling (NHWC) ---------------------------------------------

class Conv2D(Module):
    """2-D convolution, NHWC/HWIO (reference: keras/layers Convolution2D —
    which was NCHW for MKL-DNN; NHWC is the TPU-native layout)."""

    def __init__(self, filters: int, kernel_size: Union[int, Sequence[int]],
                 strides: Union[int, Sequence[int]] = 1,
                 padding: Any = "same", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "he_normal",
                 dilation: Union[int, Sequence[int]] = 1,
                 groups: int = 1, dtype: Optional[Any] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        # "same"/"valid", or torch-style numeric padding (int / pair /
        # explicit (lo, hi) pairs) for exact foreign-model parity
        self.padding = _norm_padding(padding)
        self.activation = activations.get(activation)
        self.use_bias = use_bias
        self.kernel_init = initializers.get(kernel_init)
        self.dilation = _pair(dilation)
        self.groups = groups
        self.dtype = dtype

    # plain Conv2D participates in calibrated int8 activation
    # quantization (serving); kernel-transforming subclasses
    # (ScaledWSConv2D) opt out — their weight math needs the float kernel
    _act_quant = True

    def _kernel(self, scope: Scope, shape: Tuple[int, ...]) -> jax.Array:
        """Weight fetch hook — subclasses may transform (e.g. weight
        standardization) before the conv consumes it."""
        return scope.param("kernel", self.kernel_init, shape)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        in_ch = x.shape[-1]
        w = self._kernel(scope, (kh, kw, in_ch // self.groups,
                                 self.filters))
        q = scope.quant
        if q is not None and q.mode == "collect" and self._act_quant:
            q.observe(scope.path, x)
        y = None
        if isinstance(w, dict):  # int8 serving: {marker, q, scale} kernel
            from . import quant as _quant
            if q is not None and q.mode == "apply":
                y = _quant.conv_quantized(
                    q, scope.path, x, w["q"], w["scale"], self.strides,
                    self.padding, self.dilation, self.groups,
                    q.compute_dtype)
                if y is not None:
                    y = y.astype(x.dtype)
            if y is None:
                # weight-only fallback: dequant fuses into the conv
                w = w["q"].astype(x.dtype) * w["scale"].astype(x.dtype)
        if y is None:
            y = self._float_conv(x, w)
        if self.use_bias:
            b = scope.param("bias", initializers.get("zeros"),
                            (self.filters,))
            y = y + b.astype(y.dtype)
        return self.activation(y)

    def _float_conv(self, x: jax.Array, w: jax.Array) -> jax.Array:
        kh, kw = self.kernel_size
        in_ch = x.shape[-1]
        xc = _cast_for_compute(x, self.dtype)
        wc = _cast_for_compute(w, self.dtype).astype(xc.dtype)
        pad_free = (self.padding in ("SAME", "VALID")
                    or all(p == (0, 0) for p in self.padding))
        if (kh == kw == 1 and self.strides == (1, 1) and pad_free
                and self.dilation == (1, 1) and self.groups == 1):
            # 1x1/s1 conv as an explicit matmul over flattened positions.
            # Same math, but the vjp becomes two dot_generals — profiled:
            # XLA lowered these convs' WEIGHT gradients to VPU
            # multiply-reduce fusions (~0.5 ms each across ResNet's ~30
            # 1x1 convs) instead of MXU matmuls (~0.03 ms).
            y = jnp.dot(xc.reshape(-1, in_ch), wc.reshape(in_ch,
                                                          self.filters))
            y = y.reshape(x.shape[:-1] + (self.filters,))
        else:
            # No preferred_element_type: the conv vjp in this JAX version
            # rejects mixed (bf16 cotangent, f32-preferred) operands, and
            # the TPU MXU accumulates bf16 convs in f32 natively anyway.
            y = jax.lax.conv_general_dilated(
                xc, wc,
                window_strides=self.strides, padding=self.padding,
                rhs_dilation=self.dilation,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=self.groups)
        return y.astype(x.dtype) if x.dtype != y.dtype else y


def scaled_ws_kernel(w: jax.Array, gain: jax.Array) -> jax.Array:
    """Scaled Weight Standardization of a HWIO conv kernel:
    ``gain_o * (W - mean_o) / (std_o * sqrt(fan_in))`` with per-output-
    channel statistics over the fan-in dims.  Shared by ScaledWSConv2D
    and the space-to-depth stem so the formula cannot drift."""
    fan_in = w.shape[0] * w.shape[1] * w.shape[2]
    mean = jnp.mean(w, axis=(0, 1, 2), keepdims=True)
    var = jnp.var(w, axis=(0, 1, 2), keepdims=True)
    scale = jax.lax.rsqrt(jnp.maximum(var * fan_in, 1e-4))
    return (w - mean) * (scale * gain)


class ScaledWSConv2D(Conv2D):
    """Conv2D with Scaled Weight Standardization (public technique:
    Brock et al., "Characterizing signal propagation ...", 2021 — the
    NF-ResNet building block): the kernel used in the conv is
    ``g_o * (W - mean_o) / (std_o * sqrt(fan_in))`` with per-output-
    channel statistics over the fan-in and a learnable per-channel gain.

    TPU rationale: batch norm's activation statistics cost full
    feature-map reductions every step (bandwidth-bound); weight
    statistics touch only the ~KB-scale kernels, so normalization moves
    off the hot path entirely.  Gradients flow through the
    standardization (that is what controls signal propagation).

    ``skip_init=True`` additionally folds a zero-initialised learnable
    scalar (SkipInit, times ``branch_scale``) into the kernel.  Because
    a conv is linear in its weights, ``s * conv(x, W) == conv(x, s*W)``
    — same math, but the SkipInit gradient ``dL/ds`` is computed by the
    adjoint in WEIGHT space (a kernel-sized contraction that rides the
    dW conv already being computed) instead of a full feature-map
    scalar reduction.  Measured on NF-RN50/B128: the explicit
    ``shortcut + s*h`` form cost ~1.3 ms/step of map->scalar VPU
    reduces per big block; the folded form removes them entirely.
    """

    _act_quant = False  # weight standardization needs the float kernel

    def __init__(self, *args, skip_init: bool = False,
                 branch_scale: float = 1.0, **kwargs):
        super().__init__(*args, **kwargs)
        self.skip_init = skip_init
        self.branch_scale = branch_scale

    def _kernel(self, scope: Scope, shape: Tuple[int, ...]) -> jax.Array:
        w = scope.param("kernel", self.kernel_init, shape)
        gain = scope.param("ws_gain", initializers.get("ones"),
                           (shape[-1],))
        if self.skip_init:
            s = scope.param("skip_gain", initializers.get("zeros"), ())
            gain = gain * (s * self.branch_scale)
        return scaled_ws_kernel(w, gain)


class Conv1D(Module):
    def __init__(self, filters: int, kernel_size: int, strides: int = 1,
                 padding: str = "same", activation: Any = None,
                 use_bias: bool = True, kernel_init: Any = "he_normal",
                 dilation: int = 1, name: Optional[str] = None):
        super().__init__(name)
        self.conv = Conv2D(filters, (1, kernel_size), (1, strides), padding,
                           activation, use_bias, kernel_init, (1, dilation),
                           name="conv2d")

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        y = scope.child(self.conv, x[:, None, :, :], name="conv")
        return y[:, 0]


def _pool(x: jax.Array, kind: str, window: Tuple[int, int],
          strides: Tuple[int, int], padding: Any) -> jax.Array:
    dims = (1, window[0], window[1], 1)
    strd = (1, strides[0], strides[1], 1)
    explicit = not isinstance(padding, str)
    if explicit:  # per-spatial-dim (lo, hi) pairs -> full 4-dim spec
        padding = ((0, 0),) + tuple(padding) + ((0, 0),)
    if kind == "max":
        return jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strd,
                                     padding)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strd, padding)
    if padding == "VALID" or explicit:
        # explicit numeric padding follows torch AvgPool2d semantics
        # (count_include_pad=True): pads are zeros AND count in the divisor
        return s / (window[0] * window[1])
    ones = jnp.ones(x.shape[:1] + x.shape[1:3] + (1,), x.dtype)
    cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strd, padding)
    return s / cnt


class MaxPooling2D(Module):
    def __init__(self, pool_size: Union[int, Sequence[int]] = 2,
                 strides: Optional[Union[int, Sequence[int]]] = None,
                 padding: Any = "valid", name: Optional[str] = None):
        super().__init__(name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = _norm_padding(padding)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return _pool(x, "max", self.pool_size, self.strides, self.padding)


class AveragePooling2D(Module):
    def __init__(self, pool_size: Union[int, Sequence[int]] = 2,
                 strides: Optional[Union[int, Sequence[int]]] = None,
                 padding: Any = "valid", name: Optional[str] = None):
        super().__init__(name)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = _norm_padding(padding)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return _pool(x, "avg", self.pool_size, self.strides, self.padding)


class GlobalAveragePooling2D(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.mean(axis=(1, 2))


class GlobalMaxPooling2D(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.max(axis=(1, 2))


class GlobalAveragePooling1D(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.mean(axis=1)


class GlobalMaxPooling1D(Module):
    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return x.max(axis=1)


class ZeroPadding2D(Module):
    def __init__(self, padding: Union[int, Sequence[int]] = 1,
                 name: Optional[str] = None):
        super().__init__(name)
        self.padding = _pair(padding)

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        ph, pw = self.padding
        return jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))


# -- normalization -------------------------------------------------------------

class BatchNormalization(Module):
    """Batch norm with running statistics carried in the state collection
    (reference: keras/layers BatchNormalization; BigDL mutated them in-place,
    here apply() returns the updated state)."""

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 center: bool = True, scale: bool = True,
                 axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.momentum = momentum
        self.epsilon = epsilon
        self.center = center
        self.scale = scale
        self.axis = axis

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        dim = x.shape[self.axis]
        reduce_axes = tuple(i for i in range(x.ndim)
                            if i != (self.axis % x.ndim))
        mean_run = scope.variable("mean", lambda: jnp.zeros((dim,)))
        var_run = scope.variable("var", lambda: jnp.ones((dim,)))
        if scope.training and (self.axis % x.ndim) == x.ndim - 1:
            # Channel-last training: the fused custom-VJP path
            # (ops/fused_bn.py) — identical statistics and normalize
            # math, but a hand-written backward that keeps every
            # feature-map read/write in the activation dtype.  Autodiff
            # of the inline formulation below makes XLA materialize f32
            # copies of every BN input map (measured ~40% of an RN50
            # step in reduce+conv-fusion overhead).
            from ..ops import fused_bn
            gamma = (scope.param("gamma", initializers.get("ones"),
                                 (dim,))
                     if self.scale else jnp.ones((dim,), jnp.float32))
            beta = (scope.param("beta", initializers.get("zeros"),
                                (dim,))
                    if self.center else jnp.zeros((dim,), jnp.float32))
            y, mean, var = fused_bn.bn_train(x, gamma, beta,
                                             self.epsilon)
            m = self.momentum
            scope.put_variable("mean", m * mean_run + (1 - m) * mean)
            scope.put_variable("var", m * var_run + (1 - m) * var)
            return y
        if scope.training:
            # statistics in f32 (bf16 accumulation over B*H*W loses too
            # much), state stays f32.  E[xc^2] - E[xc]^2 instead of the
            # two-pass var: both reductions share one fused read of the
            # activation (multi-output fusion) — BN is bandwidth-bound, so
            # a second full pass over every feature map is measurable.
            # xc is shifted by one stop-gradded SAMPLE per channel:
            # moments are shift-invariant (so values and gradients are
            # analytically unchanged), but the shift keeps the
            # mean-of-squares subtraction from cancelling catastrophically
            # for badly centered channels (|mean| >> std), where the raw
            # E[x^2]-E[x]^2 in f32 collapses to garbage.
            xf = x.astype(jnp.float32)
            idx = tuple(0 if i in reduce_axes else slice(None)
                        for i in range(x.ndim))
            shift = jax.lax.stop_gradient(xf[idx]).reshape(
                [1 if i in reduce_axes else x.shape[i]
                 for i in range(x.ndim)])
            xc = xf - shift
            mean_c = xc.mean(axis=reduce_axes)
            var = jnp.maximum(
                jnp.mean(jnp.square(xc), axis=reduce_axes)
                - jnp.square(mean_c), 0.0)
            mean = mean_c + shift.reshape(-1)
            m = self.momentum
            scope.put_variable("mean", m * mean_run + (1 - m) * mean)
            scope.put_variable("var", m * var_run + (1 - m) * var)
        else:
            mean, var = mean_run, var_run
        shape = [1] * x.ndim
        shape[self.axis] = dim
        # Mean-centered form with a rounding-compensated shift, all
        # per-ELEMENT math in the activation dtype.  (x - mean) of nearby
        # bf16 values is cancellation-safe (Sterbenz), and keeping the
        # elementwise chain bf16 keeps every BN fwd/bwd kernel at bf16
        # HBM bytes — an f32 upcast here measures ~6% of a whole RN50
        # train step.  The one hazard of a bf16 mean — rounding it
        # injects a per-channel bias of up to (|mean|/std)*2^-9 sigma —
        # is cancelled exactly by folding the f32 rounding residual
        # (mean_rounded - mean) * inv into the per-CHANNEL shift, which
        # costs C scalar flops.  Statistics stay f32 throughout.
        inv = jax.lax.rsqrt(var + self.epsilon)
        if self.scale:
            inv = inv * scope.param("gamma", initializers.get("ones"),
                                    (dim,))
        mean_c = mean.astype(x.dtype)
        shift = (mean_c.astype(jnp.float32) - mean) * inv
        if self.center:
            shift = shift + scope.param("beta", initializers.get("zeros"),
                                        (dim,))
        inv_c = inv.astype(x.dtype).reshape(shape)
        y = (x - mean_c.reshape(shape)) * inv_c
        return y + shift.astype(x.dtype).reshape(shape)


class LayerNormalization(Module):
    def __init__(self, epsilon: float = 1e-6, name: Optional[str] = None):
        super().__init__(name)
        self.epsilon = epsilon

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        dim = x.shape[-1]
        xf = x.astype(jnp.float32)  # stats in f32 even for bf16 activations
        mean = xf.mean(axis=-1, keepdims=True)
        var = jnp.square(xf - mean).mean(axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        g = scope.param("gamma", initializers.get("ones"), (dim,))
        b = scope.param("beta", initializers.get("zeros"), (dim,))
        return (y * g + b).astype(x.dtype)  # keep the compute dtype


# -- merge layers (reference: keras merge.Concat/Add/Mul) ----------------------

class Concatenate(Module):
    def __init__(self, axis: int = -1, name: Optional[str] = None):
        super().__init__(name)
        self.axis = axis

    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        return jnp.concatenate(list(xs), axis=self.axis)


class Add(Module):
    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out


class Multiply(Module):
    def forward(self, scope: Scope, xs: Sequence[jax.Array]) -> jax.Array:
        out = xs[0]
        for x in xs[1:]:
            out = out * x
        return out


# -- containers ----------------------------------------------------------------

class Sequential(Module):
    """Linear stack of layers (reference: keras/models Sequential)."""

    def __init__(self, layers: Optional[Sequence[Module]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.layers = list(layers or [])

    def add(self, layer: Module) -> "Sequential":
        self.layers.append(layer)
        return self

    def forward(self, scope: Scope, x: Any, **kwargs: Any) -> Any:
        for i, layer in enumerate(self.layers):
            base = layer.name or f"layer{i}"
            x = scope.child(layer, x, name=f"{i:02d}_{base}"
                            if layer.name is None else layer.name)
        return x
