"""Anomaly detectors (reference: pyzoo/zoo/chronos/detector/anomaly —
ThresholdDetector, AEDetector, DBScanDetector).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.orca.learn import Estimator


class ThresholdDetector:
    """Flag |y - yhat| above a threshold; threshold fit from a normal-ratio
    quantile when not given (reference: threshold detection on residuals)."""

    def __init__(self, threshold: Optional[float] = None,
                 ratio: float = 0.01):
        self.threshold = threshold
        self.ratio = ratio

    def fit(self, y: np.ndarray, y_pred: Optional[np.ndarray] = None
            ) -> "ThresholdDetector":
        err = np.abs(np.asarray(y) - (0 if y_pred is None
                                      else np.asarray(y_pred))).reshape(-1)
        if self.threshold is None:
            self.threshold = float(np.quantile(err, 1.0 - self.ratio))
        return self

    def score(self, y: np.ndarray, y_pred: Optional[np.ndarray] = None
              ) -> np.ndarray:
        return np.abs(np.asarray(y) - (0 if y_pred is None
                                       else np.asarray(y_pred))).reshape(-1)

    def anomaly_indexes(self, y: np.ndarray,
                        y_pred: Optional[np.ndarray] = None) -> np.ndarray:
        if self.threshold is None:
            self.fit(y, y_pred)
        return np.where(self.score(y, y_pred) > self.threshold)[0]


class AEDetector:
    """Autoencoder reconstruction-error detector (reference: AEDetector —
    torch AE there; jit-compiled dense AE here)."""

    def __init__(self, roll_len: int = 24, ratio: float = 0.05,
                 hidden: Sequence[int] = (16, 8), lr: float = 1e-3,
                 epochs: int = 10, batch_size: int = 32):
        self.roll_len = roll_len
        self.ratio = ratio
        self.hidden = list(hidden)
        self.lr = lr
        self.epochs = epochs
        self.batch_size = batch_size
        self._est = None
        self._threshold = None

    def _windows(self, y: np.ndarray) -> np.ndarray:
        y = np.asarray(y, np.float32).reshape(-1)
        if self.roll_len <= 1:
            return y[:, None]
        n = len(y) - self.roll_len + 1
        idx = np.arange(self.roll_len)[None, :] + np.arange(n)[:, None]
        return y[idx]

    def fit(self, y: np.ndarray) -> "AEDetector":
        x = self._windows(y)
        dims = self.hidden + [x.shape[-1]]
        layers = [nn.Dense(d, activation="relu" if i < len(dims) - 1
                           else None)
                  for i, d in enumerate(dims)]
        self._est = Estimator.from_keras(nn.Sequential(layers), loss="mse",
                                         learning_rate=self.lr)
        self._est.fit((x, x), epochs=self.epochs,
                      batch_size=min(self.batch_size, len(x)), verbose=False)
        self._threshold = float(np.quantile(self.score(y), 1 - self.ratio))
        return self

    def score(self, y: np.ndarray) -> np.ndarray:
        x = self._windows(y)
        recon = self._est.predict(x, batch_size=self.batch_size)
        err = np.mean(np.square(recon - x), axis=-1)
        # distribute window scores back to points (use the window end)
        pad = np.full(self.roll_len - 1, err[0])
        return np.concatenate([pad, err])

    def anomaly_indexes(self, y: np.ndarray) -> np.ndarray:
        if self._est is None:
            self.fit(y)
        return np.where(self.score(y) > self._threshold)[0]


class DBScanDetector:
    """sklearn DBSCAN outlier detection (reference: DBScanDetector)."""

    def __init__(self, eps: float = 0.5, min_samples: int = 5):
        self.eps = eps
        self.min_samples = min_samples

    def anomaly_indexes(self, y: np.ndarray) -> np.ndarray:
        from sklearn.cluster import DBSCAN
        y = np.asarray(y, np.float64).reshape(-1, 1)
        labels = DBSCAN(eps=self.eps,
                        min_samples=self.min_samples).fit_predict(y)
        return np.where(labels == -1)[0]
