"""XShardsTSDataset: the distributed TSDataset.

Reference (SURVEY.md §2.6): ``pyzoo/zoo/chronos/data/experimental/
xshardstsdataset.py`` — TSDataset semantics over SparkXShards so huge
multi-id panels (one shard = a subset of series ids) preprocess in
parallel without one host holding the whole frame.

TPU-native: the shards are host-local ``XShards`` (threaded per-shard
transforms); per-shard ops (impute, dt features, roll) run embarrassingly
parallel through ``transform_shard``, while ``scale`` does the one
genuinely distributed step — a two-pass global-moments reduction
(per-shard (count, sum, sumsq/min/max) → combined scaler → applied per
shard), so every shard is scaled by the GLOBAL statistics exactly as the
single-frame TSDataset would."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.data import XShards
from .data import TSDataset


def _make_ts(df: pd.DataFrame, cfg: Dict[str, Any]) -> TSDataset:
    return TSDataset(df, cfg["dt_col"], cfg["target_col"], cfg["id_col"],
                     cfg["extra_feature_col"])


class XShardsTSDataset:
    def __init__(self, shards: XShards, dt_col: str,
                 target_col: Union[str, Sequence[str]],
                 id_col: Optional[str] = None,
                 extra_feature_col: Optional[Sequence[str]] = None):
        self.shards = shards
        self._cfg = dict(dt_col=dt_col, target_col=target_col,
                         id_col=id_col, extra_feature_col=extra_feature_col)
        self.scaler: Optional[Dict[str, Any]] = None

    @staticmethod
    def from_xshards(shards: XShards, dt_col: str,
                     target_col: Union[str, Sequence[str]],
                     id_col: Optional[str] = None,
                     extra_feature_col: Optional[Sequence[str]] = None
                     ) -> "XShardsTSDataset":
        """Shards of DataFrames (each holding whole series — split by id,
        never mid-series) → distributed TSDataset."""
        return XShardsTSDataset(shards, dt_col, target_col, id_col,
                                extra_feature_col)

    @staticmethod
    def from_pandas(df: pd.DataFrame, dt_col: str,
                    target_col: Union[str, Sequence[str]],
                    id_col: Optional[str] = None,
                    extra_feature_col: Optional[Sequence[str]] = None,
                    num_shards: int = 4) -> "XShardsTSDataset":
        """Partition a multi-id frame into shards BY SERIES ID (a series
        never straddles shards, so rolling windows stay correct)."""
        if id_col is None:
            shards = XShards([df])
        else:
            ids = df[id_col].unique()
            groups = np.array_split(ids, max(1, min(num_shards, len(ids))))
            shards = XShards([
                df[df[id_col].isin(g)].reset_index(drop=True)
                for g in groups if len(g)])
        return XShardsTSDataset(shards, dt_col, target_col, id_col,
                                extra_feature_col)

    # -- per-shard ops (embarrassingly parallel) ------------------------------

    def _map(self, fn) -> "XShardsTSDataset":
        """Apply a TSDataset op per shard, IN PLACE (TSDataset semantics:
        ops mutate and return self, so ``ds.scale(...)`` without
        reassignment behaves identically on both classes)."""
        cfg = self._cfg
        feature_cols: List[Any] = []

        def run(df: pd.DataFrame) -> pd.DataFrame:
            ts = _make_ts(df, cfg)
            fn(ts)
            if not feature_cols:  # ops may ADD feature columns (dt feats)
                feature_cols.append(list(ts.feature_col))
            return ts.df

        self.shards = self.shards.transform_shard(run)
        if feature_cols and feature_cols[0] != list(
                self._cfg["extra_feature_col"] or []):
            self._cfg["extra_feature_col"] = feature_cols[0]
        return self

    def impute(self, mode: str = "last") -> "XShardsTSDataset":
        return self._map(lambda ts: ts.impute(mode))

    def gen_dt_feature(self, features: Optional[Sequence[str]] = None
                       ) -> "XShardsTSDataset":
        return self._map(lambda ts: ts.gen_dt_feature(features))

    # -- distributed scaling ---------------------------------------------------

    def _cols(self) -> List[str]:
        t = self._cfg["target_col"]
        targets = [t] if isinstance(t, str) else list(t)
        extras = list(self._cfg["extra_feature_col"] or [])
        return targets + extras

    def scale(self, scaler: Any = "standard", fit: bool = True
              ) -> "XShardsTSDataset":
        cols = self._cols()
        if isinstance(scaler, dict):
            self.scaler = scaler
        elif fit:
            # pass 1: per-shard sufficient statistics (per-column non-NaN
            # counts, NOT len(df) — sum/sumsq skip NaN, the count must too
            # or pre-impute scaling diverges from the single-frame path)
            stats = self.shards.transform_shard(
                lambda df: (df[cols].count(), df[cols].sum(),
                            (df[cols] ** 2).sum(),
                            df[cols].min(), df[cols].max())).collect()
            n = sum((s[0] for s in stats), pd.Series(0, index=cols))
            total = sum((s[1] for s in stats),
                        pd.Series(0.0, index=cols))
            total_sq = sum((s[2] for s in stats),
                           pd.Series(0.0, index=cols))
            if scaler == "standard":
                mean = total / n
                var = total_sq / n - mean ** 2
                std = np.sqrt(np.maximum(var, 0.0) * n / np.maximum(1, n - 1))
                std = pd.Series(std, index=cols).replace(0, 1.0)
                self.scaler = {"type": "standard", "mean": mean, "std": std}
            elif scaler == "minmax":
                mn = pd.concat([s[3] for s in stats], axis=1).min(axis=1)
                mx = pd.concat([s[4] for s in stats], axis=1).max(axis=1)
                rng = (mx - mn).replace(0, 1.0)
                self.scaler = {"type": "minmax", "min": mn, "range": rng}
            else:
                raise ValueError(f"unknown scaler {scaler!r}")
        elif self.scaler is None:
            raise ValueError("fit=False requires a previously fit scaler")
        s = self.scaler
        # pass 2 (in place): the single-frame TSDataset applies a fitted
        # dict scaler itself — one implementation of the formulas, not two
        self._map(lambda ts: ts.scale(s))
        return self

    def unscale_numpy(self, arr: np.ndarray) -> np.ndarray:
        ts = TSDataset(pd.DataFrame(columns=[self._cfg["dt_col"]]),
                       **self._cfg)
        ts.scaler = self.scaler
        return ts.unscale_numpy(arr)

    # -- windowing / export ---------------------------------------------------

    def roll(self, lookback: int, horizon: Union[int, Sequence[int]]
             ) -> "XShardsTSDataset":
        cfg = self._cfg

        def run(df: pd.DataFrame):
            ts = _make_ts(df, cfg)
            try:
                ts.roll(lookback, horizon)
            except ValueError:
                # a shard whose every series is shorter than the window
                # contributes nothing — the single-frame TSDataset drops
                # short series, so sharding must not turn that into a crash
                return None
            return ts.to_numpy()

        self._rolled = self.shards.transform_shard(run)
        return self

    def to_numpy(self) -> tuple:
        if not hasattr(self, "_rolled"):
            raise ValueError("call roll() first")
        parts = [p for p in self._rolled.collect() if p is not None]
        if not parts:
            raise ValueError(
                "no shard produced windows: every series is shorter than "
                "lookback + horizon")
        x = np.concatenate([p[0] for p in parts], axis=0)
        y = np.concatenate([p[1] for p in parts], axis=0)
        return x, y

    def to_feed(self, batch_size: int = 32, shuffle: bool = True,
                **kw: Any):
        from analytics_zoo_tpu.data import DataFeed
        x, y = self.to_numpy()
        return DataFeed.from_arrays(x, y, batch_size, shuffle=shuffle, **kw)
