"""Chronos: the time-series toolkit (reference: pyzoo/zoo/chronos —
SURVEY.md §2.6; named zoo/zouwu in older forks).

TSDataset (pandas feature pipeline), forecasters (LSTM/Seq2Seq/TCN/MTNet
on the unified Estimator; TCMF matrix factorization; ARIMA/Prophet gated
on optional CPU deps), anomaly detectors (Threshold/AE/DBScan), and AutoTS
on the automl package.
"""

from .data import TSDataset
from .forecaster import (LSTMForecaster, Seq2SeqForecaster, TCNForecaster,
                         ARIMAForecaster, ProphetForecaster)
from .mtnet import MTNetForecaster
from .tcmf import TCMFForecaster
from .detector import AEDetector, DBScanDetector, ThresholdDetector
from .autots import (AutoLSTM, AutoSeq2Seq, AutoTCN,
                     AutoTSEstimator, TSPipeline)
from .experimental import XShardsTSDataset

__all__ = ["TSDataset", "XShardsTSDataset", "LSTMForecaster", "Seq2SeqForecaster",
           "TCNForecaster", "MTNetForecaster", "TCMFForecaster",
           "ARIMAForecaster", "ProphetForecaster",
           "AEDetector", "DBScanDetector", "ThresholdDetector",
           "AutoTSEstimator", "TSPipeline",
           "AutoLSTM", "AutoTCN", "AutoSeq2Seq"]
