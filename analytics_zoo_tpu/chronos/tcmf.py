"""TCMFForecaster: temporal convolutional matrix factorization (DeepGLO).

Reference (SURVEY.md §2.6): ``pyzoo/zoo/chronos/model/tcmf/`` — TCMF
(Sen et al. 2019 "Think Globally, Act Locally" / DeepGLO): a high-
dimensional series panel Y [n, T] is factorized as Y ≈ F·X with a small
temporal basis X [k, T]; a temporal convolution network learns X's
dynamics and rolls it forward; forecasts are F·X_future.  The reference
trained it with torch on Spark/Ray workers for scale-out.

TPU-native: the factorization is a jit-compiled alternating gradient
descent (both factors updated by optax inside one compiled step — the
panel never leaves the device), and the basis dynamics reuse the chronos
TCN trunk on the unified Estimator.  API parity: fit(x={"y": ndarray}),
predict(horizon) → [n, horizon], save/load.

Distributed fit/predict (the reference ran TCMF on Spark/Ray workers via
Orca): the panel's SERIES dimension is sharded over the mesh's ``data``
axis — y and the per-series factor F live row-sharded, the shared basis X
replicated, and GSPMD inserts the psum for X's gradient.  ``fit`` also
accepts an ``XShards`` of ``{"id", "y"}`` panels (the reference's
distributed input form); ``predict`` then returns per-shard
``{"id", "prediction"}`` XShards, as the reference's distributed TCMF did.
The loss is mask-normalized so a padded/sharded run computes EXACTLY the
single-host numbers (tests assert equality).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from analytics_zoo_tpu.orca.learn import Estimator
from .forecaster import _TCN


class TCMFForecaster:
    def __init__(self, vbsize: int = 128, hbsize: int = 256, num_channels_X=None,
                 y_iters: int = 300, rank: int = 8, tcn_lookback: int = 16,
                 lam: float = 1e-3, lr: float = 5e-2, tcn_lr: float = 1e-3,
                 seed: int = 0):
        """``rank``: k, the basis dimension.  vbsize/hbsize kept for
        reference-API compatibility (batching knobs of the torch impl; the
        jit path trains the full panel in one program)."""
        self._config = dict(num_channels_X=list(num_channels_X or (16, 16)),
                            y_iters=y_iters, rank=rank,
                            tcn_lookback=tcn_lookback, lam=lam, lr=lr,
                            tcn_lr=tcn_lr, seed=seed)
        self.rank = rank
        self.iters = y_iters
        self.lam = lam
        self.lr = lr
        self.tcn_lr = tcn_lr
        self.tcn_lookback = tcn_lookback
        self.num_channels_x = self._config["num_channels_X"]
        self.seed = seed
        self.F: Optional[np.ndarray] = None      # [n, k]
        self.X: Optional[np.ndarray] = None      # [k, T]
        self._tcn_est: Optional[Any] = None
        self._roll = None                        # cached jitted rollout

    def _make_tcn_estimator(self):
        model = _TCN(num_channels=self.num_channels_x, output_dim=self.rank,
                     horizon=1)
        return Estimator.from_keras(model, loss="mse",
                                    learning_rate=self.tcn_lr,
                                    seed=self.seed)

    # -- factorization ---------------------------------------------------------

    def _factorize(self, y: np.ndarray) -> None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from analytics_zoo_tpu.core import get_mesh

        n, t = y.shape
        k = self.rank
        # series-dimension sharding over the mesh's data axis (the
        # reference's distributed TCMF sharded series across workers);
        # rows are zero-padded to the axis size and masked out of the loss,
        # so the sharded numbers equal the single-host ones exactly
        mesh = get_mesh()
        dp = int(mesh.shape.get("data", 1)) if mesh is not None else 1
        pad = (-n) % dp
        y_pad = (np.concatenate([y, np.zeros((pad, t), np.float32)])
                 if pad else y)
        mask = np.zeros((n + pad, 1), np.float32)
        mask[:n] = 1.0
        rng = jax.random.PRNGKey(self.seed)
        rf, rx = jax.random.split(rng)
        f0 = jax.random.normal(rf, (n + pad, k)) * 0.1
        params = {"F": f0, "X": jax.random.normal(rx, (k, t)) * 0.1}
        yd = jnp.asarray(y_pad, jnp.float32)
        maskd = jnp.asarray(mask)
        if dp > 1:
            row = NamedSharding(mesh, P("data", None))
            rep = NamedSharding(mesh, P())
            yd = jax.device_put(yd, row)
            maskd = jax.device_put(maskd, row)
            params = {"F": jax.device_put(params["F"], row),
                      "X": jax.device_put(params["X"], rep)}
        tx = optax.adam(self.lr)
        opt = jax.jit(tx.init)(params)  # opt slots inherit param shardings
        lam = self.lam
        denom_mse = float(n * t)
        denom_f = float(n * k)

        def step(params, opt):
            def loss_fn(p):
                recon = p["F"] @ p["X"]
                mse = jnp.sum(((recon - yd) * maskd) ** 2) / denom_mse
                reg = lam * (jnp.sum((p["F"] * maskd) ** 2) / denom_f
                             + jnp.mean(p["X"] ** 2))
                return mse + reg

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt = tx.update(grads, opt)
            return optax.apply_updates(params, updates), opt, loss

        # lax-scan the whole optimization into ONE compiled program
        @jax.jit
        def run(params, opt):
            def body(carry, _):
                p, o = carry
                p, o, l = step(p, o)
                return (p, o), l

            (params, opt), losses = jax.lax.scan(body, (params, opt), None,
                                                 length=self.iters)
            return params, losses

        params, losses = run(params, opt)
        self.F = np.asarray(params["F"])[:n]
        self.X = np.asarray(params["X"])
        self._factor_loss = float(losses[-1])

    # -- public API ------------------------------------------------------------

    def fit(self, x: Any, val_len: int = 0,
            epochs: int = 5, batch_size: int = 64) -> float:
        """``x``: {"y": [n, T] panel}, or an ``XShards`` whose shards are
        such dicts (optionally with "id") — the reference's distributed
        input form.  Returns the factorization loss."""
        from analytics_zoo_tpu.data import XShards

        self._shard_sizes = self._shard_ids = None
        if isinstance(x, XShards):
            parts = x.collect()
            self._shard_sizes = [np.asarray(p["y"]).shape[0] for p in parts]
            self._shard_ids = [p.get("id") for p in parts]
            x = {"y": np.concatenate(
                [np.asarray(p["y"], np.float32) for p in parts])}
        y = np.asarray(x["y"], np.float32)
        if y.ndim != 2:
            raise ValueError(f"y must be [n, T], got {y.shape}")
        if y.shape[1] <= self.tcn_lookback + 1:
            raise ValueError(
                f"series length {y.shape[1]} too short for tcn_lookback="
                f"{self.tcn_lookback}")
        self._factorize(y)
        # train the TCN on the basis: windows of X.T [T, k]
        xt = self.X.T                                     # [T, k]
        look = self.tcn_lookback
        wins = np.stack([xt[i:i + look] for i in
                         range(len(xt) - look)])          # [N, look, k]
        nexts = np.stack([xt[i + look][None] for i in
                          range(len(xt) - look)])         # [N, 1, k]
        self._tcn_est = self._make_tcn_estimator()
        hist = self._tcn_est.fit((wins, nexts), epochs=epochs,
                                 batch_size=min(batch_size, len(wins)),
                                 verbose=False)
        self._tcn_loss = hist["loss"][-1]
        return self._factor_loss

    def predict(self, horizon: int = 24) -> np.ndarray:
        """Roll the basis forward with the TCN; return F @ X_future
        → [n, horizon].

        The whole autoregressive rollout is ONE compiled program
        (lax.scan over the horizon, window kept on device) — not a
        per-step Estimator.predict round-trip."""
        if self.F is None or self._tcn_est is None:
            raise ValueError("fit first")
        est = self._tcn_est
        model = est.model
        if self._roll is None:
            from functools import partial

            @partial(jax.jit, static_argnums=(2,))
            def roll(ts, window, h):
                def body(w, _):
                    out, _ = model.apply(
                        {"params": ts["params"], "state": ts["state"]},
                        w, training=False)
                    nxt = out[:, 0]                        # [1, k]
                    w = jnp.concatenate([w[:, 1:], nxt[:, None]], axis=1)
                    return w, nxt[0]

                _, steps = jax.lax.scan(body, window, None, length=h)
                return steps                               # [h, k]

            self._roll = roll
        window0 = jnp.asarray(self.X.T[-self.tcn_lookback:],
                              jnp.float32)[None]           # [1, look, k]
        xf = np.asarray(self._roll(est._ts, window0, horizon)).T  # [k, h]
        preds = self.F @ xf
        if getattr(self, "_shard_sizes", None):
            # distributed-input parity: fit saw an XShards panel, so hand
            # back per-shard {"id", "prediction"} shards
            from analytics_zoo_tpu.data import XShards
            out, off = [], 0
            for size, ids in zip(self._shard_sizes, self._shard_ids):
                shard = {"prediction": preds[off:off + size]}
                if ids is not None:
                    shard["id"] = ids
                out.append(shard)
                off += size
            return XShards(out)
        return preds

    def evaluate(self, target_value: Dict[str, np.ndarray],
                 metric=("mae",)) -> Dict[str, float]:
        y = np.asarray(target_value["y"], np.float32)
        pred = self.predict(horizon=y.shape[1])
        if not isinstance(pred, np.ndarray):  # distributed-input mode
            pred = np.concatenate([s["prediction"] for s in pred.collect()])
        err = pred - y
        out = {}
        for m in metric:
            if m == "mae":
                out["mae"] = float(np.mean(np.abs(err)))
            elif m == "mse":
                out["mse"] = float(np.mean(err ** 2))
            else:
                raise ValueError(f"unknown metric {m}")
        return out

    # -- persistence -----------------------------------------------------------

    def save(self, path: str) -> str:
        if self.F is None:
            raise ValueError("nothing to save: fit first")
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "factors.npz"), F=self.F, X=self.X)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(self._config, f)
        if getattr(self, "_shard_sizes", None):
            # distributed-fit metadata: predict() must keep returning
            # per-shard XShards after a save/load round trip
            with open(os.path.join(path, "shards.json"), "w") as f:
                json.dump({"sizes": self._shard_sizes,
                           "ids": [list(i) if i is not None else None
                                   for i in self._shard_ids]}, f)
        self._tcn_est.save(os.path.join(path, "tcn"))
        return path

    @staticmethod
    def load(path: str) -> "TCMFForecaster":
        with open(os.path.join(path, "config.json")) as f:
            cfg = json.load(f)
        fc = TCMFForecaster(**cfg)
        z = np.load(os.path.join(path, "factors.npz"))
        fc.F, fc.X = z["F"], z["X"]
        shards_file = os.path.join(path, "shards.json")
        if os.path.exists(shards_file):
            with open(shards_file) as f:
                meta = json.load(f)
            fc._shard_sizes, fc._shard_ids = meta["sizes"], meta["ids"]
        fc._tcn_est = fc._make_tcn_estimator()
        fc._tcn_est.load(os.path.join(path, "tcn"))
        return fc
