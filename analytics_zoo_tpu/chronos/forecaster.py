"""Forecasters (reference: pyzoo/zoo/chronos/forecaster/*.py — one class per
model, uniform fit/predict/evaluate/save/load).

LSTM / Seq2Seq (enc-dec GRU-or-LSTM) / TCN (dilated temporal conv) run on the
unified Estimator (jit-compiled, mesh-aware).  ARIMA/Prophet wrap optional
CPU libraries (statsmodels/prophet) and are import-gated exactly like the
reference gated pmdarima/prophet.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module
from analytics_zoo_tpu.orca.learn import Estimator


# -- model trunks -------------------------------------------------------------

class _VanillaLSTM(Module):
    def __init__(self, hidden_dim=32, layer_num=1, dropout=0.1,
                 output_dim=1, horizon=1):
        super().__init__()
        self.hidden_dim, self.layer_num = hidden_dim, layer_num
        self.dropout, self.output_dim, self.horizon = (dropout, output_dim,
                                                       horizon)

    def forward(self, scope, x):
        h = x
        for i in range(self.layer_num):
            last = i == self.layer_num - 1
            h = scope.child(nn.LSTM(self.hidden_dim,
                                    return_sequences=not last), h,
                            name=f"lstm_{i}")
            h = scope.child(nn.Dropout(self.dropout), h, name=f"drop_{i}")
        out = scope.child(nn.Dense(self.horizon * self.output_dim), h,
                          name="head")
        return out.reshape(x.shape[0], self.horizon, self.output_dim)


class _Seq2SeqTS(Module):
    def __init__(self, lstm_hidden_dim=32, lstm_layer_num=1, dropout=0.1,
                 output_dim=1, horizon=1, rnn_type="lstm", teacher=False):
        super().__init__()
        self.hidden = lstm_hidden_dim
        self.layers = lstm_layer_num
        self.dropout, self.output_dim, self.horizon = (dropout, output_dim,
                                                       horizon)
        self.rnn_type = rnn_type

    def forward(self, scope, x):
        cls = nn.LSTM if self.rnn_type == "lstm" else nn.GRU
        h = x
        for i in range(self.layers):
            h = scope.child(cls(self.hidden, return_sequences=True), h,
                            name=f"enc_{i}")
        summary = h[:, -1]                               # [B, H]
        # decoder: repeat the summary as input for each horizon step
        dec_in = jnp.repeat(summary[:, None, :], self.horizon, axis=1)
        d = dec_in
        for i in range(self.layers):
            d = scope.child(cls(self.hidden, return_sequences=True), d,
                            name=f"dec_{i}")
        d = scope.child(nn.Dropout(self.dropout), d, name="drop")
        return scope.child(nn.Dense(self.output_dim), d, name="head")


class _TCN(Module):
    """Dilated temporal convolution network (reference:
    pyzoo/zoo/chronos/model/tcn.py — Bai et al. TCN): causal convs via
    left-padding, residual blocks, exponentially growing dilation."""

    def __init__(self, num_channels: Sequence[int] = (32, 32),
                 kernel_size: int = 3, dropout: float = 0.1,
                 output_dim: int = 1, horizon: int = 1):
        super().__init__()
        self.num_channels = list(num_channels)
        self.kernel_size = kernel_size
        self.dropout = dropout
        self.output_dim = output_dim
        self.horizon = horizon

    def forward(self, scope, x):
        h = x                                            # [B, T, F]
        for i, ch in enumerate(self.num_channels):
            dilation = 2 ** i
            pad = (self.kernel_size - 1) * dilation
            blk_in = h
            for j in range(2):
                hp = jnp.pad(h, ((0, 0), (pad, 0), (0, 0)))  # causal pad
                h = scope.child(
                    nn.Conv1D(ch, self.kernel_size, padding="valid",
                              dilation=dilation, activation="relu"),
                    hp, name=f"tcn{i}_conv{j}")
                h = scope.child(nn.Dropout(self.dropout), h,
                                name=f"tcn{i}_drop{j}")
            if blk_in.shape[-1] != ch:
                blk_in = scope.child(nn.Dense(ch), blk_in, name=f"tcn{i}_proj")
            h = jnp.maximum(h + blk_in, 0)
        out = scope.child(nn.Dense(self.horizon * self.output_dim),
                          h[:, -1], name="head")
        return out.reshape(x.shape[0], self.horizon, self.output_dim)


# -- forecaster base ----------------------------------------------------------

class _Forecaster:
    MODEL_CLS: Any = None

    def __init__(self, past_seq_len: int, future_seq_len: int,
                 input_feature_num: int, output_feature_num: int,
                 loss: str = "mse", optimizer: str = "adam",
                 lr: float = 1e-3, metrics: Sequence[str] = ("mse",),
                 seed: int = 0, **model_kwargs: Any):
        self.past_seq_len = past_seq_len
        self.future_seq_len = future_seq_len
        self.input_feature_num = input_feature_num
        self.output_feature_num = output_feature_num
        self.model_kwargs = model_kwargs
        self.model = self._build_model()
        self.est = Estimator.from_keras(
            self.model, loss=loss, optimizer=optimizer, learning_rate=lr,
            metrics=list(metrics), seed=seed)

    def _build_model(self) -> Module:
        return self.MODEL_CLS(output_dim=self.output_feature_num,
                              horizon=self.future_seq_len,
                              **self.model_kwargs)

    @classmethod
    def from_tsdataset(cls, tsdata, past_seq_len: int = 24,
                       future_seq_len: int = 1, **kwargs: Any):
        tsdata.roll(past_seq_len, future_seq_len)
        x, y = tsdata.to_numpy()
        fc = cls(past_seq_len=past_seq_len, future_seq_len=future_seq_len,
                 input_feature_num=x.shape[-1],
                 output_feature_num=y.shape[-1], **kwargs)
        fc._tsdata_xy = (x, y)
        return fc

    def fit(self, data: Any = None, epochs: int = 1, batch_size: int = 32,
            validation_data: Any = None) -> Dict[str, Any]:
        if data is None:
            data = getattr(self, "_tsdata_xy", None)
            if data is None:
                raise ValueError("pass data or use from_tsdataset")
        return self.est.fit(data, epochs=epochs, batch_size=batch_size,
                            validation_data=validation_data, verbose=False)

    def predict(self, x: np.ndarray, batch_size: int = 32) -> np.ndarray:
        return self.est.predict(np.asarray(x, np.float32),
                                batch_size=batch_size)

    def evaluate(self, data: Tuple[np.ndarray, np.ndarray],
                 batch_size: int = 32) -> Dict[str, float]:
        return self.est.evaluate(data, batch_size=batch_size)

    def save(self, path: str) -> str:
        return self.est.save(path)

    def load(self, path: str) -> None:
        self.est.load(path)

    restore = load  # older reference name


class LSTMForecaster(_Forecaster):
    MODEL_CLS = _VanillaLSTM


class Seq2SeqForecaster(_Forecaster):
    MODEL_CLS = _Seq2SeqTS


class TCNForecaster(_Forecaster):
    MODEL_CLS = _TCN


# -- classical (statsmodels preferred, pure-numpy fallback) -------------------

class _NumpyARIMA:
    """Pure-numpy ARIMA(p, d, q) with optional seasonal differencing —
    Hannan–Rissanen two-stage estimation (long-AR residuals, then OLS on
    lagged values + lagged residuals), recursive forecasting with
    differencing inversion.  Exists so ARIMAForecaster EXECUTES in images
    without statsmodels (reference: chronos/model/arima.py wrapped
    pmdarima, an optional dep there too).  Seasonal AR/MA terms (P, Q > 0)
    need a full likelihood optimizer and stay statsmodels-only."""

    def __init__(self, order: Tuple[int, int, int],
                 seasonal_order: Tuple[int, int, int, int] = (0, 0, 0, 0)):
        self.p, self.d, self.q = order
        P, self.D, Q, self.s = seasonal_order
        if P or Q:
            raise NotImplementedError(
                "seasonal AR/MA (P, Q > 0) requires statsmodels; the "
                "numpy backend supports seasonal differencing (D) only")
        if self.d > 2 or self.D > 1:
            raise NotImplementedError("numpy ARIMA supports d<=2, D<=1")

    def fit(self, y: np.ndarray) -> "_NumpyARIMA":
        y = np.asarray(y, np.float64).ravel()
        # differencing pipeline: seasonal first, then regular; tails of
        # every level are kept for inversion at forecast time
        self._season_tail = None
        w = y
        if self.D and self.s:
            self._season_tail = w[-self.s:].copy()
            w = w[self.s:] - w[:-self.s]
        self._level_tails = []
        for _ in range(self.d):
            self._level_tails.append(w[-1])
            w = np.diff(w)
        p, q = self.p, self.q
        need = max(p, q) + p + q + 8
        if len(w) < need:
            raise ValueError(
                f"series too short for ARIMA{(p, self.d, q)}: {len(w)} "
                f"points after differencing, need >= {need}")
        if q:
            # stage 1: long-AR residuals
            p_long = min(max(p + q + 3, 10), len(w) // 3)
            e = np.zeros_like(w)
            X = np.column_stack(
                [np.ones(len(w) - p_long)]
                + [w[p_long - i:len(w) - i] for i in range(1, p_long + 1)])
            beta, *_ = np.linalg.lstsq(X, w[p_long:], rcond=None)
            e[p_long:] = w[p_long:] - X @ beta
        else:
            e = np.zeros_like(w)
        # stage 2: OLS on [1, w lags, e lags]
        m = max(p, q)
        cols = [np.ones(len(w) - m)]
        cols += [w[m - i:len(w) - i] for i in range(1, p + 1)]
        cols += [e[m - j:len(w) - j] for j in range(1, q + 1)]
        X2 = np.column_stack(cols)
        beta, *_ = np.linalg.lstsq(X2, w[m:], rcond=None)
        self.const = beta[0]
        self.phi = beta[1:1 + p]
        self.theta = beta[1 + p:1 + p + q]
        resid = np.zeros_like(w)
        resid[m:] = w[m:] - X2 @ beta
        self._w_tail = w[len(w) - max(p, 1):].copy()
        self._e_tail = resid[len(resid) - max(q, 1):].copy()
        return self

    def forecast(self, horizon: int) -> np.ndarray:
        p, q = self.p, self.q
        w_hist = list(self._w_tail)
        e_hist = list(self._e_tail)
        out = []
        for _ in range(horizon):
            v = self.const
            for i in range(1, p + 1):
                v += self.phi[i - 1] * w_hist[-i]
            for j in range(1, q + 1):
                v += self.theta[j - 1] * e_hist[-j]
            out.append(v)
            w_hist.append(v)
            e_hist.append(0.0)  # future shocks: expectation zero
        f = np.asarray(out)
        # invert regular differencing (innermost level first)
        for last in reversed(self._level_tails):
            f = last + np.cumsum(f)
        # invert seasonal differencing
        if self._season_tail is not None:
            s = self.s
            vals = list(self._season_tail)
            inv = []
            for k, fv in enumerate(f):
                inv.append(vals[k] + fv)
                vals.append(inv[-1])
            f = np.asarray(inv)
        return f


class ARIMAForecaster:
    """ARIMA via statsmodels when importable, else the pure-numpy
    Hannan–Rissanen backend (reference: chronos/model/arima.py — pmdarima,
    likewise an optional dep there)."""

    def __init__(self, order: Tuple[int, int, int] = (1, 0, 0),
                 seasonal_order: Tuple[int, int, int, int] = (0, 0, 0, 0),
                 backend: str = "auto"):
        """``backend``: "auto" (statsmodels if importable), "statsmodels",
        or "numpy"."""
        if backend not in ("auto", "statsmodels", "numpy"):
            raise ValueError(
                f"backend must be 'auto', 'statsmodels' or 'numpy', got "
                f"{backend!r}")
        if backend == "auto":
            try:
                from statsmodels.tsa.arima.model import ARIMA  # noqa: F401
                backend = "statsmodels"
            except ImportError:
                backend = "numpy"
        if backend == "statsmodels":
            from statsmodels.tsa.arima.model import ARIMA  # noqa: F401
        self.backend = backend
        self.order = order
        self.seasonal_order = seasonal_order
        self._fitted = None

    def fit(self, data: np.ndarray) -> "ARIMAForecaster":
        if self.backend == "statsmodels":
            from statsmodels.tsa.arima.model import ARIMA
            self._fitted = ARIMA(np.asarray(data, np.float64),
                                 order=self.order,
                                 seasonal_order=self.seasonal_order).fit()
        else:
            self._fitted = _NumpyARIMA(self.order,
                                       self.seasonal_order).fit(data)
        return self

    def predict(self, horizon: int = 1) -> np.ndarray:
        if self._fitted is None:
            raise ValueError("fit first")
        return np.asarray(self._fitted.forecast(horizon))

    def evaluate(self, y_true: np.ndarray, horizon: Optional[int] = None
                 ) -> Dict[str, float]:
        pred = self.predict(horizon or len(y_true))
        err = pred - np.asarray(y_true)
        return {"mse": float(np.mean(err ** 2)),
                "mae": float(np.mean(np.abs(err)))}


class ProphetForecaster:
    """prophet wrapper (optional dep, import-gated)."""

    def __init__(self, **prophet_kwargs: Any):
        try:
            from prophet import Prophet  # noqa: F401
        except ImportError as e:  # pragma: no cover
            raise ImportError(
                "ProphetForecaster requires the optional 'prophet' package"
            ) from e
        self.kwargs = prophet_kwargs
        self._m = None

    def fit(self, df) -> "ProphetForecaster":
        from prophet import Prophet
        self._m = Prophet(**self.kwargs)
        self._m.fit(df)
        return self

    def predict(self, horizon: int = 1, freq: str = "D"):
        future = self._m.make_future_dataframe(periods=horizon, freq=freq)
        return self._m.predict(future).tail(horizon)
