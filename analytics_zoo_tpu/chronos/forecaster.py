"""Forecasters (reference: pyzoo/zoo/chronos/forecaster/*.py — one class per
model, uniform fit/predict/evaluate/save/load).

LSTM / Seq2Seq (enc-dec GRU-or-LSTM) / TCN (dilated temporal conv) run on the
unified Estimator (jit-compiled, mesh-aware).  ARIMA/Prophet wrap optional
CPU libraries (statsmodels/prophet) and are import-gated exactly like the
reference gated pmdarima/prophet.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module
from analytics_zoo_tpu.orca.learn import Estimator


# -- model trunks -------------------------------------------------------------

class _VanillaLSTM(Module):
    def __init__(self, hidden_dim=32, layer_num=1, dropout=0.1,
                 output_dim=1, horizon=1):
        super().__init__()
        self.hidden_dim, self.layer_num = hidden_dim, layer_num
        self.dropout, self.output_dim, self.horizon = (dropout, output_dim,
                                                       horizon)

    def forward(self, scope, x):
        h = x
        for i in range(self.layer_num):
            last = i == self.layer_num - 1
            h = scope.child(nn.LSTM(self.hidden_dim,
                                    return_sequences=not last), h,
                            name=f"lstm_{i}")
            h = scope.child(nn.Dropout(self.dropout), h, name=f"drop_{i}")
        out = scope.child(nn.Dense(self.horizon * self.output_dim), h,
                          name="head")
        return out.reshape(x.shape[0], self.horizon, self.output_dim)


class _Seq2SeqTS(Module):
    def __init__(self, lstm_hidden_dim=32, lstm_layer_num=1, dropout=0.1,
                 output_dim=1, horizon=1, rnn_type="lstm", teacher=False):
        super().__init__()
        self.hidden = lstm_hidden_dim
        self.layers = lstm_layer_num
        self.dropout, self.output_dim, self.horizon = (dropout, output_dim,
                                                       horizon)
        self.rnn_type = rnn_type

    def forward(self, scope, x):
        cls = nn.LSTM if self.rnn_type == "lstm" else nn.GRU
        h = x
        for i in range(self.layers):
            h = scope.child(cls(self.hidden, return_sequences=True), h,
                            name=f"enc_{i}")
        summary = h[:, -1]                               # [B, H]
        # decoder: repeat the summary as input for each horizon step
        dec_in = jnp.repeat(summary[:, None, :], self.horizon, axis=1)
        d = dec_in
        for i in range(self.layers):
            d = scope.child(cls(self.hidden, return_sequences=True), d,
                            name=f"dec_{i}")
        d = scope.child(nn.Dropout(self.dropout), d, name="drop")
        return scope.child(nn.Dense(self.output_dim), d, name="head")


class _TCN(Module):
    """Dilated temporal convolution network (reference:
    pyzoo/zoo/chronos/model/tcn.py — Bai et al. TCN): causal convs via
    left-padding, residual blocks, exponentially growing dilation."""

    def __init__(self, num_channels: Sequence[int] = (32, 32),
                 kernel_size: int = 3, dropout: float = 0.1,
                 output_dim: int = 1, horizon: int = 1):
        super().__init__()
        self.num_channels = list(num_channels)
        self.kernel_size = kernel_size
        self.dropout = dropout
        self.output_dim = output_dim
        self.horizon = horizon

    def forward(self, scope, x):
        h = x                                            # [B, T, F]
        for i, ch in enumerate(self.num_channels):
            dilation = 2 ** i
            pad = (self.kernel_size - 1) * dilation
            blk_in = h
            for j in range(2):
                hp = jnp.pad(h, ((0, 0), (pad, 0), (0, 0)))  # causal pad
                h = scope.child(
                    nn.Conv1D(ch, self.kernel_size, padding="valid",
                              dilation=dilation, activation="relu"),
                    hp, name=f"tcn{i}_conv{j}")
                h = scope.child(nn.Dropout(self.dropout), h,
                                name=f"tcn{i}_drop{j}")
            if blk_in.shape[-1] != ch:
                blk_in = scope.child(nn.Dense(ch), blk_in, name=f"tcn{i}_proj")
            h = jnp.maximum(h + blk_in, 0)
        out = scope.child(nn.Dense(self.horizon * self.output_dim),
                          h[:, -1], name="head")
        return out.reshape(x.shape[0], self.horizon, self.output_dim)


# -- forecaster base ----------------------------------------------------------

class _Forecaster:
    MODEL_CLS: Any = None

    def __init__(self, past_seq_len: int, future_seq_len: int,
                 input_feature_num: int, output_feature_num: int,
                 loss: str = "mse", optimizer: str = "adam",
                 lr: float = 1e-3, metrics: Sequence[str] = ("mse",),
                 seed: int = 0, **model_kwargs: Any):
        self.past_seq_len = past_seq_len
        self.future_seq_len = future_seq_len
        self.input_feature_num = input_feature_num
        self.output_feature_num = output_feature_num
        self.model_kwargs = model_kwargs
        self.model = self._build_model()
        self.est = Estimator.from_keras(
            self.model, loss=loss, optimizer=optimizer, learning_rate=lr,
            metrics=list(metrics), seed=seed)

    def _build_model(self) -> Module:
        return self.MODEL_CLS(output_dim=self.output_feature_num,
                              horizon=self.future_seq_len,
                              **self.model_kwargs)

    @classmethod
    def from_tsdataset(cls, tsdata, past_seq_len: int = 24,
                       future_seq_len: int = 1, **kwargs: Any):
        tsdata.roll(past_seq_len, future_seq_len)
        x, y = tsdata.to_numpy()
        fc = cls(past_seq_len=past_seq_len, future_seq_len=future_seq_len,
                 input_feature_num=x.shape[-1],
                 output_feature_num=y.shape[-1], **kwargs)
        fc._tsdata_xy = (x, y)
        return fc

    def fit(self, data: Any = None, epochs: int = 1, batch_size: int = 32,
            validation_data: Any = None) -> Dict[str, Any]:
        if data is None:
            data = getattr(self, "_tsdata_xy", None)
            if data is None:
                raise ValueError("pass data or use from_tsdataset")
        return self.est.fit(data, epochs=epochs, batch_size=batch_size,
                            validation_data=validation_data, verbose=False)

    def predict(self, x: np.ndarray, batch_size: int = 32) -> np.ndarray:
        return self.est.predict(np.asarray(x, np.float32),
                                batch_size=batch_size)

    def evaluate(self, data: Tuple[np.ndarray, np.ndarray],
                 batch_size: int = 32) -> Dict[str, float]:
        return self.est.evaluate(data, batch_size=batch_size)

    def save(self, path: str) -> str:
        return self.est.save(path)

    def load(self, path: str) -> None:
        self.est.load(path)

    restore = load  # older reference name


class LSTMForecaster(_Forecaster):
    MODEL_CLS = _VanillaLSTM


class Seq2SeqForecaster(_Forecaster):
    MODEL_CLS = _Seq2SeqTS


class TCNForecaster(_Forecaster):
    MODEL_CLS = _TCN


# -- classical (statsmodels preferred, pure-numpy fallback) -------------------

class _NumpyARIMA:
    """Pure-numpy ARIMA(p, d, q) with optional seasonal differencing —
    Hannan–Rissanen two-stage estimation (long-AR residuals, then OLS on
    lagged values + lagged residuals), recursive forecasting with
    differencing inversion.  Exists so ARIMAForecaster EXECUTES in images
    without statsmodels (reference: chronos/model/arima.py wrapped
    pmdarima, an optional dep there too).  Seasonal AR/MA terms (P, Q > 0)
    need a full likelihood optimizer and stay statsmodels-only."""

    def __init__(self, order: Tuple[int, int, int],
                 seasonal_order: Tuple[int, int, int, int] = (0, 0, 0, 0)):
        self.p, self.d, self.q = order
        P, self.D, Q, self.s = seasonal_order
        if P or Q:
            raise NotImplementedError(
                "seasonal AR/MA (P, Q > 0) requires statsmodels; the "
                "numpy backend supports seasonal differencing (D) only")
        if self.d > 2 or self.D > 1:
            raise NotImplementedError("numpy ARIMA supports d<=2, D<=1")

    def fit(self, y: np.ndarray) -> "_NumpyARIMA":
        y = np.asarray(y, np.float64).ravel()
        # differencing pipeline: seasonal first, then regular; tails of
        # every level are kept for inversion at forecast time
        self._season_tail = None
        w = y
        if self.D and self.s:
            self._season_tail = w[-self.s:].copy()
            w = w[self.s:] - w[:-self.s]
        self._level_tails = []
        for _ in range(self.d):
            self._level_tails.append(w[-1])
            w = np.diff(w)
        p, q = self.p, self.q
        need = max(p, q) + p + q + 8
        if len(w) < need:
            raise ValueError(
                f"series too short for ARIMA{(p, self.d, q)}: {len(w)} "
                f"points after differencing, need >= {need}")
        if q:
            # stage 1: long-AR residuals
            p_long = min(max(p + q + 3, 10), len(w) // 3)
            e = np.zeros_like(w)
            X = np.column_stack(
                [np.ones(len(w) - p_long)]
                + [w[p_long - i:len(w) - i] for i in range(1, p_long + 1)])
            beta, *_ = np.linalg.lstsq(X, w[p_long:], rcond=None)
            e[p_long:] = w[p_long:] - X @ beta
        else:
            e = np.zeros_like(w)
        # stage 2: OLS on [1, w lags, e lags]
        m = max(p, q)
        cols = [np.ones(len(w) - m)]
        cols += [w[m - i:len(w) - i] for i in range(1, p + 1)]
        cols += [e[m - j:len(w) - j] for j in range(1, q + 1)]
        X2 = np.column_stack(cols)
        beta, *_ = np.linalg.lstsq(X2, w[m:], rcond=None)
        self.const = beta[0]
        self.phi = beta[1:1 + p]
        self.theta = beta[1 + p:1 + p + q]
        resid = np.zeros_like(w)
        resid[m:] = w[m:] - X2 @ beta
        self._w_tail = w[len(w) - max(p, 1):].copy()
        self._e_tail = resid[len(resid) - max(q, 1):].copy()
        return self

    def forecast(self, horizon: int) -> np.ndarray:
        p, q = self.p, self.q
        w_hist = list(self._w_tail)
        e_hist = list(self._e_tail)
        out = []
        for _ in range(horizon):
            v = self.const
            for i in range(1, p + 1):
                v += self.phi[i - 1] * w_hist[-i]
            for j in range(1, q + 1):
                v += self.theta[j - 1] * e_hist[-j]
            out.append(v)
            w_hist.append(v)
            e_hist.append(0.0)  # future shocks: expectation zero
        f = np.asarray(out)
        # invert regular differencing (innermost level first)
        for last in reversed(self._level_tails):
            f = last + np.cumsum(f)
        # invert seasonal differencing
        if self._season_tail is not None:
            s = self.s
            vals = list(self._season_tail)
            inv = []
            for k, fv in enumerate(f):
                inv.append(vals[k] + fv)
                vals.append(inv[-1])
            f = np.asarray(inv)
        return f


class ARIMAForecaster:
    """ARIMA via statsmodels when importable, else the pure-numpy
    Hannan–Rissanen backend (reference: chronos/model/arima.py — pmdarima,
    likewise an optional dep there)."""

    def __init__(self, order: Tuple[int, int, int] = (1, 0, 0),
                 seasonal_order: Tuple[int, int, int, int] = (0, 0, 0, 0),
                 backend: str = "auto"):
        """``backend``: "auto" (statsmodels if importable), "statsmodels",
        or "numpy"."""
        if backend not in ("auto", "statsmodels", "numpy"):
            raise ValueError(
                f"backend must be 'auto', 'statsmodels' or 'numpy', got "
                f"{backend!r}")
        if backend == "auto":
            try:
                from statsmodels.tsa.arima.model import ARIMA  # noqa: F401
                backend = "statsmodels"
            except ImportError:
                backend = "numpy"
        if backend == "statsmodels":
            from statsmodels.tsa.arima.model import ARIMA  # noqa: F401
        self.backend = backend
        self.order = order
        self.seasonal_order = seasonal_order
        self._fitted = None

    def fit(self, data: np.ndarray) -> "ARIMAForecaster":
        if self.backend == "statsmodels":
            from statsmodels.tsa.arima.model import ARIMA
            self._fitted = ARIMA(np.asarray(data, np.float64),
                                 order=self.order,
                                 seasonal_order=self.seasonal_order).fit()
        else:
            self._fitted = _NumpyARIMA(self.order,
                                       self.seasonal_order).fit(data)
        return self

    def predict(self, horizon: int = 1) -> np.ndarray:
        if self._fitted is None:
            raise ValueError("fit first")
        return np.asarray(self._fitted.forecast(horizon))

    def evaluate(self, y_true: np.ndarray, horizon: Optional[int] = None
                 ) -> Dict[str, float]:
        pred = self.predict(horizon or len(y_true))
        err = pred - np.asarray(y_true)
        return {"mse": float(np.mean(err ** 2)),
                "mae": float(np.mean(np.abs(err)))}


class _NumpyProphet:
    """Prophet-style decomposable model via ridge regression: piecewise-
    linear trend (changepoint basis, L2 on slope changes) + Fourier
    seasonalities — Prophet's own model family (Taylor & Letham 2017),
    fitted as a linear system instead of Stan MAP.  Exists so
    ProphetForecaster EXECUTES in images without the prophet package."""

    def __init__(self, n_changepoints: int = 25,
                 changepoint_range: float = 0.8,
                 yearly_order: int = 10, weekly_order: int = 3,
                 daily_order: int = 4, reg: float = 10.0,
                 force_seasons: Sequence[str] = ()):
        self.n_changepoints = n_changepoints
        self.changepoint_range = changepoint_range
        self.orders = {"yearly": (365.25, yearly_order),
                       "weekly": (7.0, weekly_order),
                       "daily": (1.0, daily_order)}
        # explicitly requested components are fitted regardless of span
        # (Prophet semantics: an explicit True overrides the auto gate)
        self.force_seasons = set(force_seasons)
        self.reg = reg

    def _design(self, t_days: np.ndarray) -> np.ndarray:
        cols = [np.ones_like(t_days), t_days]
        for cp in self._cps:
            cols.append(np.maximum(t_days - cp, 0.0))  # slope change
        for period, order in self._active:
            for k in range(1, order + 1):
                ang = 2 * np.pi * k * t_days / period
                cols.append(np.sin(ang))
                cols.append(np.cos(ang))
        return np.column_stack(cols)

    def fit(self, ds: np.ndarray, y: np.ndarray) -> "_NumpyProphet":
        import pandas as pd
        ds = pd.to_datetime(pd.Series(ds))
        order = np.argsort(ds.to_numpy())  # prophet sorts history too
        ds = ds.iloc[order].reset_index(drop=True)
        y = np.asarray(y, np.float64)[order]
        self._t0 = ds.iloc[0]
        t = (ds - self._t0).dt.total_seconds().to_numpy() / 86400.0
        span = t[-1] - t[0]
        # Prophet-style auto seasonality: enable a component if the
        # history covers >= 2 of its periods OR it was explicitly forced
        self._active = [po for name, po in self.orders.items()
                        if po[1] > 0
                        and (span >= 2 * po[0]
                             or name in self.force_seasons)]
        hi = t[0] + self.changepoint_range * span
        self._cps = np.linspace(t[0], hi, self.n_changepoints + 2)[1:-1]
        X = self._design(t)
        self._y_mean, self._y_scale = y.mean(), max(y.std(), 1e-9)
        ys = (y - self._y_mean) / self._y_scale
        # ridge: no penalty on intercept/base slope, L2 on changepoint
        # deltas (Prophet's Laplace prior, L2 here) and seasonal coefs
        pen = np.zeros(X.shape[1])
        pen[2:2 + len(self._cps)] = self.reg
        pen[2 + len(self._cps):] = 1.0
        A = X.T @ X + np.diag(pen)
        self._beta = np.linalg.solve(A, X.T @ ys)
        return self

    def predict(self, ds_future: np.ndarray) -> np.ndarray:
        import pandas as pd
        ds = pd.to_datetime(pd.Series(ds_future))
        t = (ds - self._t0).dt.total_seconds().to_numpy() / 86400.0
        yhat = self._design(t) @ self._beta
        return yhat * self._y_scale + self._y_mean


def _prophet_kwargs_to_numpy(kwargs: Dict[str, Any]) -> Dict[str, Any]:
    """Translate standard Prophet constructor kwargs for _NumpyProphet;
    unknown/unsupported kwargs raise a clear error instead of a TypeError
    deep inside fit."""
    season_default = {"yearly": 10, "weekly": 3, "daily": 4}
    out: Dict[str, Any] = {}
    for k, v in kwargs.items():
        if k in ("n_changepoints", "changepoint_range"):
            out[k] = v
        elif k in ("yearly_seasonality", "weekly_seasonality",
                   "daily_seasonality"):
            name = k.split("_")[0]
            if v == "auto":
                continue  # keep the span-based auto default
            order = (season_default[name] if v is True
                     else 0 if v is False else int(v))
            out[f"{name}_order"] = order
            if order > 0:  # explicit request overrides the span gate
                out.setdefault("force_seasons", [])
                out["force_seasons"].append(name)
        else:
            raise ValueError(
                f"prophet kwarg {k!r} is not supported by the numpy "
                "fallback backend (supported: n_changepoints, "
                "changepoint_range, yearly/weekly/daily_seasonality); "
                "install prophet for the full parameter surface")
    return out


class ProphetForecaster:
    """Prophet when importable, else a pure-numpy decomposable-model
    backend (piecewise-linear trend + Fourier seasonality, ridge-fitted) —
    it always executes (reference: chronos/model/prophet.py wrapped the
    optional prophet package)."""

    def __init__(self, backend: str = "auto", **prophet_kwargs: Any):
        if backend not in ("auto", "prophet", "numpy"):
            raise ValueError(
                f"backend must be 'auto', 'prophet' or 'numpy', got "
                f"{backend!r}")
        if backend == "auto":
            try:
                from prophet import Prophet  # noqa: F401
                backend = "prophet"
            except ImportError:
                backend = "numpy"
        if backend == "prophet":
            try:
                from prophet import Prophet  # noqa: F401
            except ImportError as e:
                raise ImportError(
                    "backend='prophet' requires the optional 'prophet' "
                    "package (use backend='auto'/'numpy' for the built-in "
                    "fallback)") from e
        self.backend = backend
        self.kwargs = prophet_kwargs
        if backend == "numpy":
            # fail at construction, not deep inside fit
            _prophet_kwargs_to_numpy(prophet_kwargs)
        self._m = None
        self._last_ds = None

    def fit(self, df) -> "ProphetForecaster":
        """``df``: Prophet-convention DataFrame with ``ds`` and ``y``."""
        import pandas as pd
        if self.backend == "prophet":
            from prophet import Prophet
            self._m = Prophet(**self.kwargs)
            self._m.fit(df)
        else:
            kw = _prophet_kwargs_to_numpy(self.kwargs)
            self._m = _NumpyProphet(**kw).fit(
                df["ds"].to_numpy(), df["y"].to_numpy())
        self._last_ds = pd.to_datetime(df["ds"]).max()
        return self

    def predict(self, horizon: int = 1, freq: str = "D"):
        import pandas as pd
        if self._m is None:
            raise ValueError("fit first")
        if self.backend == "prophet":
            future = self._m.make_future_dataframe(periods=horizon,
                                                   freq=freq)
            return self._m.predict(future).tail(horizon)
        future_ds = pd.date_range(self._last_ds, periods=horizon + 1,
                                  freq=freq)[1:]
        yhat = self._m.predict(future_ds.to_numpy())
        return pd.DataFrame({"ds": future_ds, "yhat": yhat})
