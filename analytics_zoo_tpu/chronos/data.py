"""TSDataset (reference: pyzoo/zoo/chronos/data/tsdataset.py).

Pandas-based container with the reference's method chain: impute,
deduplicate, resample, scale/unscale_numpy, gen_dt_feature, roll → numpy
(x, y) windows.  Pure host-side feature engineering; arrays feed the
jit-compiled forecasters.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

_DT_FEATURES = ["HOUR", "DAY", "DAYOFWEEK", "MONTH", "DAYOFYEAR",
                "WEEKOFYEAR", "MINUTE", "IS_WEEKEND"]


class TSDataset:
    def __init__(self, df: pd.DataFrame, dt_col: str,
                 target_col: Sequence[str], id_col: Optional[str] = None,
                 extra_feature_col: Optional[Sequence[str]] = None):
        self.df = df.copy()
        self.dt_col = dt_col
        self.target_col = ([target_col] if isinstance(target_col, str)
                           else list(target_col))
        self.id_col = id_col
        self.feature_col = list(extra_feature_col or [])
        self.scaler = None
        self._scaler_cols: List[str] = []
        self.df[dt_col] = pd.to_datetime(self.df[dt_col])
        self.df.sort_values(dt_col, inplace=True)
        self.df.reset_index(drop=True, inplace=True)

    # -- constructors ---------------------------------------------------------

    @staticmethod
    def from_pandas(df: pd.DataFrame, dt_col: str,
                    target_col: Union[str, Sequence[str]],
                    id_col: Optional[str] = None,
                    extra_feature_col: Optional[Sequence[str]] = None,
                    with_split: bool = False, val_ratio: float = 0.0,
                    test_ratio: float = 0.1):
        """Reference API; with_split returns (train, val, test) datasets."""
        ds = TSDataset(df, dt_col, target_col, id_col, extra_feature_col)
        if not with_split:
            return ds
        n = len(ds.df)
        n_test = int(n * test_ratio)
        n_val = int(n * val_ratio)
        n_train = n - n_val - n_test
        parts = (ds.df.iloc[:n_train], ds.df.iloc[n_train:n_train + n_val],
                 ds.df.iloc[n_train + n_val:])
        return tuple(
            TSDataset(p, dt_col, target_col, id_col, extra_feature_col)
            for p in parts)

    # -- cleaning -------------------------------------------------------------

    def impute(self, mode: str = "last") -> "TSDataset":
        cols = self.target_col + self.feature_col
        if mode == "last":
            self.df[cols] = self.df[cols].ffill().bfill()
        elif mode == "const":
            self.df[cols] = self.df[cols].fillna(0)
        elif mode == "linear":
            self.df[cols] = self.df[cols].interpolate(
                method="linear", limit_direction="both")
        else:
            raise ValueError(f"unknown impute mode {mode!r}")
        return self

    def deduplicate(self) -> "TSDataset":
        self.df.drop_duplicates(subset=[self.dt_col], keep="last",
                                inplace=True)
        self.df.reset_index(drop=True, inplace=True)
        return self

    def resample(self, interval: str, merge_mode: str = "mean") -> "TSDataset":
        num = self.target_col + self.feature_col
        g = self.df.set_index(self.dt_col)[num].resample(interval)
        agg = getattr(g, merge_mode)()
        keep = self.df.drop(columns=num).set_index(self.dt_col).resample(
            interval).first()
        self.df = pd.concat([agg, keep], axis=1).reset_index()
        return self

    # -- features -------------------------------------------------------------

    def gen_dt_feature(self, features: Optional[Sequence[str]] = None
                       ) -> "TSDataset":
        feats = [f.upper() for f in (features or
                                     ["HOUR", "DAYOFWEEK", "MONTH",
                                      "IS_WEEKEND"])]
        dt = self.df[self.dt_col].dt
        gens = {
            "HOUR": dt.hour, "DAY": dt.day, "DAYOFWEEK": dt.dayofweek,
            "MONTH": dt.month, "DAYOFYEAR": dt.dayofyear,
            "WEEKOFYEAR": dt.isocalendar().week.astype(np.int64),
            "MINUTE": dt.minute,
            "IS_WEEKEND": (dt.dayofweek >= 5).astype(np.int64),
        }
        for f in feats:
            if f not in gens:
                raise ValueError(f"unknown dt feature {f!r}; "
                                 f"known: {_DT_FEATURES}")
            self.df[f] = np.asarray(gens[f])
            if f not in self.feature_col:
                self.feature_col.append(f)
        return self

    # -- scaling --------------------------------------------------------------

    def scale(self, scaler: Any = "standard", fit: bool = True) -> "TSDataset":
        """scaler: "standard"/"minmax" or a fitted dict from another split."""
        cols = self.target_col + self.feature_col
        if isinstance(scaler, str):
            if fit:
                if scaler == "standard":
                    mean = self.df[cols].mean()
                    std = self.df[cols].std().replace(0, 1.0)
                    self.scaler = {"type": "standard", "mean": mean,
                                   "std": std}
                elif scaler == "minmax":
                    mn, mx = self.df[cols].min(), self.df[cols].max()
                    rng = (mx - mn).replace(0, 1.0)
                    self.scaler = {"type": "minmax", "min": mn, "range": rng}
                else:
                    raise ValueError(f"unknown scaler {scaler!r}")
            elif self.scaler is None:
                raise ValueError("fit=False requires a previously fit scaler")
        else:
            self.scaler = scaler
        s = self.scaler
        self._scaler_cols = cols
        if s["type"] == "standard":
            self.df[cols] = (self.df[cols] - s["mean"]) / s["std"]
        else:
            self.df[cols] = (self.df[cols] - s["min"]) / s["range"]
        return self

    def unscale_numpy(self, arr: np.ndarray) -> np.ndarray:
        """Invert the target-col part of the scaler on a rolled y array
        [N, horizon, n_targets]."""
        if self.scaler is None:
            return arr
        s = self.scaler
        n_t = len(self.target_col)
        if s["type"] == "standard":
            mean = s["mean"][self.target_col].to_numpy()[:n_t]
            std = s["std"][self.target_col].to_numpy()[:n_t]
            return arr * std + mean
        mn = s["min"][self.target_col].to_numpy()[:n_t]
        rng = s["range"][self.target_col].to_numpy()[:n_t]
        return arr * rng + mn

    # -- windowing ------------------------------------------------------------

    def roll(self, lookback: int, horizon: Union[int, Sequence[int]],
             feature_col: Optional[Sequence[str]] = None,
             target_col: Optional[Sequence[str]] = None) -> "TSDataset":
        """Sliding windows → self._x [N, lookback, F], self._y
        [N, horizon, T] (reference returns via to_numpy())."""
        targets = list(target_col or self.target_col)
        feats = list(feature_col if feature_col is not None
                     else self.feature_col)
        cols = targets + [f for f in feats if f not in targets]
        # int horizon = all steps 1..h (reference semantics); a list selects
        # specific future offsets
        horizons = (list(range(1, horizon + 1)) if isinstance(horizon, int)
                    else list(horizon))
        h_max = max(horizons)
        hsel = np.asarray(horizons) - 1

        def roll_one(frame: pd.DataFrame):
            values = frame[cols].to_numpy(np.float32)
            tgt = frame[targets].to_numpy(np.float32)
            n = len(values) - lookback - h_max + 1
            if n <= 0:
                return None
            idx = np.arange(lookback)[None, :] + np.arange(n)[:, None]
            yidx = np.arange(n)[:, None] + lookback + hsel[None, :]
            return values[idx], tgt[yidx]

        if self.id_col is not None:
            # multi-series: windows must NEVER span two ids (reference
            # grouped by id before rolling)
            parts = [roll_one(g.sort_values(self.dt_col))
                     for _, g in self.df.groupby(self.id_col, sort=False)]
            parts = [p for p in parts if p is not None]
            if not parts:
                raise ValueError("every id-series is too short for "
                                 f"lookback {lookback} + horizon {h_max}")
            self._x = np.concatenate([p[0] for p in parts])
            self._y = np.concatenate([p[1] for p in parts])
        else:
            out = roll_one(self.df)
            if out is None:
                raise ValueError(
                    f"series of {len(self.df)} rows too short for lookback "
                    f"{lookback} + horizon {h_max}")
            self._x, self._y = out
        return self

    def to_numpy(self) -> Tuple[np.ndarray, np.ndarray]:
        if not hasattr(self, "_x"):
            raise ValueError("call roll() first")
        return self._x, self._y

    def to_feed(self, batch_size: int = 32, shuffle: bool = True,
                **kw: Any):
        """Rolled windows → a device DataFeed (reference:
        TSDataset.to_torch_data_loader — the train-loader bridge)."""
        from analytics_zoo_tpu.data import DataFeed
        x, y = self.to_numpy()
        return DataFeed.from_arrays(x, y, batch_size, shuffle=shuffle, **kw)

    def to_torch_data_loader(self, batch_size: int = 32,
                             shuffle: bool = True):
        """Rolled windows as a ``torch.utils.data.DataLoader`` (reference:
        TSDataset.to_torch_data_loader) — for porting torch training loops
        unchanged; native training uses :meth:`to_feed`."""
        import torch
        from torch.utils.data import DataLoader, TensorDataset
        x, y = self.to_numpy()
        ds = TensorDataset(torch.as_tensor(x), torch.as_tensor(y))
        return DataLoader(ds, batch_size=batch_size, shuffle=shuffle)

    def to_pandas(self) -> pd.DataFrame:
        return self.df.copy()
