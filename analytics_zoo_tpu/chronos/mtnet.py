"""MTNetForecaster: memory time-series network.

Reference (SURVEY.md §2.6): ``pyzoo/zoo/chronos/model/MTNet_keras.py`` —
MTNet (Chang et al. 2018): a long history is split into ``long_series_num``
memory blocks of ``series_length`` steps; a CNN+RNN encoder embeds each
block and the short-term window; attention over the memory embeddings
against the short-term embedding forms a context; an autoregressive linear
highway over the raw recent targets is added to the nonlinear output.

TPU-native: one encoder applied to all blocks at once by folding the block
axis into the batch (shared weights with no parameter duplication, and one
big MXU-friendly conv/rnn instead of ``long_num`` small ones).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Scope
from .forecaster import _Forecaster


class _MTNet(Module):
    def __init__(self, long_num: int = 4, time_step: int = 8,
                 cnn_hid_size: int = 32, rnn_hid_size: int = 32,
                 cnn_kernel_size: int = 3, ar_window: int = 4,
                 dropout: float = 0.1, output_dim: int = 1,
                 horizon: int = 1):
        super().__init__()
        self.long_num = long_num
        self.time_step = time_step
        self.cnn_hid = cnn_hid_size
        self.rnn_hid = rnn_hid_size
        self.k = cnn_kernel_size
        self.ar_window = ar_window
        self.dropout = dropout
        self.output_dim = output_dim
        self.horizon = horizon

    def forward(self, scope: Scope, x: jnp.ndarray) -> jnp.ndarray:
        b, total, f = x.shape
        ln, t = self.long_num, self.time_step
        if total != (ln + 1) * t:
            raise ValueError(
                f"MTNet needs past_seq_len == (long_num+1)*time_step = "
                f"{(ln + 1) * t}, got {total}")
        # memory blocks + the short-term window, folded into the batch so
        # ONE encoder embeds all of them with shared weights
        blocks = x.reshape(b * (ln + 1), t, f)
        h = scope.child(nn.Conv1D(self.cnn_hid, self.k, padding="same",
                                  activation="relu"), blocks, name="enc_cnn")
        h = scope.child(nn.Dropout(self.dropout), h, name="enc_drop")
        h = scope.child(nn.GRU(self.rnn_hid, return_sequences=False), h,
                        name="enc_rnn")                    # [B*(ln+1), H]
        h = h.reshape(b, ln + 1, self.rnn_hid)
        memory, short = h[:, :ln], h[:, ln]                # [B,ln,H], [B,H]
        # attention of the short-term embedding over memory blocks
        wq = scope.param("attn_w", nn.initializers.get("glorot_uniform"),
                         (self.rnn_hid, self.rnn_hid))
        scores = jnp.einsum("blh,hk,bk->bl", memory, wq, short)
        attn = jax.nn.softmax(scores, axis=-1)
        context = jnp.einsum("bl,blh->bh", attn, memory)   # [B, H]
        combined = jnp.concatenate([context, short], axis=-1)
        out = scope.child(nn.Dense(self.horizon * self.output_dim), combined,
                          name="head")
        out = out.reshape(b, self.horizon, self.output_dim)
        # autoregressive highway on the recent raw targets (first
        # output_dim features are the targets, TSDataset.roll's layout)
        ar_in = x[:, -self.ar_window:, : self.output_dim]  # [B, ar, D]
        ar_in = jnp.swapaxes(ar_in, 1, 2).reshape(b * self.output_dim,
                                                  self.ar_window)
        ar = scope.child(nn.Dense(self.horizon, use_bias=False), ar_in,
                         name="ar")
        ar = ar.reshape(b, self.output_dim, self.horizon)
        return out + jnp.swapaxes(ar, 1, 2)


class MTNetForecaster(_Forecaster):
    """Reference API: MTNetForecaster(target_dim, feature_dim,
    long_series_num, series_length, ...) with fit/predict/evaluate/save/
    load via the unified estimator.  ``past_seq_len`` must equal
    (long_series_num + 1) * series_length."""

    MODEL_CLS = _MTNet

    def __init__(self, past_seq_len: int, future_seq_len: int,
                 input_feature_num: int, output_feature_num: int,
                 long_series_num: int = 4, series_length: int = 0,
                 **kwargs: Any):
        if series_length == 0:
            if past_seq_len % (long_series_num + 1):
                raise ValueError(
                    f"past_seq_len {past_seq_len} not divisible into "
                    f"{long_series_num}+1 blocks; pass series_length")
            series_length = past_seq_len // (long_series_num + 1)
        kwargs.setdefault("ar_window", min(4, series_length))
        super().__init__(past_seq_len, future_seq_len, input_feature_num,
                         output_feature_num, long_num=long_series_num,
                         time_step=series_length, **kwargs)
