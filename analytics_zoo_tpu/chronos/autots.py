"""AutoTS (reference: pyzoo/zoo/chronos/autots — AutoTSEstimator searching
model type + hyperparams + lookback via Tune; result wrapped as TSPipeline
with save/load).

TPU-native: search runs on the automl package (no Ray); the model space is
{lstm, seq2seq, tcn}; lookback may itself be a search dimension (re-rolling
the TSDataset per trial, as the reference did).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.automl import hp as hp_mod
from analytics_zoo_tpu.automl.search import (ASHAScheduler,
                                             RandomSearchEngine, StopTrial)
from .forecaster import (LSTMForecaster, Seq2SeqForecaster, TCNForecaster)

_MODELS = {"lstm": LSTMForecaster, "seq2seq": Seq2SeqForecaster,
           "tcn": TCNForecaster}


def _target_scaler(tsdata) -> Optional[Dict[str, Any]]:
    """Compact, json-able slice of a TSDataset scaler covering the target
    columns only (what predictions need for unscaling)."""
    s = getattr(tsdata, "scaler", None)
    if s is None:
        return None
    cols = tsdata.target_col
    if s["type"] == "standard":
        return {"type": "standard",
                "mean": [float(v) for v in s["mean"][cols]],
                "std": [float(v) for v in s["std"][cols]]}
    return {"type": "minmax",
            "min": [float(v) for v in s["min"][cols]],
            "range": [float(v) for v in s["range"][cols]]}


class TSPipeline:
    """Fitted forecaster + the fitted target scaler: predict/evaluate/save/
    load.  Predictions are returned in the ORIGINAL (unscaled) space when a
    scaler is present, matching the reference TSPipeline (SURVEY.md §2.6)."""

    def __init__(self, forecaster, config: Dict[str, Any],
                 scaler: Optional[Dict[str, Any]] = None):
        self.forecaster = forecaster
        self.config = config
        self.scaler = scaler

    def _unscale(self, arr: np.ndarray) -> np.ndarray:
        s = self.scaler
        if s is None:
            return arr
        if s["type"] == "standard":
            return arr * np.asarray(s["std"]) + np.asarray(s["mean"])
        return arr * np.asarray(s["range"]) + np.asarray(s["min"])

    def predict(self, x: np.ndarray, unscale: bool = True) -> np.ndarray:
        pred = self.forecaster.predict(x)
        return self._unscale(pred) if unscale else pred

    def evaluate(self, data) -> Dict[str, float]:
        """Metrics in the original space when a scaler is present (x and y
        are still expected in the scaled space the model was trained on)."""
        if self.scaler is None:
            return self.forecaster.evaluate(data)
        x, y = data.to_numpy() if hasattr(data, "to_numpy") else data
        pred = self.predict(x)
        truth = self._unscale(np.asarray(y))
        err = pred - truth
        return {"mse": float(np.mean(err ** 2)),
                "mae": float(np.mean(np.abs(err)))}

    def save(self, path: str) -> str:
        os.makedirs(path, exist_ok=True)
        self.forecaster.save(os.path.join(path, "model"))

        def jsonable(v) -> bool:
            if isinstance(v, (int, float, str, bool, type(None))):
                return True
            if isinstance(v, (list, tuple)):
                return all(jsonable(x) for x in v)
            if isinstance(v, dict):  # model_kwargs must survive the trip
                return all(isinstance(k, str) and jsonable(x)
                           for k, x in v.items())
            return False

        payload = {k: v for k, v in self.config.items() if jsonable(v)}
        if self.scaler is not None:
            payload["__scaler__"] = self.scaler
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(payload, f)
        return path

    @staticmethod
    def load(path: str) -> "TSPipeline":
        with open(os.path.join(path, "config.json")) as f:
            config = json.load(f)
        scaler = config.pop("__scaler__", None)
        model_cls = _MODELS[config["model"]]
        fc = model_cls(
            past_seq_len=config["past_seq_len"],
            future_seq_len=config["future_seq_len"],
            input_feature_num=config["input_feature_num"],
            output_feature_num=config["output_feature_num"],
            **config.get("model_kwargs", {}))
        # initialize then load weights
        fc.est.load(os.path.join(path, "model"))
        return TSPipeline(fc, config, scaler=scaler)


class AutoTSEstimator:
    def __init__(self, model: Any = "lstm",
                 search_space: Optional[Dict[str, Any]] = None,
                 past_seq_len: Any = 24, future_seq_len: int = 1,
                 metric: str = "mse", metric_mode: str = "min",
                 seed: int = 0):
        """``model``: name, list of names, or hp.choice over names."""
        if isinstance(model, str):
            model = [model]
        self.model_space = (model if isinstance(model, hp_mod.Sampler)
                            else hp_mod.choice(list(model)))
        self.search_space = dict(search_space or {})
        self.past_seq_len = past_seq_len
        self.future_seq_len = future_seq_len
        self.metric = metric
        self.metric_mode = metric_mode
        self.seed = seed
        self.best_config: Optional[Dict[str, Any]] = None

    def fit(self, data, validation_data=None, epochs: int = 2,
            batch_size: int = 32, n_sampling: int = 4,
            scheduler: Optional[ASHAScheduler] = None,
            max_concurrent: Optional[int] = None) -> TSPipeline:
        """``data``: a TSDataset (re-rolled per lookback candidate) or a
        rolled (x, y) tuple.  ``max_concurrent``: parallel trials (thread
        pool; XLA releases the GIL during compute)."""
        from .data import TSDataset
        is_tsdata = isinstance(data, TSDataset)
        space = dict(self.search_space)
        space["model"] = self.model_space
        if isinstance(self.past_seq_len, hp_mod.Sampler):
            space["past_seq_len"] = self.past_seq_len
        engine = RandomSearchEngine(metric_mode=self.metric_mode,
                                    scheduler=scheduler,
                                    max_concurrent=max_concurrent or 1,
                                    seed=self.seed)

        import threading
        roll_lock = threading.Lock()  # concurrent trials share `data`:
        # roll() mutates the dataset's window state, so window extraction
        # must be atomic per trial (the arrays it returns are fresh copies)

        def make(config: Dict[str, Any]):
            cfg = dict(config)
            name = cfg.pop("model")
            lookback = int(cfg.pop("past_seq_len", self.past_seq_len))
            lr = cfg.pop("lr", 1e-3)
            if is_tsdata:
                with roll_lock:
                    data.roll(lookback, self.future_seq_len)
                    x, y = data.to_numpy()
            else:
                x, y = data
                lookback = x.shape[1]
            fc = _MODELS[name](past_seq_len=lookback,
                               future_seq_len=self.future_seq_len,
                               input_feature_num=x.shape[-1],
                               output_feature_num=y.shape[-1], lr=lr,
                               metrics=[self.metric] if self.metric != "loss"
                               else ("mse",), **cfg)
            return fc, (x, y), dict(config)

        def trial_fn(config, report):
            fc, (x, y), _ = make(config)
            if validation_data is not None:
                if isinstance(validation_data, TSDataset):
                    # re-roll per trial: each candidate lookback needs its
                    # own validation windows (same lock as `data`)
                    with roll_lock:
                        validation_data.roll(fc.past_seq_len,
                                             self.future_seq_len)
                        vx, vy = validation_data.to_numpy()
                else:
                    vx, vy = validation_data
            else:
                n_val = max(1, len(x) // 5)
                vx, vy = x[-n_val:], y[-n_val:]
                x, y = x[:-n_val], y[:-n_val]
            best = None
            for epoch in range(epochs):
                fc.fit((x, y), epochs=1,
                       batch_size=min(batch_size, len(x)))
                m = fc.evaluate((vx, vy),
                                batch_size=min(batch_size, len(vx)))
                m = m.get(self.metric, m["loss"])
                if best is None or (m < best if self.metric_mode == "min"
                                    else m > best):
                    best = m
                report(m, epoch + 1)
            return best

        best = engine.run(trial_fn, space, n_trials=n_sampling)
        self.best_config = dict(best.config)
        self.trials = engine.trials
        # refit winner on the full data
        fc, (x, y), raw_cfg = make(dict(best.config))
        fc.fit((x, y), epochs=epochs, batch_size=min(batch_size, len(x)))
        cfg = dict(raw_cfg)
        cfg.update(model=best.config["model"],
                   past_seq_len=fc.past_seq_len,
                   future_seq_len=self.future_seq_len,
                   input_feature_num=fc.input_feature_num,
                   output_feature_num=fc.output_feature_num,
                   model_kwargs={k: v for k, v in raw_cfg.items()
                                 if k not in ("model", "past_seq_len", "lr",
                                              "batch_size")})
        return TSPipeline(fc, cfg,
                          scaler=_target_scaler(data) if is_tsdata else None)

    def get_best_config(self) -> Dict[str, Any]:
        if self.best_config is None:
            raise ValueError("call fit() first")
        return dict(self.best_config)


class _SingleModelAuto(AutoTSEstimator):
    """Per-model HPO wrapper (reference: AutoLSTM/AutoTCN/AutoSeq2Seq in
    pyzoo/zoo/chronos/autots/model/) — an AutoTSEstimator with the model
    family fixed, searching only hyperparameters (+ lookback if given as
    a space)."""

    MODEL_NAME: str = ""

    def __init__(self, **kwargs: Any):
        if "model" in kwargs:
            raise ValueError(
                f"{type(self).__name__} searches the "
                f"{self.MODEL_NAME!r} family only; use AutoTSEstimator "
                "to search across model types")
        super().__init__(model=[self.MODEL_NAME], **kwargs)


class AutoLSTM(_SingleModelAuto):
    MODEL_NAME = "lstm"


class AutoTCN(_SingleModelAuto):
    MODEL_NAME = "tcn"


class AutoSeq2Seq(_SingleModelAuto):
    MODEL_NAME = "seq2seq"
