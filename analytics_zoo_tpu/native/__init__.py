"""Native (C++) runtime: bounded MPMC byte queue + batch assembly.

Reference parity (SURVEY.md §2.10): the reference's host data plane was
native (BlockManager/plasma/Redis/PMEM behind JNI).  Here the equivalent —
the queueing/synchronization under data prefetch and serving batching — is
C++ (zoo_native.cpp), compiled on first import with g++ and loaded via
ctypes.  A pure-Python fallback (queue.Queue) keeps every feature working if
no compiler is available; ``NativeQueue.is_native`` reports which is active.
"""

from __future__ import annotations

import atexit
import ctypes
import logging
import os
import queue as pyqueue
import subprocess
import threading
import weakref
from typing import Optional, Tuple

logger = logging.getLogger("analytics_zoo_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "zoo_native.cpp")
_SO = os.path.join(_HERE, "libzoonative.so")
_lib = None
_lib_lock = threading.Lock()


def _build(force: bool = False) -> Optional[str]:
    if (not force and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)):
        return _SO
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           _SRC, "-o", _SO + ".tmp"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return _SO
    except (OSError, subprocess.SubprocessError) as e:
        logger.warning("native build failed (%s); using Python fallback "
                       "queue", e)
        return None


def _load(so: str) -> Optional[ctypes.CDLL]:
    """dlopen, tolerating a STALE prebuilt .so (packaged artifact built
    against a different glibc/toolchain): rebuild from source once and
    retry; a second failure falls back to the Python queue instead of
    crashing every import of the serving stack."""
    try:
        return ctypes.CDLL(so)
    except OSError as e:
        logger.warning("stale native library %s (%s); rebuilding", so, e)
        so = _build(force=True)
        if so is None:
            return None
        try:
            return ctypes.CDLL(so)
        except OSError as e2:
            logger.warning("rebuilt native library failed to load (%s); "
                           "using Python fallback queue", e2)
            return None


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it on first use (None if
    unavailable — callers must fall back)."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib or None
        so = _build()
        if so is None:
            _lib = False
            return None
        lib = _load(so)
        if lib is None:
            _lib = False
            return None
        lib.zn_queue_create.restype = ctypes.c_void_p
        lib.zn_queue_create.argtypes = [ctypes.c_size_t, ctypes.c_size_t]
        lib.zn_queue_destroy.argtypes = [ctypes.c_void_p]
        lib.zn_queue_close.argtypes = [ctypes.c_void_p]
        lib.zn_queue_push.restype = ctypes.c_int
        lib.zn_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_size_t, ctypes.c_uint64,
                                      ctypes.c_int]
        lib.zn_queue_pop.restype = ctypes.c_longlong
        lib.zn_queue_pop.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_size_t,
                                     ctypes.POINTER(ctypes.c_uint64),
                                     ctypes.c_int]
        lib.zn_queue_next_size.restype = ctypes.c_size_t
        lib.zn_queue_next_size.argtypes = [ctypes.c_void_p]
        lib.zn_queue_len.restype = ctypes.c_size_t
        lib.zn_queue_len.argtypes = [ctypes.c_void_p]
        lib.zn_queue_pushed.restype = ctypes.c_uint64
        lib.zn_queue_pushed.argtypes = [ctypes.c_void_p]
        lib.zn_queue_popped.restype = ctypes.c_uint64
        lib.zn_queue_popped.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


# Every live queue, closed from an atexit hook: worker threads blocked in
# push/pop must wake and exit while the interpreter is still fully alive —
# a daemon thread returning from the (GIL-released) native call during
# interpreter teardown is a "Fatal Python error" crash.
_live_queues: "weakref.WeakSet[NativeQueue]" = weakref.WeakSet()


@atexit.register
def _close_all_queues() -> None:
    for q in list(_live_queues):
        try:
            q.close()
        except Exception:  # noqa: BLE001 — best-effort shutdown
            pass


class NativeQueue:
    """Bounded MPMC byte queue; C++-backed when the native lib builds."""

    def __init__(self, max_items: int = 0, max_bytes: int = 0):
        lib = get_lib()
        self._lib = lib
        if lib is not None:
            self._q = lib.zn_queue_create(max_items, max_bytes)
            self.is_native = True
        else:
            self._pyq = pyqueue.Queue(maxsize=max_items or 0)
            self.is_native = False
        self._closed = False
        _live_queues.add(self)

    # -- ops ------------------------------------------------------------------

    def push(self, payload: bytes, tag: int = 0,
             timeout: Optional[float] = None) -> bool:
        """False on timeout; raises if the queue is closed."""
        if self.is_native:
            rc = self._lib.zn_queue_push(
                self._q, payload, len(payload), tag,
                -1 if timeout is None else int(timeout * 1000))
            if rc == -2:
                raise RuntimeError("queue closed")
            return rc == 0
        # poll in short slices so close() can wake a blocked producer (the
        # C++ path gets this from the condvar broadcast in zn_queue_close)
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        while True:
            if self._closed:
                raise RuntimeError("queue closed")
            slice_t = 0.05
            if deadline is not None:
                left = deadline - _time.monotonic()
                if left <= 0:
                    return False
                slice_t = min(slice_t, left)
            try:
                self._pyq.put((payload, tag), timeout=slice_t)
                return True
            except pyqueue.Full:
                continue

    def pop(self, timeout: Optional[float] = None
            ) -> Optional[Tuple[bytes, int]]:
        """(payload, tag) or None on timeout; raises when closed+drained."""
        if self.is_native:
            tag = ctypes.c_uint64(0)
            size = self._lib.zn_queue_next_size(self._q)
            buf = ctypes.create_string_buffer(max(size, 1 << 16))
            while True:
                rc = self._lib.zn_queue_pop(
                    self._q, buf, len(buf), ctypes.byref(tag),
                    -1 if timeout is None else int(timeout * 1000))
                if rc == -3:        # distinct from a popped empty payload
                    return None
                if rc == -2:
                    raise RuntimeError("queue closed")
                if rc < 0:          # buffer too small: retry with exact size
                    buf = ctypes.create_string_buffer(-rc)
                    continue
                return buf.raw[:rc], tag.value
        try:
            item = self._pyq.get(timeout=timeout)
        except pyqueue.Empty:
            if self._closed:
                raise RuntimeError("queue closed") from None
            return None
        if item is None:
            raise RuntimeError("queue closed")
        return item

    def close(self) -> None:
        self._closed = True
        if self.is_native:
            self._lib.zn_queue_close(self._q)
        else:
            try:
                self._pyq.put_nowait(None)
            except pyqueue.Full:
                pass

    def __len__(self) -> int:
        if self.is_native:
            return int(self._lib.zn_queue_len(self._q))
        return self._pyq.qsize()

    def stats(self) -> Tuple[int, int]:
        if self.is_native:
            return (int(self._lib.zn_queue_pushed(self._q)),
                    int(self._lib.zn_queue_popped(self._q)))
        return (-1, -1)

    def __del__(self):
        try:
            if getattr(self, "is_native", False):
                self._lib.zn_queue_destroy(self._q)
        except Exception:  # noqa: BLE001 — interpreter shutdown
            pass
