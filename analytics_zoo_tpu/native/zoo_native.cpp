// Native runtime primitives for analytics_zoo_tpu.
//
// Reference parity (SURVEY.md §2.10): the reference's runtime data plane was
// native — Spark BlockManager (netty), Ray plasma, Redis, PMEM native arrays
// behind JNI.  The TPU-native equivalent is the host-side data plane that
// feeds the chip: a bounded MPMC byte-queue (prefetch pipelines, serving
// request batching) implemented in C++ with POSIX threads, exposed through a
// plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O3 -march=native -shared -fPIC zoo_native.cpp -o libzoonative.so
// (driven by analytics_zoo_tpu/native/__init__.py at first import).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

namespace {

struct Item {
  std::vector<uint8_t> data;
  uint64_t tag;
};

struct Queue {
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  std::deque<Item> items;
  size_t capacity_items;
  size_t capacity_bytes;
  size_t bytes = 0;
  std::atomic<bool> closed{false};
  // stats
  std::atomic<uint64_t> pushed{0};
  std::atomic<uint64_t> popped{0};
};

}  // namespace

extern "C" {

// ---- bounded MPMC byte queue ------------------------------------------------

void* zn_queue_create(size_t capacity_items, size_t capacity_bytes) {
  auto* q = new Queue();
  q->capacity_items = capacity_items ? capacity_items : SIZE_MAX;
  q->capacity_bytes = capacity_bytes ? capacity_bytes : SIZE_MAX;
  return q;
}

void zn_queue_destroy(void* qp) { delete static_cast<Queue*>(qp); }

void zn_queue_close(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  q->closed.store(true);
  std::lock_guard<std::mutex> lk(q->mu);
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

// returns: 0 ok, -1 timeout, -2 closed
int zn_queue_push(void* qp, const uint8_t* data, size_t len, uint64_t tag,
                  int timeout_ms) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto has_room = [&] {
    return (q->items.size() < q->capacity_items &&
            q->bytes + len <= q->capacity_bytes) || q->closed.load();
  };
  if (timeout_ms < 0) {
    q->not_full.wait(lk, has_room);
  } else if (!q->not_full.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                   has_room)) {
    return -1;
  }
  if (q->closed.load()) return -2;
  Item it;
  it.data.assign(data, data + len);
  it.tag = tag;
  q->bytes += len;
  q->items.push_back(std::move(it));
  q->pushed.fetch_add(1);
  q->not_empty.notify_one();
  return 0;
}

// Peek size of the next item without popping (0 if empty).
size_t zn_queue_next_size(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.empty() ? 0 : q->items.front().data.size();
}

// Pop into caller buffer.  Returns payload size (>= 0), -3 on timeout,
// -2 closed+empty.  A zero-length payload is a valid pop (returns 0), which
// is why timeout has its own code.  If the buffer is too small the item
// stays queued and -(needed) is returned (callers retry with a bigger
// buffer; needed is always > buflen >= 4, so it cannot collide with
// -2/-3).
long long zn_queue_pop(void* qp, uint8_t* buf, size_t buflen, uint64_t* tag,
                       int timeout_ms) {
  auto* q = static_cast<Queue*>(qp);
  std::unique_lock<std::mutex> lk(q->mu);
  auto has_item = [&] { return !q->items.empty() || q->closed.load(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lk, has_item);
  } else if (!q->not_empty.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                    has_item)) {
    return -3;
  }
  if (q->items.empty()) return -2;  // closed and drained
  Item& it = q->items.front();
  if (it.data.size() > buflen) return -(long long)it.data.size();
  size_t n = it.data.size();
  std::memcpy(buf, it.data.data(), n);
  if (tag) *tag = it.tag;
  q->bytes -= n;
  q->items.pop_front();
  q->popped.fetch_add(1);
  q->not_full.notify_one();
  return (long long)n;
}

size_t zn_queue_len(void* qp) {
  auto* q = static_cast<Queue*>(qp);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

uint64_t zn_queue_pushed(void* qp) {
  return static_cast<Queue*>(qp)->pushed.load();
}

uint64_t zn_queue_popped(void* qp) {
  return static_cast<Queue*>(qp)->popped.load();
}

// ---- fast batch assembly ----------------------------------------------------
// Stack n_rows row-major float32 rows (each row_len floats, given as an array
// of pointers) into one contiguous [n_rows, row_len] buffer.  This is the hot
// host-side op when assembling a serving micro-batch from many requests.

void zn_stack_rows_f32(const float** rows, size_t n_rows, size_t row_len,
                       float* out) {
  for (size_t i = 0; i < n_rows; ++i) {
    std::memcpy(out + i * row_len, rows[i], row_len * sizeof(float));
  }
}

}  // extern "C"
