"""NNImageReader: image directory → DataFrame with decoded image column.

Reference: ``pyzoo/zoo/pipeline/nnframes/nn_image_reader.py`` —
``NNImageReader.readImages(path, sc)`` produced a Spark DataFrame with an
``image`` struct column (origin/height/width/nChannels/mode/data) consumed
by NNEstimator via ImageFeatureToTensor preprocessing.

TPU-native: a pandas frame whose ``image`` column holds decoded HWC
float32 ndarrays (the struct fields live as plain columns), reusing the
data.image decode + transform chain.  Feeds NNEstimator directly —
``setFeaturesCol("image")``.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np


class NNImageReader:
    @staticmethod
    def readImages(path: str, transforms: Optional[Sequence[Callable]] = None,
                   with_label: bool = True):
        """Read a directory (class-per-subdir when ``with_label``) into a
        pandas DataFrame with columns: image (HWC ndarray), origin (path),
        height, width, n_channels, and label when present."""
        import pandas as pd

        from analytics_zoo_tpu.data.image import ImageSet, apply_chain, \
            decode_image

        iset = ImageSet.read(path, with_label=with_label)
        rows = []
        for i, p in enumerate(iset.paths):
            img = apply_chain(decode_image(p), list(transforms or []))
            if img.dtype.kind in "ui":
                # decode yields uint8; models need float activations (a
                # uint8 feed would truncate every conv/dense output)
                img = img.astype(np.float32)
            row = {"image": img, "origin": p, "height": img.shape[0],
                   "width": img.shape[1],
                   "n_channels": img.shape[2] if img.ndim == 3 else 1}
            if iset.labels is not None:
                row["label"] = int(iset.labels[i])
            rows.append(row)
        return pd.DataFrame(rows)
