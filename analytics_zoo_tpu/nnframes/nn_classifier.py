"""NNEstimator / NNModel / NNClassifier over pandas frames.

Reference: ``pyzoo/zoo/pipeline/nnframes/nn_classifier.py`` —
``NNEstimator(model, criterion, preprocessing).setBatchSize(...)
.setMaxEpoch(...).fit(df)`` → ``NNModel`` with ``transform(df)``.

The reference's ``Preprocessing`` hierarchy (SeqToTensor, ArrayToTensor,
ImageFeatureToTensor, ...) existed to marshal JVM Row objects into BigDL
Tensors.  Here a row is already a numpy-friendly value, so "preprocessing"
is any ``fn(column_value) -> ndarray`` applied per-cell before stacking —
the same escape hatch with none of the class zoo.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from analytics_zoo_tpu.nn.module import Module


def _stack_cols(df, cols: Sequence[str],
                preprocessing: Optional[Callable]) -> np.ndarray:
    """DataFrame columns → one [n, ...] array.  Cells may be scalars or
    ndarrays/lists (image/sequence columns); multiple scalar columns are
    stacked feature-wise."""
    mats = []
    for c in cols:
        vals = df[c].tolist()
        if preprocessing is not None:
            vals = [preprocessing(v) for v in vals]
        arr = np.asarray(vals)
        mats.append(arr if arr.ndim > 1 else arr[:, None])
    if len(mats) == 1:
        return mats[0]
    return np.concatenate([m.reshape(len(m), -1) for m in mats], axis=1)


class NNEstimator:
    """fit(df) → NNModel (reference: NNEstimator.scala / nn_classifier.py).

    Fluent setters mirror the reference's Spark-ML params API; plain
    constructor kwargs work too.
    """

    def __init__(self, model: Module, criterion: Any = "mse",
                 feature_preprocessing: Optional[Callable] = None,
                 label_preprocessing: Optional[Callable] = None):
        self.model = model
        self.criterion = criterion
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.feature_cols: List[str] = ["features"]
        self.label_cols: List[str] = ["label"]
        self.batch_size = 32
        self.max_epoch = 1
        self.learning_rate: Optional[float] = None
        self.optimizer = "adam"
        self.metrics: Optional[Sequence[Any]] = None
        self.sharding: Any = "dp"

    # -- reference-parity fluent setters --------------------------------------

    def setFeaturesCol(self, *cols: str) -> "NNEstimator":
        self.feature_cols = list(cols)
        return self

    def setLabelCol(self, *cols: str) -> "NNEstimator":
        self.label_cols = list(cols)
        return self

    def setBatchSize(self, n: int) -> "NNEstimator":
        self.batch_size = n
        return self

    def setMaxEpoch(self, n: int) -> "NNEstimator":
        self.max_epoch = n
        return self

    def setLearningRate(self, lr: float) -> "NNEstimator":
        self.learning_rate = lr
        return self

    def setOptimMethod(self, opt: Any) -> "NNEstimator":
        self.optimizer = opt
        return self

    # -- core -----------------------------------------------------------------

    def _collect_xy(self, df) -> Dict[str, np.ndarray]:
        from analytics_zoo_tpu.data import XShards
        if isinstance(df, XShards):
            import pandas as pd
            df = pd.concat(df.collect(), ignore_index=True)
        x = _stack_cols(df, self.feature_cols, self.feature_preprocessing)
        out = {"x": x.astype(np.float32) if x.dtype == np.float64 else x}
        if all(c in df.columns for c in self.label_cols):
            y = _stack_cols(df, self.label_cols, self.label_preprocessing)
            if y.shape[-1] == 1:
                y = y[:, 0]
            out["y"] = y.astype(np.float32) if y.dtype == np.float64 else y
        return out

    def fit(self, df) -> "NNModel":
        """Train from DataFrame (or XShards-of-DataFrames) columns."""
        from analytics_zoo_tpu.orca.learn import Estimator
        data = self._collect_xy(df)
        if "y" not in data:
            raise ValueError(
                f"label column(s) {self.label_cols} not found in frame")
        est = Estimator.from_keras(
            self.model, loss=self.criterion, optimizer=self.optimizer,
            learning_rate=self.learning_rate, metrics=self.metrics,
            sharding=self.sharding)
        est.fit((data["x"], self._prepare_label(data["y"])),
                epochs=self.max_epoch, batch_size=self.batch_size,
                verbose=False)
        return self._make_model(est)

    def _prepare_label(self, y: np.ndarray) -> np.ndarray:
        return y

    def _make_model(self, est) -> "NNModel":
        return NNModel(self.model, est, self.feature_cols,
                       self.feature_preprocessing, self.batch_size)


class NNModel:
    """transform(df) appends a ``prediction`` column (reference: NNModel
    extends Spark ML Model[NNModel])."""

    prediction_col = "prediction"

    def __init__(self, model: Module, estimator, feature_cols: Sequence[str],
                 feature_preprocessing: Optional[Callable],
                 batch_size: int = 32):
        self.model = model
        self.estimator = estimator
        self.feature_cols = list(feature_cols)
        self.feature_preprocessing = feature_preprocessing
        self.batch_size = batch_size

    def setPredictionCol(self, col: str) -> "NNModel":
        self.prediction_col = col
        return self

    def setBatchSize(self, n: int) -> "NNModel":
        self.batch_size = n
        return self

    def _predict_array(self, df) -> np.ndarray:
        x = _stack_cols(df, self.feature_cols, self.feature_preprocessing)
        if x.dtype == np.float64:
            x = x.astype(np.float32)
        return self.estimator.predict(x, batch_size=self.batch_size)

    def transform(self, df):
        """DataFrame (or XShards of DataFrames) → same frame + prediction
        column.  XShards transform stays per-shard (order-preserving)."""
        from analytics_zoo_tpu.data import XShards
        if isinstance(df, XShards):
            return df.transform_shard(self._transform_one)
        return self._transform_one(df)

    def _transform_one(self, df):
        out = df.copy()
        pred = self._predict_array(df)
        out[self.prediction_col] = self._format_predictions(pred)
        return out

    def _format_predictions(self, pred: np.ndarray) -> List[Any]:
        return list(pred)

    def save(self, path: str) -> str:
        return self.estimator.save(path)

    def load_weights(self, path: str) -> "NNModel":
        self.estimator.load(path)
        return self


class NNClassifier(NNEstimator):
    """Classification specialization (reference: NNClassifier — label is a
    class index, transform emits the argmax class)."""

    def __init__(self, model: Module,
                 criterion: Any = "sparse_categorical_crossentropy",
                 feature_preprocessing: Optional[Callable] = None):
        super().__init__(model, criterion, feature_preprocessing)

    def _prepare_label(self, y: np.ndarray) -> np.ndarray:
        return y.astype(np.int32)

    def _make_model(self, est) -> "NNClassifierModel":
        return NNClassifierModel(self.model, est, self.feature_cols,
                                 self.feature_preprocessing, self.batch_size)


class NNClassifierModel(NNModel):
    def _format_predictions(self, pred: np.ndarray) -> List[Any]:
        if pred.ndim > 1 and pred.shape[-1] > 1:
            return list(np.argmax(pred, axis=-1).astype(np.int64))
        return list((pred.reshape(len(pred), -1)[:, 0] > 0).astype(np.int64))
