"""NNFrames: DataFrame-native train/transform (Spark ML Pipeline analog).

Reference (SURVEY.md §2.3 "NNFrames"): ``NNEstimator.fit(df)`` trained a
BigDL model straight from DataFrame columns via ``Preprocessing``
converters and returned an ``NNModel`` Spark-ML transformer;
``NNClassifier``/``NNClassifierModel`` specialized to class labels;
``NNImageReader`` loaded images into a DataFrame (Scala
``pipeline/nnframes/*.scala``, Py ``pyzoo/zoo/pipeline/nnframes/
nn_classifier.py``, ``nn_image_reader.py``).

TPU-native redesign: the "DataFrame" is pandas — either one frame or an
``XShards`` of per-host frames — because with Spark gone, pandas is the
frame runtime users actually hold.  The estimator/transformer contract is
kept: ``fit`` returns an ``NNModel`` whose ``transform(df)`` appends a
prediction column, so sklearn/Spark-ML-style pipelines port 1:1.  The
train path is the unified orca Estimator underneath — same jit step, same
mesh sharding.
"""

from .nn_classifier import (NNEstimator, NNModel, NNClassifier,
                            NNClassifierModel)
from .nn_image_reader import NNImageReader

__all__ = ["NNEstimator", "NNModel", "NNClassifier", "NNClassifierModel",
           "NNImageReader"]
