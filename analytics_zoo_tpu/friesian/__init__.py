"""Friesian: recommender-system feature engineering (reference SURVEY.md
§2.2 — pyzoo/zoo/friesian/feature/table.py on Spark DataFrames).

``FeatureTable`` is the offline (sharded pandas) half;
``FeaturePipeline`` replays the fitted transforms per request in the
serving path (plain dicts, picklable, no pandas).
"""

from .table import FeatureTable, StringIndex
from .pipeline import FeaturePipeline

__all__ = ["FeatureTable", "StringIndex", "FeaturePipeline"]
