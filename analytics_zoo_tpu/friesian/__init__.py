"""Friesian: recommender-system feature engineering (reference SURVEY.md
§2.2 — pyzoo/zoo/friesian/feature/table.py on Spark DataFrames)."""

from .table import FeatureTable, StringIndex

__all__ = ["FeatureTable", "StringIndex"]
