"""FeaturePipeline: fitted, picklable per-request feature transforms.

``FeatureTable`` does offline feature engineering over sharded pandas;
serving needs the SAME transforms replayed per request on plain dicts —
no pandas, no shards, microseconds not milliseconds.  A
``FeaturePipeline`` records a chain of fitted steps (fillna, clip,
``StringIndex`` encodes, hashed crosses) as plain data, so it pickles
with the model artifact and replays anywhere:

    idx_u, idx_i = table.gen_string_idx(["user", "item"])
    pipe = (FeaturePipeline()
            .fillna(0.0, ["age"]).clip(["age"], min=0, max=100)
            .encode_string(idx_u).encode_string(idx_i)
            .cross_columns([("user", "item")], [1000]))
    feats = pipe.transform({"user": "u1", "item": "i9", "age": 31.0})

Registered on ``ClusterServing(pipelines={...})`` via
``as_server_transform``, it turns the raw event columns of an assembled
request batch into the model's numeric features server-side — clients
send events, not feature vectors.

Semantics match ``FeatureTable`` exactly (same ``_stable_hash`` for
crosses, unseen categories → the reserved id 0), asserted by the
offline-vs-pipeline parity tests.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .table import StringIndex, _stable_hash


def _is_missing(v: Any) -> bool:
    if v is None:
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    return False


def _fill_col(arr: np.ndarray, value: Any) -> np.ndarray:
    if arr.dtype.kind == "f":
        return np.where(np.isnan(arr), value, arr)
    if arr.dtype == object:
        return np.array([value if _is_missing(v) else v for v in arr],
                        dtype=object)
    return arr


def _encode_col(arr: np.ndarray, index: Dict[Any, int]) -> np.ndarray:
    """Category values → fitted ids; unseen/missing → the reserved id 0
    (``encode_string``'s transform-time semantics).  The wire may carry
    str(category) for a vocab fitted on non-strings — fall back to the
    string form before giving up on a value."""
    out = np.empty(len(arr), np.int64)
    for i, v in enumerate(arr):
        hit = index.get(v)
        if hit is None and not isinstance(v, str):
            hit = index.get(str(v))
        out[i] = 0 if hit is None else hit
    return out


class FeaturePipeline:
    """A fitted feature-transform chain over plain dict events.

    Steps are stored as plain tuples/dicts (no closures, no pandas), so
    the pipeline pickles alongside the model and replays identically in
    any process.  All chaining methods return ``self``."""

    def __init__(self) -> None:
        self._steps: List[tuple] = []

    # -- chain construction ---------------------------------------------------

    def fillna(self, value: Any,
               columns: Sequence[str]) -> "FeaturePipeline":
        self._steps.append(("fillna", {"value": value,
                                       "columns": list(columns)}))
        return self

    def clip(self, columns: Sequence[str], min: Any = None,  # noqa: A002
             max: Any = None) -> "FeaturePipeline":  # noqa: A002
        self._steps.append(("clip", {"columns": list(columns),
                                     "min": min, "max": max}))
        return self

    def encode_string(self, index: StringIndex) -> "FeaturePipeline":
        """Encode ``index.col_name`` through a vocab fitted offline by
        ``FeatureTable.gen_string_idx`` (unseen → 0)."""
        self._steps.append(("encode", {"column": index.col_name,
                                       "index": dict(index.index)}))
        return self

    def cross_columns(self, crosses: Sequence[Sequence[str]],
                      bucket_sizes: Sequence[int]) -> "FeaturePipeline":
        """Hashed crosses, same hash and naming as
        ``FeatureTable.cross_columns`` (new column ``"a_b"``)."""
        if len(crosses) != len(bucket_sizes):
            raise ValueError("one bucket size per cross")
        for cols, size in zip(crosses, bucket_sizes):
            self._steps.append(("cross", {"columns": list(cols),
                                          "size": int(size)}))
        return self

    # -- replay ---------------------------------------------------------------

    def transform(self, events: Union[Dict[str, Any],
                                      Sequence[Dict[str, Any]]]
                  ) -> Dict[str, np.ndarray]:
        """Replay the chain on one event dict or a list of them; returns
        ``{column: np.ndarray}`` with cross columns appended."""
        if isinstance(events, dict):
            events = [events]
        names = list(events[0])
        cols = {c: np.array([e.get(c) for e in events]) for c in names}
        for op, p in self._steps:
            if op == "fillna":
                for c in p["columns"]:
                    if c in cols:
                        cols[c] = _fill_col(cols[c], p["value"])
            elif op == "clip":
                for c in p["columns"]:
                    if c in cols:
                        cols[c] = np.clip(
                            cols[c].astype(np.float64), p["min"], p["max"])
            elif op == "encode":
                c = p["column"]
                if c in cols:
                    cols[c] = _encode_col(cols[c], p["index"])
            elif op == "cross":
                name = "_".join(p["columns"])
                joined = ["_".join(str(cols[c][i]) for c in p["columns"])
                          for i in range(len(events))]
                cols[name] = np.array(
                    [_stable_hash(s) % p["size"] for s in joined],
                    np.int64)
        return cols

    def transform_matrix(self, x: np.ndarray, columns: Sequence[str],
                         dtype: Any = np.float32) -> np.ndarray:
        """Replay the chain on a column-laid-out batch ``[B, C]`` (the
        serving wire layout).  ``columns`` names each position and MAY
        repeat (a ranking request carries one user column and k item
        columns) — a step applies at every position its column names.
        Crosses use the first occurrence of each named column and append
        to the right, in step order.  Returns a numeric ``[B, C']``."""
        x = np.asarray(x)
        if x.ndim != 2 or x.shape[1] != len(columns):
            raise ValueError(
                f"batch shape {x.shape} does not match the declared "
                f"{len(columns)} column(s)")
        names = list(columns)
        out_cols = [np.asarray(x[:, i]) for i in range(x.shape[1])]
        for op, p in self._steps:
            if op == "fillna":
                for i, c in enumerate(names):
                    if c in p["columns"]:
                        out_cols[i] = _fill_col(out_cols[i], p["value"])
            elif op == "clip":
                for i, c in enumerate(names):
                    if c in p["columns"]:
                        out_cols[i] = np.clip(
                            out_cols[i].astype(np.float64),
                            p["min"], p["max"])
            elif op == "encode":
                for i, c in enumerate(names):
                    if c == p["column"]:
                        out_cols[i] = _encode_col(out_cols[i], p["index"])
            elif op == "cross":
                srcs = [out_cols[names.index(c)] for c in p["columns"]]
                joined = ["_".join(str(col[i]) for col in srcs)
                          for i in range(x.shape[0])]
                names.append("_".join(p["columns"]))
                out_cols.append(np.array(
                    [_stable_hash(s) % p["size"] for s in joined],
                    np.int64))
        return np.stack([c.astype(dtype) for c in out_cols], axis=1)

    def as_server_transform(self, columns: Sequence[str],
                            dtype: Any = np.float32) -> Any:
        """A picklable ``fn(batch) -> features`` for
        ``ClusterServing(pipelines={model: fn})``: the assembled request
        batch (raw event columns, laid out per ``columns``) becomes the
        model's numeric features server-side."""
        return _ServerTransform(self, list(columns), dtype)


class _ServerTransform:
    """Top-level class (not a closure) so a pipeline registered on a
    server config stays picklable end to end."""

    def __init__(self, pipeline: FeaturePipeline, columns: List[str],
                 dtype: Any):
        self.pipeline = pipeline
        self.columns = columns
        self.dtype = dtype

    def __call__(self, x: np.ndarray) -> np.ndarray:
        return self.pipeline.transform_matrix(x, self.columns,
                                              dtype=self.dtype)
