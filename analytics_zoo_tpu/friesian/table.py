"""FeatureTable: tabular feature engineering for recsys pipelines.

Reference (SURVEY.md §2.2): ``pyzoo/zoo/friesian/feature/table.py`` —
FeatureTable wrapped a Spark DataFrame with encode_string / gen_string_idx
(StringIndex), fillna/clip, cross_columns (hashed crosses), negative
sampling for implicit-feedback training, and train/test splits.

TPU-native: the table is sharded pandas (XShards of DataFrames — the same
host-parallel data plane the rest of the framework uses); global operations
(vocab building, negative sampling universe) reduce over shards, per-row
transforms run shard-parallel via ``XShards.transform_shard``.  Output
feeds ``zoo.models.recommendation`` through the unified Estimator.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd

from analytics_zoo_tpu.data import XShards


class StringIndex:
    """A fitted category→id vocabulary for one column (reference:
    StringIndex).  Ids start at 1; 0 is reserved for unseen/missing."""

    def __init__(self, col_name: str, index: Dict[Any, int]):
        self.col_name = col_name
        self.index = index

    @property
    def size(self) -> int:
        """Embedding-table size (ids run 0..len(index))."""
        return len(self.index) + 1

    def to_dict(self) -> Dict[Any, int]:
        return dict(self.index)


def _to_shards(df: Union[pd.DataFrame, XShards],
               num_shards: int = 4) -> XShards:
    if isinstance(df, XShards):
        return df
    parts = np.array_split(np.arange(len(df)), num_shards)
    return XShards([df.iloc[p].reset_index(drop=True) for p in parts])


class FeatureTable:
    """Sharded tabular data + chainable feature ops (each op returns a NEW
    FeatureTable; shards are never mutated in place)."""

    def __init__(self, shards: XShards):
        self.shards = shards

    # -- constructors ----------------------------------------------------------

    @staticmethod
    def from_pandas(df: pd.DataFrame, num_shards: int = 4) -> "FeatureTable":
        return FeatureTable(_to_shards(df, num_shards))

    @staticmethod
    def read_csv(path: str, **kw: Any) -> "FeatureTable":
        from analytics_zoo_tpu.data import read_csv
        return FeatureTable(read_csv(path, **kw))

    # -- inspection ------------------------------------------------------------

    def to_pandas(self) -> pd.DataFrame:
        return pd.concat(self.shards.collect(), ignore_index=True)

    def __len__(self) -> int:
        return sum(len(df) for df in self.shards.collect())

    @property
    def columns(self) -> List[str]:
        return list(self.shards.collect()[0].columns)

    # -- cleaning --------------------------------------------------------------

    def fillna(self, value: Any,
               columns: Optional[Sequence[str]] = None) -> "FeatureTable":
        cols = list(columns) if columns else None

        def fill(df: pd.DataFrame) -> pd.DataFrame:
            df = df.copy()
            target = cols or df.columns
            df[target] = df[target].fillna(value)
            return df

        return FeatureTable(self.shards.transform_shard(fill))

    def clip(self, columns: Sequence[str], min: Any = None,  # noqa: A002
             max: Any = None) -> "FeatureTable":  # noqa: A002
        cols = list(columns)

        def do(df: pd.DataFrame) -> pd.DataFrame:
            df = df.copy()
            df[cols] = df[cols].clip(lower=min, upper=max)
            return df

        return FeatureTable(self.shards.transform_shard(do))

    def rename(self, mapping: Dict[str, str]) -> "FeatureTable":
        return FeatureTable(self.shards.transform_shard(
            lambda df: df.rename(columns=mapping)))

    def drop(self, *columns: str) -> "FeatureTable":
        return FeatureTable(self.shards.transform_shard(
            lambda df: df.drop(columns=list(columns))))

    # -- categorical encoding --------------------------------------------------

    def gen_string_idx(self, columns: Union[str, Sequence[str]],
                       freq_limit: int = 1) -> List[StringIndex]:
        """Build StringIndex vocabs from the full table (global reduce over
        shards), ordered by descending frequency (reference semantics)."""
        cols = [columns] if isinstance(columns, str) else list(columns)
        indices = []
        dfs = self.shards.collect()
        for c in cols:
            counts: Dict[Any, int] = {}
            for df in dfs:
                for v, n in df[c].value_counts().items():
                    counts[v] = counts.get(v, 0) + int(n)
            vocab = [v for v, n in sorted(counts.items(),
                                          key=lambda kv: (-kv[1], str(kv[0])))
                     if n >= freq_limit]
            indices.append(StringIndex(c, {v: i + 1 for i, v in
                                           enumerate(vocab)}))
        return indices

    def encode_string(self, columns: Union[str, Sequence[str]],
                      indices: Optional[Sequence[StringIndex]] = None
                      ) -> Tuple["FeatureTable", List[StringIndex]]:
        """Replace category values with ids (unseen → 0).  Pass the train
        table's ``indices`` to encode val/test consistently."""
        cols = [columns] if isinstance(columns, str) else list(columns)
        if indices is None:
            indices = self.gen_string_idx(cols)
        by_col = {si.col_name: si.index for si in indices}

        def encode(df: pd.DataFrame) -> pd.DataFrame:
            df = df.copy()
            for c in cols:
                df[c] = df[c].map(by_col[c]).fillna(0).astype(np.int64)
            return df

        return FeatureTable(self.shards.transform_shard(encode)), \
            list(indices)

    # -- crosses ---------------------------------------------------------------

    def cross_columns(self, crosses: Sequence[Sequence[str]],
                      bucket_sizes: Sequence[int]) -> "FeatureTable":
        """Hashed feature crosses: new column "a_b" = hash(a, b) % bucket
        (reference: cross_columns; W&D's wide-side crosses)."""
        if len(crosses) != len(bucket_sizes):
            raise ValueError("one bucket size per cross")

        def do(df: pd.DataFrame) -> pd.DataFrame:
            df = df.copy()
            for cols, size in zip(crosses, bucket_sizes):
                name = "_".join(cols)
                joined = df[list(cols)].astype(str).agg("_".join, axis=1)
                # stable non-cryptographic hash (python hash() is salted)
                df[name] = joined.map(
                    lambda s: _stable_hash(s) % size).astype(np.int64)
            return df

        return FeatureTable(self.shards.transform_shard(do))

    # -- negative sampling -----------------------------------------------------

    def negative_sample(self, item_size: int, item_col: str = "item",
                        label_col: str = "label", neg_num: int = 1,
                        seed: int = 0) -> "FeatureTable":
        """Implicit-feedback training data: every existing row becomes a
        positive (label 1) and gains ``neg_num`` copies with a random item
        and label 0 (reference: add_negative_samples).  ``item_size`` is the
        exclusive upper item-id bound; sampled ids start at 1 (0 = pad).

        Sampling is counter-based on ``(seed, global row, slot)`` — each
        negative is a pure function of the row's GLOBAL position, not of
        which shard holds it, so the same rows with the same ``seed``
        yield the same negatives across runs AND across shard counts
        (1-shard debugging reproduces the 64-shard job)."""
        if item_size < 2:
            raise ValueError(
                f"item_size must be >= 2 (ids sample from [1, item_size)),"
                f" got {item_size}")

        def do(df: pd.DataFrame, start: int) -> pd.DataFrame:
            gidx = np.arange(start, start + len(df), dtype=np.uint64)
            pos = df.copy()
            pos[label_col] = 1
            negs = []
            for j in range(neg_num):
                neg = df.copy()
                neg[item_col] = _counter_sample(seed, gidx, j, item_size)
                neg[label_col] = 0
                negs.append(neg)
            return pd.concat([pos] + negs, ignore_index=True)

        dfs = self.shards.collect()
        offsets = np.concatenate([[0], np.cumsum([len(d) for d in dfs])])
        out = [do(df, int(offsets[i])) for i, df in enumerate(dfs)]
        return FeatureTable(XShards(out))

    # -- splits / export -------------------------------------------------------

    def random_split(self, weights: Sequence[float], seed: int = 0
                     ) -> List["FeatureTable"]:
        """Row-wise split, e.g. [0.8, 0.2] (reference: split)."""
        w = np.asarray(weights, np.float64)
        w = w / w.sum()
        dfs = self.shards.collect()
        parts: List[List[pd.DataFrame]] = [[] for _ in w]
        for i, df in enumerate(dfs):
            rng = np.random.default_rng(seed + i)
            assign = rng.choice(len(w), size=len(df), p=w)
            for j in range(len(w)):
                parts[j].append(df[assign == j].reset_index(drop=True))
        return [FeatureTable(XShards(p)) for p in parts]

    def to_numpy_dict(self, feature_cols: Sequence[str],
                      label_col: str = "label") -> Dict[str, np.ndarray]:
        df = self.to_pandas()
        return {"x": df[list(feature_cols)].to_numpy(),
                "y": df[label_col].to_numpy()}

    def to_feed(self, feature_cols: Sequence[str], label_col: str = "label",
                batch_size: int = 32, **kw: Any):
        from analytics_zoo_tpu.data import DataFeed
        d = self.to_numpy_dict(feature_cols, label_col)
        return DataFeed(d, batch_size, **kw)


def _counter_sample(seed: int, gidx: np.ndarray, slot: int,
                    item_size: int) -> np.ndarray:
    """Deterministic item ids in ``[1, item_size)`` from ``(seed, global
    row index, negative slot)`` — a vectorized splitmix64 finalizer, so
    the draw depends only on the row's global position (shard-count
    invariant by construction)."""
    mask = np.uint64(0xFFFFFFFFFFFFFFFF)
    key = np.uint64((seed * 0x9E3779B97F4A7C15
                     + (slot + 1) * 0xBF58476D1CE4E5B9)
                    & 0xFFFFFFFFFFFFFFFF)
    x = (gidx.astype(np.uint64) * np.uint64(0x94D049BB133111EB)) ^ key
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9) & mask
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB) & mask
    x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(item_size - 1) + np.uint64(1)).astype(np.int64)


def _stable_hash(s: str) -> int:
    """FNV-1a 64-bit: deterministic across processes (unlike hash())."""
    h = 0xcbf29ce484222325
    for b in s.encode():
        h = ((h ^ b) * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return h
