"""Keras-2-named layers (reference: pyzoo/zoo/pipeline/api/keras2/layers).

Every class is the TPU-native implementation from ``analytics_zoo_tpu.nn``;
Keras-2 spellings that differ from Keras-1 (Conv2D vs Convolution2D, ...)
are the canonical names here.
"""

from analytics_zoo_tpu.nn import *  # noqa: F401,F403
from analytics_zoo_tpu.nn import __all__ as _nn_all

__all__ = list(_nn_all)
