"""Keras-2 model containers (reference: pyzoo/zoo/pipeline/api/keras2)."""

from analytics_zoo_tpu.nn import Input, Model, Sequential  # noqa: F401

__all__ = ["Input", "Model", "Sequential"]
