"""keras2 namespace: the Keras-2-style API surface (reference:
pyzoo/zoo/pipeline/api/keras2/ — the reference shipped a second, Keras-2-
named layer namespace alongside the Keras-1.2 one).

Here both namespaces front the SAME TPU-native module system; this package
exists so reference scripts using ``zoo.pipeline.api.keras2`` port with an
import-line change:

    from analytics_zoo_tpu.keras2.layers import Dense, Conv2D
    from analytics_zoo_tpu.keras2.models import Model, Sequential
"""

from . import layers, models  # noqa: F401
