"""AutoML (reference: pyzoo/zoo/orca/automl — SURVEY.md §2.5).

The reference ran Ray Tune trials across Spark executors.  TPU-native
redesign: trials are plain Python callables over the jit-compiled Estimator;
the search engine schedules them in-process (sequentially on the pod, or
thread-parallel for CPU-bound trials) with ASHA-style early stopping — no
Ray, no cluster bootstrap (SURVEY.md §7: 'AutoML trial scheduling without
Ray').
"""

from . import hp
from .search import (ASHAScheduler, GridSearchEngine, RandomSearchEngine,
                     SearchEngine, StopTrial, Trial, TrialTimeout)
from .auto_estimator import AutoEstimator

__all__ = ["hp", "AutoEstimator", "SearchEngine", "RandomSearchEngine",
           "GridSearchEngine", "ASHAScheduler", "Trial", "StopTrial",
           "TrialTimeout"]
