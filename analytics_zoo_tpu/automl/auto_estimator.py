"""AutoEstimator (reference: zoo.orca.automl.auto_estimator —
pyzoo/zoo/orca/automl/auto_estimator.py: model-creator fn + search space →
Tune trials → best-config refit/get_best_model).

Same contract: ``model_creator(config) -> nn.Module`` and optional
``optimizer/loss`` entries inside the config; each trial trains through the
unified Estimator and reports the validation metric per epoch (ASHA prunes).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

import numpy as np

from .search import ASHAScheduler, RandomSearchEngine, SearchEngine, Trial

logger = logging.getLogger("analytics_zoo_tpu")


class AutoEstimator:
    def __init__(self, model_creator: Callable[[Dict[str, Any]], Any],
                 loss: Any = "mse", optimizer: Any = "adam",
                 metric: str = "loss", metric_mode: str = "min",
                 search_engine: Optional[SearchEngine] = None):
        self.model_creator = model_creator
        self.loss = loss
        self.optimizer = optimizer
        self.metric = metric
        self.metric_mode = metric_mode
        self.engine = search_engine
        self._best_trial: Optional[Trial] = None
        self._best_estimator = None

    # reference parity constructors ------------------------------------------
    @staticmethod
    def from_keras(model_creator, loss="mse", optimizer="adam",
                   metric="loss", metric_mode="min") -> "AutoEstimator":
        return AutoEstimator(model_creator, loss=loss, optimizer=optimizer,
                             metric=metric, metric_mode=metric_mode)

    from_torch = from_keras  # the reference had both; one estimator here

    def fit(self, data: Any, validation_data: Any = None, epochs: int = 1,
            batch_size: Any = 32, n_sampling: int = 4,
            search_space: Optional[Dict[str, Any]] = None,
            scheduler: Optional[ASHAScheduler] = None,
            max_concurrent: Optional[int] = None,
            seed: int = 0) -> "AutoEstimator":
        """Search; then keep the best trained estimator.

        ``scheduler``: an ASHAScheduler, or the string "asha" for default
        ASHA settings (reference: tune scheduler names).

        ``max_concurrent``: trials running at once (reference:
        RayTuneSearchEngine ran one trial per Ray worker).  Trials run in
        a thread pool — XLA releases the GIL during compute, so CPU-host
        trials genuinely overlap; on a single TPU pod keep 1 (one pod =
        one trial)."""
        from analytics_zoo_tpu.orca.learn import Estimator
        search_space = dict(search_space or {})
        val = validation_data if validation_data is not None else data
        if scheduler == "asha":
            scheduler = ASHAScheduler(metric_mode=self.metric_mode)
        engine = self.engine or RandomSearchEngine(
            metric_mode=self.metric_mode, scheduler=scheduler,
            max_concurrent=max_concurrent or 1, seed=seed)
        # fit()'s arguments must take effect on a pre-existing engine too
        # (custom search_engine, or a second fit() on the cached engine);
        # None = unspecified, an explicit 1 restores serial execution
        if max_concurrent is not None:
            engine.max_concurrent = max_concurrent
        if scheduler is not None:
            engine.scheduler = scheduler
        self.engine = engine

        def trial_fn(config: Dict[str, Any], report) -> float:
            lr = config.pop("lr", config.pop("learning_rate", None))
            bs = config.pop("batch_size", None) or (
                batch_size if isinstance(batch_size, int) else 32)
            model = self.model_creator(dict(config))
            est = Estimator.from_keras(
                model, loss=self.loss, optimizer=self.optimizer,
                learning_rate=lr,
                metrics=[self.metric] if self.metric != "loss" else None)
            best = None
            for epoch in range(epochs):
                est.fit(data, epochs=1, batch_size=int(bs), verbose=False)
                m = est.evaluate(val, batch_size=int(bs))[self.metric]
                better = (best is None or
                          (m < best if self.metric_mode == "min" else m > best))
                if better:
                    best = m
                report(m, epoch + 1)
            return best

        if not isinstance(batch_size, int):  # a Sampler: search over it
            search_space.setdefault("batch_size", batch_size)
        best = engine.run(trial_fn, search_space, n_trials=n_sampling)
        self._best_trial = best
        # refit the winner to get its estimator (trials may be pruned)
        model = self.model_creator({k: v for k, v in best.config.items()
                                    if k not in ("lr", "learning_rate",
                                                 "batch_size")})
        lr = best.config.get("lr", best.config.get("learning_rate"))
        bs = int(best.config.get("batch_size") or (
            batch_size if isinstance(batch_size, int) else 32))
        est = Estimator.from_keras(
            model, loss=self.loss, optimizer=self.optimizer, learning_rate=lr,
            metrics=[self.metric] if self.metric != "loss" else None)
        est.fit(data, epochs=epochs, batch_size=bs, verbose=False)
        self._best_estimator = est
        self._best_model = model
        return self

    def get_best_model(self):
        if self._best_estimator is None:
            raise ValueError("call fit() first")
        return self._best_model

    def get_best_estimator(self):
        if self._best_estimator is None:
            raise ValueError("call fit() first")
        return self._best_estimator

    def get_best_config(self) -> Dict[str, Any]:
        if self._best_trial is None:
            raise ValueError("call fit() first")
        return dict(self._best_trial.config)

    @property
    def trials(self):
        return self.engine.trials if self.engine else []
