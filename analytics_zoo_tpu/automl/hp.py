"""Search-space DSL (reference: zoo.orca.automl.hp —
pyzoo/zoo/orca/automl/hp.py wrapped Ray Tune's sample primitives).

Same API surface: ``hp.choice/uniform/quniform/loguniform/randint/grid_search``
— self-contained sampling objects, no Tune dependency.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence

import numpy as np


class Sampler:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError

    def grid_values(self) -> List[Any]:
        """Discretization for grid search (continuous: a small linspace)."""
        raise NotImplementedError


@dataclass
class Choice(Sampler):
    options: Sequence[Any]

    def sample(self, rng):
        return self.options[int(rng.integers(0, len(self.options)))]

    def grid_values(self):
        return list(self.options)


@dataclass
class Uniform(Sampler):
    lower: float
    upper: float

    def sample(self, rng):
        return float(rng.uniform(self.lower, self.upper))

    def grid_values(self):
        return list(np.linspace(self.lower, self.upper, 3))


@dataclass
class QUniform(Sampler):
    lower: float
    upper: float
    q: float = 1.0

    def sample(self, rng):
        v = rng.uniform(self.lower, self.upper)
        return float(np.round(v / self.q) * self.q)

    def grid_values(self):
        vals = np.arange(self.lower, self.upper + self.q / 2, self.q)
        return [float(v) for v in vals[:10]]


@dataclass
class LogUniform(Sampler):
    lower: float
    upper: float

    def sample(self, rng):
        return float(np.exp(rng.uniform(np.log(self.lower),
                                        np.log(self.upper))))

    def grid_values(self):
        return list(np.exp(np.linspace(np.log(self.lower),
                                       np.log(self.upper), 3)))


@dataclass
class RandInt(Sampler):
    lower: int
    upper: int  # exclusive, Tune semantics

    def sample(self, rng):
        return int(rng.integers(self.lower, self.upper))

    def grid_values(self):
        step = max(1, (self.upper - self.lower) // 3)
        return list(range(self.lower, self.upper, step))


@dataclass
class GridSearch(Sampler):
    options: Sequence[Any]

    def sample(self, rng):  # random engines treat grid like choice
        return self.options[int(rng.integers(0, len(self.options)))]

    def grid_values(self):
        return list(self.options)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(list(options))


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def quniform(lower: float, upper: float, q: float = 1.0) -> QUniform:
    return QUniform(lower, upper, q)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> RandInt:
    return RandInt(lower, upper)


def grid_search(options: Sequence[Any]) -> GridSearch:
    return GridSearch(list(options))


def sample(space: Dict[str, Any], rng: np.random.Generator) -> Dict[str, Any]:
    """One concrete config from a {name: Sampler-or-literal} space."""
    return {k: (v.sample(rng) if isinstance(v, Sampler) else v)
            for k, v in space.items()}


def grid(space: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cartesian product over grid_values of every Sampler in the space."""
    import itertools
    keys, value_lists = [], []
    for k, v in space.items():
        keys.append(k)
        value_lists.append(v.grid_values() if isinstance(v, Sampler) else [v])
    return [dict(zip(keys, combo))
            for combo in itertools.product(*value_lists)]
