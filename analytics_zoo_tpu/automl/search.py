"""Search engines + ASHA early stopping.

Reference (SURVEY.md §2.5): ``SearchEngine`` abstraction with a
``RayTuneSearchEngine`` implementation (pyzoo/zoo/orca/automl/search/) —
Tune workers trained one trial each, the ASHA scheduler killed stragglers.

TPU-native: a trial is ``fn(config, report) -> result``; ``report(metric,
step)`` streams intermediate results so ASHA can stop a trial early (the
callback raises StopTrial).  Engines run trials in-process — sequential by
default (one TPU pod = one trial at a time; the reference's parallelism came
from having a CPU cluster), optional thread pool for host-bound trials.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from . import hp as hp_mod

logger = logging.getLogger("analytics_zoo_tpu")


class StopTrial(Exception):
    """Raised inside report() when the scheduler prunes the trial."""


class TrialTimeout(Exception):
    """A trial exceeded its wall-clock budget (``trial_timeout_s``)."""


@dataclass
class Trial:
    trial_id: int
    config: Dict[str, Any]
    metric: Optional[float] = None     # best reported (per mode)
    history: List[float] = field(default_factory=list)
    status: str = "pending"            # pending | done | pruned | error
    #                                  # | timeout
    error: Optional[str] = None
    duration_s: float = 0.0
    retries: int = 0                   # transient-failure retries used


class ASHAScheduler:
    """Asynchronous Successive Halving: at each rung (step budget
    grace_period * reduction_factor^k), a trial continues only if its metric
    is in the top 1/reduction_factor of completed rung results."""

    def __init__(self, metric_mode: str = "min", grace_period: int = 1,
                 reduction_factor: int = 3, max_t: int = 100):
        self.mode = metric_mode
        self.grace = grace_period
        self.rf = reduction_factor
        self.max_t = max_t
        self._rungs: Dict[int, List[float]] = {}
        self._lock = threading.Lock()

    def _rung_of(self, step: int) -> Optional[int]:
        t = self.grace
        while t <= self.max_t:
            if step == t:
                return t
            t *= self.rf
        return None

    def on_report(self, trial: Trial, metric: float, step: int) -> bool:
        """Returns False if the trial should be pruned now."""
        rung = self._rung_of(step)
        if rung is None:
            return True
        key = metric if self.mode == "min" else -metric
        with self._lock:
            peers = self._rungs.setdefault(rung, [])
            peers.append(key)
            if len(peers) < self.rf:      # not enough evidence yet
                return True
            cutoff = np.quantile(peers, 1.0 / self.rf)
            return key <= cutoff


class SearchEngine:
    """Base: subclasses yield configs; run_trials executes + tracks them."""

    def __init__(self, metric_mode: str = "min",
                 scheduler: Optional[ASHAScheduler] = None,
                 max_concurrent: int = 1, seed: int = 0,
                 trial_timeout_s: Optional[float] = None,
                 trial_retries: int = 0):
        """``trial_timeout_s``: per-trial wall-clock budget — a trial past
        it is marked ``status="timeout"`` (keeping any partial metric from
        its reports) instead of wedging the whole search.  Enforced
        cooperatively at every ``report()`` call AND by a hard wall (the
        trial runs on an abandonable daemon thread; a trial that never
        reports and never returns leaks that thread — acceptable for
        host-bound trial bodies, the only kind that wedges).

        ``trial_retries``: transient trial failures (any exception) are
        retried up to this many times before the trial is marked
        ``error``; the count used is recorded on ``Trial.retries``."""
        self.mode = metric_mode
        self.scheduler = scheduler
        self.max_concurrent = max_concurrent
        self.trial_timeout_s = trial_timeout_s
        self.trial_retries = max(0, trial_retries)
        self.rng = np.random.default_rng(seed)
        self.trials: List[Trial] = []

    def configs(self, space: Dict[str, Any], n_trials: int
                ) -> List[Dict[str, Any]]:
        raise NotImplementedError

    def run(self, trial_fn: Callable, space: Dict[str, Any],
            n_trials: int = 8) -> Trial:
        """trial_fn(config, report) → final metric (float) or dict with
        'metric'.  Returns the best Trial."""
        configs = self.configs(space, n_trials)
        self.trials = [Trial(i, c) for i, c in enumerate(configs)]

        def execute(trial: Trial) -> None:
            t0 = time.monotonic()
            deadline = (t0 + self.trial_timeout_s
                        if self.trial_timeout_s else None)

            def report(metric: float, step: int) -> None:
                if deadline is not None and time.monotonic() > deadline:
                    raise TrialTimeout()  # cooperative wall-clock stop
                trial.history.append(float(metric))
                # retry attempts do not re-feed the shared ASHA rungs: the
                # first attempt already contributed this trial's evidence
                # there, and duplicate samples would skew every sibling's
                # promotion cutoff.  (They also forgo pruning — a retried
                # transient failure should run out its budget.)
                if (self.scheduler and trial.retries == 0
                        and not self.scheduler.on_report(
                            trial, float(metric), step)):
                    raise StopTrial()

            def partial_metric() -> None:
                if trial.history:
                    trial.metric = (min(trial.history) if self.mode == "min"
                                    else max(trial.history))

            trial.status = "running"
            while True:
                trial.history.clear()  # fresh attempt, fresh reports
                try:
                    out = _call_with_deadline(
                        trial_fn, (dict(trial.config), report), deadline)
                    metric = out["metric"] if isinstance(out, dict) else out
                    trial.metric = float(metric)
                    trial.status = "done"
                    trial.error = None  # a retried failure that healed
                except StopTrial:
                    trial.status = "pruned"
                    partial_metric()
                except TrialTimeout:
                    trial.status = "timeout"
                    partial_metric()  # partial evidence is still evidence
                    logger.warning("trial %d timed out after %.1fs",
                                   trial.trial_id, self.trial_timeout_s)
                except Exception as e:  # noqa: BLE001 — trials fail freely
                    trial.error = f"{type(e).__name__}: {e}"
                    if trial.retries < self.trial_retries:
                        trial.retries += 1
                        logger.warning(
                            "trial %d failed transiently (%s); retry %d/%d",
                            trial.trial_id, trial.error, trial.retries,
                            self.trial_retries)
                        continue
                    trial.status = "error"
                    logger.warning("trial %d failed: %s", trial.trial_id,
                                   trial.error)
                break
            trial.duration_s = time.monotonic() - t0
            # per-trial telemetry (core/metrics.py): search throughput
            # and outcome mix, without holding the engine object
            from analytics_zoo_tpu.core import metrics as metrics_lib
            reg = metrics_lib.get_registry()
            reg.observe("automl.trial_ms", trial.duration_s * 1000.0)
            reg.inc("automl.trials", status=trial.status)

        if self.max_concurrent > 1:
            with ThreadPoolExecutor(self.max_concurrent) as pool:
                list(pool.map(execute, self.trials))
        else:
            for t in self.trials:
                execute(t)

        scored = [t for t in self.trials if t.metric is not None]
        if not scored:
            errs = [t.error for t in self.trials if t.error]
            raise RuntimeError(f"all {len(self.trials)} trials failed; "
                               f"first error: {errs[0] if errs else '?'}")
        best = (min if self.mode == "min" else max)(
            scored, key=lambda t: t.metric)
        logger.info("search done: best trial %d metric=%.5f config=%s",
                    best.trial_id, best.metric, best.config)
        return best


def _call_with_deadline(fn: Callable, args: tuple,
                        deadline: Optional[float]) -> Any:
    """Run ``fn(*args)`` with a hard wall clock: past ``deadline`` the
    caller gets ``TrialTimeout`` while the work runs out its course on an
    abandoned daemon thread (Python cannot kill a thread; the cooperative
    ``report()`` deadline check is what actually stops well-behaved
    trials)."""
    if deadline is None:
        return fn(*args)
    box: Dict[str, Any] = {}

    def run() -> None:
        try:
            box["out"] = fn(*args)
        except BaseException as e:  # noqa: BLE001 — re-raised by caller
            box["exc"] = e

    th = threading.Thread(target=run, daemon=True, name="zoo-trial")
    th.start()
    th.join(timeout=max(0.0, deadline - time.monotonic()))
    if th.is_alive():
        raise TrialTimeout()
    if "exc" in box:
        raise box["exc"]
    return box["out"]


class RandomSearchEngine(SearchEngine):
    def configs(self, space, n_trials):
        return [hp_mod.sample(space, self.rng) for _ in range(n_trials)]


class GridSearchEngine(SearchEngine):
    def configs(self, space, n_trials):
        grid = hp_mod.grid(space)
        if n_trials and len(grid) > n_trials:
            idx = self.rng.permutation(len(grid))[:n_trials]
            grid = [grid[i] for i in idx]
        return grid
