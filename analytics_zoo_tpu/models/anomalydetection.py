"""AnomalyDetector (reference: zoo.models.anomalydetection —
models/anomalydetection/AnomalyDetector.scala + Unroll helpers).

Stacked-LSTM next-value regressor over unrolled windows; anomalies = points
whose prediction error ranks in the top ``anomaly_fraction``.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

import analytics_zoo_tpu.nn as nn
from .common import ZooModel


def unroll(data: np.ndarray, unroll_length: int,
           predict_step: int = 1) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows: series [N, F] → (x [M, unroll, F], y [M])
    (reference: AnomalyDetector.unroll on an RDD; here vectorized numpy)."""
    data = np.asarray(data)
    if data.ndim == 1:
        data = data[:, None]
    n = len(data) - unroll_length - predict_step + 1
    if n <= 0:
        raise ValueError("series shorter than unroll_length + predict_step")
    idx = np.arange(unroll_length)[None, :] + np.arange(n)[:, None]
    x = data[idx]
    y = data[np.arange(n) + unroll_length + predict_step - 1, 0]
    return x.astype(np.float32), y.astype(np.float32)


class AnomalyDetector(ZooModel):
    def __init__(self, feature_shape: Sequence[int],
                 hidden_layers: Sequence[int] = (8, 32, 15),
                 dropouts: Sequence[float] = (0.2, 0.2, 0.2)):
        super().__init__()
        self._config = dict(feature_shape=list(feature_shape),
                            hidden_layers=list(hidden_layers),
                            dropouts=list(dropouts))
        self.feature_shape = tuple(feature_shape)
        self.hidden_layers = list(hidden_layers)
        self.dropouts = list(dropouts)

    def forward(self, scope, x):
        h = x
        for i, (units, rate) in enumerate(zip(self.hidden_layers,
                                              self.dropouts)):
            last = i == len(self.hidden_layers) - 1
            h = scope.child(nn.LSTM(units, return_sequences=not last), h,
                            name=f"lstm_{i}")
            h = scope.child(nn.Dropout(rate), h, name=f"drop_{i}")
        return scope.child(nn.Dense(1), h, name="head")

    def detect_anomalies(self, y_true: np.ndarray, y_pred: np.ndarray,
                         anomaly_fraction: float = 0.05) -> np.ndarray:
        """Indices of the top-fraction absolute errors (reference:
        detectAnomalies RDD sort → threshold)."""
        y_true = np.asarray(y_true).reshape(-1)
        y_pred = np.asarray(y_pred).reshape(-1)
        err = np.abs(y_true - y_pred)
        k = max(1, int(len(err) * anomaly_fraction))
        thresh = np.sort(err)[-k]
        return np.where(err >= thresh)[0]
