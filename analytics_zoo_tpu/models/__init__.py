"""Built-in model zoo (reference: zoo.models — SURVEY.md §2.7).

Every family from the reference's Scala+Py twin zoo, rebuilt as pure-JAX
modules over analytics_zoo_tpu.nn: recommendation (NeuralCF, WideAndDeep,
SessionRecommender), text classification, text matching (KNRM), anomaly
detection, seq2seq, image classification (ResNet), object detection (SSD),
plus the BERT family the reference shipped through TFPark.
"""

from .common import ZooModel
from .recommendation import (NCFTail, NeuralCF, SessionRecommender,
                             UserItemFeature, UserItemPrediction,
                             WideAndDeep)
from .textclassification import TextClassifier
from .textmatching import KNRM
from .anomalydetection import AnomalyDetector, unroll
from .seq2seq import Seq2seq, RNNEncoder, RNNDecoder
from .image import ImageClassifier, ResNet
from .objectdetection import ObjectDetector, SSDLite, Visualizer
from .bert import BERT, BERTClassifier, BERTNER, BERTSQuAD
from .graphnet import GraphNet
from .net import ForeignNet, Net

__all__ = [
    "Net", "ForeignNet", "GraphNet",
    "ZooModel", "NeuralCF", "NCFTail", "WideAndDeep", "SessionRecommender",
    "UserItemFeature", "UserItemPrediction", "TextClassifier", "KNRM",
    "AnomalyDetector", "unroll", "Seq2seq", "RNNEncoder", "RNNDecoder",
    "ImageClassifier", "ResNet", "ObjectDetector", "SSDLite", "Visualizer",
    "BERT", "BERTClassifier", "BERTNER", "BERTSQuAD",
]
