"""Foreign-model import: TF/Keras and PyTorch models → the nn module system.

Reference (SURVEY.md §2.3): the reference ran foreign models through JNI
engine bridges — TFNet executed frozen TF graphs via libtensorflow
(zoo/.../pipeline/api/net/TFNet.scala), TorchNet ran TorchScript via
libtorch (Torch*.scala), loaded from Python by ``Net.load_tf`` /
``Net.load_torch`` (pyzoo/zoo/pipeline/api/net.py).

TPU-native redesign: there is no second engine to bridge to — a foreign
model is *converted* into this framework's pure-function modules + a baked
variables pytree, then jit-compiles onto the TPU like any native model
(and can be fine-tuned by the Estimator, which the JNI bridges could not).
Conversion covers the common layer vocabulary (dense/conv/pool/norm/
embedding/activation chains — the zoo.models-scale subset); anything else
raises with pointers to the escape hatch:

  ESCAPE HATCH: write the forward as an ``nn.Module`` yourself and pour the
  foreign weights in via ``Net.torch_params_to_tree(mod)`` (name→array dict
  of every torch parameter/buffer) or ``model.get_weights()`` on the Keras
  side, then construct variables for your module directly.

Differential tests (tests/test_net.py) assert converted outputs match the
source framework within float tolerance — the SURVEY §4.4 pattern.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Params, Scope


class ForeignNet(Module):
    """A converted foreign model: a linear chain of native layers whose
    weights came from the source framework (baked into ``init``)."""

    def __init__(self, stages: Sequence[Tuple[str, Module]],
                 variables: Params, source: str, nchw_input: bool = False):
        super().__init__(name=None)
        self.stages = list(stages)
        self._variables = variables
        self.source = source
        #: torch convnets take NCHW; the converted net transposes to NHWC at
        #: the boundary so callers keep feeding torch-layout arrays
        self.nchw_input = nchw_input

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if self.nchw_input and x.ndim == 4:
            x = jnp.transpose(x, (0, 2, 3, 1))
        for name, mod in self.stages:
            x = scope.child(mod, x, name=name)
        if self.nchw_input and x.ndim == 4:
            # symmetric boundary: a conv-ending net hands back torch layout
            x = jnp.transpose(x, (0, 3, 1, 2))
        return x

    def init(self, rng: jax.Array, *args: Any, **kwargs: Any) -> Params:
        """The imported weights, not a random init."""
        return jax.tree_util.tree_map(jnp.asarray,
                                      copy.deepcopy(self._variables))


class ForeignGraphNet(Module):
    """A converted foreign model with DAG structure (residual adds, branches,
    merges) — the general case the chain-shaped ``ForeignNet`` can't express.

    ``nodes`` execute in topological order over an environment of named
    values; each node is either a native Module (weights baked into
    ``init``) or a pure function of earlier values.  Reference parity:
    TFNet/TorchNet executed arbitrary foreign graphs through JNI engines
    (zoo/.../pipeline/api/net/TFNet.scala, Torch*.scala); here the graph is
    converted once and jit-compiles onto the TPU like any native model."""

    def __init__(self, input_names: Sequence[str], nodes: Sequence[Dict],
                 output_name: str, variables: Params, source: str,
                 nchw_input: bool = False):
        super().__init__(name=None)
        self.input_names = list(input_names)
        self.nodes = list(nodes)
        self.output_name = output_name
        self._variables = variables
        self.source = source
        self.nchw_input = nchw_input

    def forward(self, scope: Scope, *xs: jax.Array) -> jax.Array:
        if len(xs) != len(self.input_names):
            raise ValueError(
                f"model takes {len(self.input_names)} inputs, got {len(xs)}")
        env: Dict[str, jax.Array] = {}
        for name, x in zip(self.input_names, xs):
            if self.nchw_input and x.ndim == 4:
                x = jnp.transpose(x, (0, 2, 3, 1))
            env[name] = x
        for node in self.nodes:
            args = [env[a] if ref else a for ref, a in node["args"]]
            if node["module"] is not None:
                env[node["name"]] = scope.child(node["module"], *args,
                                                name=node["name"])
            else:
                env[node["name"]] = node["fn"](*args)
        out = env[self.output_name]
        if self.nchw_input and out.ndim == 4:
            out = jnp.transpose(out, (0, 3, 1, 2))
        return out

    def init(self, rng: jax.Array, *args: Any, **kwargs: Any) -> Params:
        """The imported weights, not a random init."""
        return jax.tree_util.tree_map(jnp.asarray,
                                      copy.deepcopy(self._variables))


class Net:
    """Loader namespace (reference: ``Net.load_tf/load_torch/load_bigdl``)."""

    # -- torch -----------------------------------------------------------------

    @staticmethod
    def load_torch(module: Any, example_input: Any) -> ForeignNet:
        """Convert a ``torch.nn.Module`` (or TorchScript file path) whose
        execution is a Sequential chain of supported leaf layers.

        ``example_input``: one real input batch (torch NCHW layout for conv
        nets) — used to trace per-layer input shapes, which the conversion
        needs (e.g. reordering Linear weights that follow a Flatten of NCHW
        feature maps into NHWC order)."""
        import torch
        if isinstance(module, str):
            try:
                module = torch.jit.load(module)
            except RuntimeError:
                module = torch.load(module, weights_only=False)
        module = module.eval()
        try:
            leaves = _torch_leaves(module)
        except NotImplementedError:
            # not a Sequential chain: convert the full DAG via torch.fx
            # (raises itself for TorchScript, which cannot be fx-traced)
            return _load_torch_fx(module, example_input)
        x = torch.as_tensor(np.asarray(example_input))
        shapes = _torch_trace_shapes(module, leaves, x)
        nchw = x.ndim == 4
        stages: List[Tuple[str, Module]] = []
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        # NCHW shape the last Flatten consumed; carried through order-
        # preserving layers (Dropout/activations) until the first Linear
        # uses it to reorder its kernel rows into NHWC flatten order
        flat_origin: Optional[Tuple[int, ...]] = None
        for i, leaf in enumerate(leaves):
            kind = _torch_kind(leaf)
            name = f"{i}_{kind.lower()}"
            conv = _TORCH_CONVERTERS.get(kind)
            if conv is None:
                raise NotImplementedError(
                    f"torch layer {kind} is not in the supported conversion "
                    f"set {sorted(_TORCH_CONVERTERS)}; see the escape hatch "
                    "in analytics_zoo_tpu.models.net's docstring")
            mod, p, s = conv(leaf, shapes[i], flat_origin)
            if kind == "Flatten" and len(shapes[i]) == 4:
                flat_origin = tuple(shapes[i])
            elif kind == "Linear":
                flat_origin = None  # consumed: later Linears see mixed space
            if mod is None:
                continue  # identity (e.g. Dropout at inference keeps staged)
            stages.append((name, mod))
            if p:
                params[name] = p
            if s:
                state[name] = s
        return ForeignNet(stages, {"params": params, "state": state},
                          source="torch", nchw_input=nchw)

    @staticmethod
    def load_torch_graph(module: Any, example_input: Any) -> ForeignGraphNet:
        """Convert a graph-structured ``torch.nn.Module`` (residual adds,
        branches, concats — e.g. torchvision-style ResNets) via torch.fx
        symbolic tracing.  ``load_torch`` falls back to this automatically
        when the module is not a Sequential chain; TorchScript modules
        cannot be fx-traced and must convert via the chain path or the
        escape hatch."""
        return _load_torch_fx(module, example_input)

    @staticmethod
    def torch_params_to_tree(module: Any) -> Dict[str, np.ndarray]:
        """Escape hatch: every parameter and buffer as {dotted_name: array}."""
        out = {}
        for n, p in module.state_dict().items():
            out[n] = p.detach().cpu().numpy()
        return out

    # -- tf/keras --------------------------------------------------------------

    @staticmethod
    def load_tf(model_or_path: Any) -> ForeignNet:
        """Convert a ``tf.keras`` model (object, .h5/.keras file, or a
        SavedModel/keras dir) built as a Sequential chain of supported
        layers.  Non-Keras SavedModels (raw ConcreteFunctions) are not
        convertible — re-export through tf.keras or use the escape hatch."""
        import tensorflow as tf
        model = model_or_path
        if isinstance(model, str):
            model = tf.keras.models.load_model(model)
        if not isinstance(model, tf.keras.Sequential):
            # functional graph (branches/merges): walk the config DAG
            return _load_keras_functional(model)
        layers = [l for l in model.layers
                  if type(l).__name__ != "InputLayer"]
        stages: List[Tuple[str, Module]] = []
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        for i, layer in enumerate(layers):
            kind = type(layer).__name__
            name = f"{i}_{kind.lower()}"
            conv = _TF_CONVERTERS.get(kind)
            if conv is None:
                raise NotImplementedError(
                    f"keras layer {kind} is not in the supported conversion "
                    f"set {sorted(_TF_CONVERTERS)}; see the escape hatch in "
                    "analytics_zoo_tpu.models.net's docstring")
            mod, p, s = conv(layer)
            if mod is None:
                continue
            stages.append((name, mod))
            if p:
                params[name] = p
            if s:
                state[name] = s
        return ForeignNet(stages, {"params": params, "state": state},
                          source="tf")

    @staticmethod
    def load_keras(model_or_path: Any,
                   weights_path: Optional[str] = None) -> ForeignNet:
        """Reference parity (SURVEY.md §2.3 Net loaders): the reference's
        ``Net.load_keras(def_path, weights_path)`` took a Keras
        architecture-JSON definition plus an optional separate HDF5
        weights file.  Accepts that form (``.json`` def + weights), a
        single ``.h5``/``.keras``/SavedModel path, or a live keras model
        object; conversion itself is the ``load_tf`` path."""
        import tensorflow as tf
        model = model_or_path
        if isinstance(model, str) and model.endswith(".json"):
            with open(model) as f:
                model = tf.keras.models.model_from_json(f.read())
        elif isinstance(model, str):
            model = tf.keras.models.load_model(model)
        if weights_path is not None:
            model.load_weights(weights_path)
        return Net.load_tf(model)

    # -- consciously dropped formats ------------------------------------------

    @staticmethod
    def load_bigdl(*a: Any, **k: Any) -> None:
        raise NotImplementedError(
            "BigDL protobuf serialization is a JVM-era format with no "
            "TPU-side runtime; retrain or re-export via torch/keras "
            "(consciously dropped, SURVEY.md §2.3)")

    load_caffe = load_bigdl


# -- torch helpers -------------------------------------------------------------

def _torch_kind(m: Any) -> str:
    n = type(m).__name__
    if n == "RecursiveScriptModule":  # TorchScript wrapper
        return m.original_name
    return n


def _torch_leaves(m: Any) -> List[Any]:
    kids = list(m.children())
    if not kids:
        return [m]
    kind = _torch_kind(m)
    if kind not in ("Sequential", "ModuleList"):
        raise NotImplementedError(
            f"torch container {kind} does not guarantee Sequential "
            "execution; only nn.Sequential trees convert automatically "
            "(see the escape hatch in analytics_zoo_tpu.models.net)")
    out: List[Any] = []
    for k in kids:
        out.extend(_torch_leaves(k))
    return out


def _torch_trace_shapes(module: Any, leaves: List[Any], x: Any
                        ) -> List[Tuple[int, ...]]:
    """Input shape of every leaf, by running the chain leaf-by-leaf (valid
    because only Sequential trees are accepted; forward hooks would be the
    general tool but ScriptModules don't support them)."""
    import torch
    shapes: List[Tuple[int, ...]] = []
    with torch.no_grad():
        for leaf in leaves:
            shapes.append(tuple(x.shape))
            x = leaf(x)
    return shapes


def _np(t: Any) -> np.ndarray:
    return t.detach().cpu().numpy()


def _t_linear(m, in_shape, prev_flat):
    w = _np(m.weight)                    # [out, in]
    kernel = w.T.copy()                  # [in, out]
    if prev_flat is not None:
        # the Linear consumed a Flatten of NCHW maps, but the converted net
        # flattens NHWC: reorder kernel rows c*H*W+h*W+w → h*W*C+w*C+c
        _, c, h, wid = prev_flat
        perm = np.arange(c * h * wid).reshape(c, h, wid)
        perm = perm.transpose(1, 2, 0).reshape(-1)  # NHWC order → NCHW index
        kernel = kernel[perm]
    p = {"kernel": kernel}
    if m.bias is not None:
        p["bias"] = _np(m.bias)
    return nn.Dense(m.out_features, use_bias=m.bias is not None), p, {}


def _t_conv2d(m, in_shape, prev_flat):
    stride = tuple(m.stride)
    pad = m.padding
    k = tuple(m.kernel_size)
    if isinstance(pad, str):        # torch accepts 'same'/'valid' strings
        if pad == "valid":
            padding: Any = "valid"
        elif pad == "same" and stride == (1, 1):
            padding = "same"
        else:
            raise NotImplementedError(
                f"torch Conv2d padding={pad!r} stride={stride} has no "
                "exact equivalent; use the escape hatch")
    else:
        # numeric torch padding: exact via explicit (lo, hi) pairs —
        # torch pads symmetrically, which differs from XLA SAME at
        # stride > 1, so never approximate with "same" here
        pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
        padding = ((pad[0], pad[0]), (pad[1], pad[1]))
    p = {"kernel": _np(m.weight).transpose(2, 3, 1, 0)}  # OIHW → HWIO
    if m.bias is not None:
        p["bias"] = _np(m.bias)
    return (nn.Conv2D(m.out_channels, k, stride, padding,
                      use_bias=m.bias is not None, groups=m.groups,
                      dilation=tuple(m.dilation)), p, {})


def _t_batchnorm(m, in_shape, prev_flat):
    if m.running_mean is None:
        raise NotImplementedError(
            "BatchNorm with track_running_stats=False evaluates on batch "
            "statistics, which this converter's inference semantics don't "
            "replicate; use the escape hatch")
    if m.momentum is None:
        raise NotImplementedError(
            "BatchNorm with momentum=None (cumulative averaging) has no "
            "equivalent here; use the escape hatch")
    affine = m.weight is not None
    # torch: running = (1-mom)*running + mom*batch; ours: m*run + (1-m)*batch
    mod = nn.BatchNormalization(momentum=1.0 - m.momentum, epsilon=m.eps,
                                center=affine, scale=affine)
    p = ({"gamma": _np(m.weight), "beta": _np(m.bias)} if affine else {})
    s = {"mean": _np(m.running_mean), "var": _np(m.running_var)}
    return mod, p, s


def _t_layernorm(m, in_shape, prev_flat):
    if len(m.normalized_shape) != 1:
        raise NotImplementedError(
            f"LayerNorm over {len(m.normalized_shape)} trailing dims has no "
            "equivalent (last-axis only); use the escape hatch")
    if m.weight is None:
        raise NotImplementedError(
            "LayerNorm(elementwise_affine=False) is unsupported; use the "
            "escape hatch")
    return (nn.LayerNormalization(epsilon=m.eps),
            {"gamma": _np(m.weight), "beta": _np(m.bias)}, {})


def _t_embedding(m, in_shape, prev_flat):
    return (nn.Embedding(m.num_embeddings, m.embedding_dim),
            {"embeddings": _np(m.weight)}, {})


def _t_act(name):
    def conv(m, in_shape, prev_flat):
        return nn.Activation(name), {}, {}
    return conv


def _t_pool(kind):
    def conv(m, in_shape, prev_flat):
        k = m.kernel_size
        k = (k, k) if isinstance(k, int) else tuple(k)
        s = m.stride or k
        s = (s, s) if isinstance(s, int) else tuple(s)
        if getattr(m, "ceil_mode", False):
            raise NotImplementedError(
                "torch pooling with ceil_mode=True has no exact equivalent "
                "here; use the escape hatch")
        pad = m.padding
        pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
        if (kind == "avg" and pad != (0, 0)
                and not getattr(m, "count_include_pad", True)):
            raise NotImplementedError(
                "AvgPool2d(count_include_pad=False) with padding has no "
                "exact equivalent here; use the escape hatch")
        padding: Any = ("valid" if pad == (0, 0)
                        else ((pad[0], pad[0]), (pad[1], pad[1])))
        cls = nn.MaxPooling2D if kind == "max" else nn.AveragePooling2D
        return cls(k, s, padding=padding), {}, {}
    return conv


def _t_flatten(m, in_shape, prev_flat):
    return nn.Flatten(), {}, {}


def _t_dropout(m, in_shape, prev_flat):
    return nn.Dropout(m.p), {}, {}


def _t_adaptive_avg(m, in_shape, prev_flat):
    out = m.output_size
    out = (out, out) if isinstance(out, int) else tuple(out)
    if out not in ((1, 1), (1,)):
        raise NotImplementedError(
            "AdaptiveAvgPool2d converts only for output_size=1 "
            "(global average)")

    class _Glob(Module):
        def forward(self, scope, x):
            return x.mean(axis=(1, 2), keepdims=True)

    return _Glob(), {}, {}


_TORCH_CONVERTERS: Dict[str, Callable] = {
    "Linear": _t_linear,
    "Conv2d": _t_conv2d,
    "BatchNorm1d": _t_batchnorm,
    "BatchNorm2d": _t_batchnorm,
    "LayerNorm": _t_layernorm,
    "Embedding": _t_embedding,
    "ReLU": _t_act("relu"),
    # torch GELU defaults to the exact erf form; jax.nn.gelu defaults to
    # the tanh approximation — pick by the module's own setting
    "GELU": lambda m, s, f: (nn.Activation(
        (lambda x: jax.nn.gelu(x, approximate=False))
        if getattr(m, "approximate", "none") == "none"
        else (lambda x: jax.nn.gelu(x, approximate=True))), {}, {}),
    "Tanh": _t_act("tanh"),
    "Sigmoid": _t_act("sigmoid"),
    "Softmax": _t_act("softmax"),
    "Flatten": _t_flatten,
    "Dropout": _t_dropout,
    "MaxPool2d": _t_pool("max"),
    "AvgPool2d": _t_pool("avg"),
    "AdaptiveAvgPool2d": _t_adaptive_avg,
    "Identity": lambda m, s, f: (None, {}, {}),
}


# -- torch fx graph conversion -------------------------------------------------

# elementwise torch module kinds: safe to carry a pending Flatten->Linear
# kernel-reorder through (order-preserving on the flattened axis, no
# per-position parameters)
_ORDER_PRESERVING_KINDS = frozenset({
    "ReLU", "GELU", "Tanh", "Sigmoid", "Softmax", "Dropout", "Identity",
    "LeakyReLU", "ELU", "SiLU", "Hardswish",
})

# kinds with PER-POSITION parameters: applying them to an NCHW-flattened
# value would need their own param reorder, which is not implemented
_POSITIONAL_PARAM_KINDS = frozenset({"LayerNorm", "BatchNorm1d"})

def _load_torch_fx(module: Any, example_input: Any) -> ForeignGraphNet:
    """fx-trace a torch module and convert its DAG to a ForeignGraphNet.

    Layout invariant: every 4-D value in the converted graph is NHWC (the
    TPU-native layout); the net transposes at its input/output boundary.
    Shape metadata from torch's ShapeProp is NCHW and is used to (a) detect
    4-D values whose axis arguments need remapping (cat/softmax/mean) and
    (b) reorder Linear kernels that consume a flatten of feature maps."""
    import torch
    from torch import fx
    from torch.fx.passes.shape_prop import ShapeProp

    if isinstance(module, torch.jit.ScriptModule):
        raise NotImplementedError(
            "TorchScript modules cannot be fx-traced; only Sequential "
            "TorchScript chains convert (see the escape hatch in "
            "analytics_zoo_tpu.models.net)")
    module = module.eval()
    x = torch.as_tensor(np.asarray(example_input))
    try:
        traced = fx.symbolic_trace(module)
        ShapeProp(traced).propagate(x)
    except Exception as e:
        raise NotImplementedError(
            f"module could not be fx-traced for graph conversion ({e}); "
            "see the escape hatch in analytics_zoo_tpu.models.net's "
            "docstring") from e

    def shp(n) -> Optional[Tuple[int, ...]]:
        tm = n.meta.get("tensor_meta") if isinstance(n, fx.Node) else None
        return tuple(tm.shape) if tm is not None else None

    nchw = x.ndim == 4
    input_names: List[str] = []
    nodes: List[Dict] = []
    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    output_name: Optional[str] = None
    # env-name aliasing for identity nodes (Dropout-eval, .contiguous())
    alias: Dict[str, str] = {}
    # env name -> NCHW shape its value was flattened from (kernel reorder)
    flat_origin: Dict[str, Tuple[int, ...]] = {}
    # env name -> True if the value derives ONLY from constant tensors
    # (get_attr and ops thereof) and is non-scalar: such values kept
    # torch's natural (NCHW-flat) element order, so combining them
    # elementwise with a flattened NHWC feature map would misorder
    const_origin: Dict[str, bool] = {}

    def res(n) -> str:
        name = n.name
        while name in alias:
            name = alias[name]
        return name

    def refargs(args) -> List[Tuple[bool, Any]]:
        out = []
        for a in args:
            if isinstance(a, fx.Node):
                out.append((True, res(a)))
            else:
                out.append((False, a))
        return out

    for n in traced.graph.nodes:
        if n.op == "placeholder":
            input_names.append(n.name)
            continue
        if n.op == "output":
            arg = n.args[0]
            if not isinstance(arg, fx.Node):
                raise NotImplementedError(
                    "only single-tensor outputs convert; see the escape "
                    "hatch in analytics_zoo_tpu.models.net")
            output_name = res(arg)
            continue
        if n.op == "call_module":
            leaf = traced.get_submodule(n.target)
            kind = _torch_kind(leaf)
            conv = _TORCH_CONVERTERS.get(kind)
            if conv is None:
                raise NotImplementedError(
                    f"torch layer {kind} is not in the supported conversion "
                    f"set {sorted(_TORCH_CONVERTERS)}; see the escape hatch "
                    "in analytics_zoo_tpu.models.net's docstring")
            in_shape = shp(n.args[0]) or ()
            mod, p, s = conv(leaf, in_shape, flat_origin.get(res(n.args[0])))
            if kind == "Flatten" and len(in_shape) == 4:
                flat_origin[n.name] = in_shape
            elif kind in _ORDER_PRESERVING_KINDS:
                # elementwise module between Flatten and Linear: the
                # pending kernel-reorder flows through
                src = res(n.args[0])
                if src in flat_origin:
                    flat_origin[n.name] = flat_origin[src]
            elif (kind in _POSITIONAL_PARAM_KINDS
                  and res(n.args[0]) in flat_origin):
                raise NotImplementedError(
                    f"{kind} applied to a flattened NCHW feature map would "
                    "need its per-position parameters reordered, which is "
                    "unsupported; use the escape hatch")
            if mod is None:
                alias[n.name] = res(n.args[0])
                continue
            nodes.append({"name": n.name, "module": mod, "fn": None,
                          "args": refargs(n.args)})
            if p:
                params[n.name] = p
            if s:
                state[n.name] = s
            continue
        if n.op in ("call_function", "call_method"):
            handled = _fx_function(n, shp, res, refargs, alias, flat_origin,
                                   const_origin)
            if handled is not None:
                nodes.append(handled)
            # constant-ness flows through any op whose every node operand
            # is constant-derived (conservative: scalar-producing ops on
            # non-scalar constants stay flagged — a false raise is safe,
            # a silent misorder is not)
            operands = [a for a in n.args if isinstance(a, fx.Node)]
            if operands and all(res(a) in const_origin for a in operands):
                const_origin[res(n)] = any(const_origin[res(a)]
                                           for a in operands)
            continue
        if n.op == "get_attr":
            # a constant tensor/parameter referenced directly in forward;
            # 4-D constants are NCHW in torch but every 4-D value in the
            # converted graph is NHWC — transpose at the boundary
            t = traced
            for part in n.target.split("."):
                t = getattr(t, part)
            val = np.asarray(t.detach().cpu().numpy())
            if val.ndim == 4:
                val = val.transpose(0, 2, 3, 1)
            const_origin[n.name] = val.size > 1
            nodes.append({"name": n.name, "module": None,
                          "fn": (lambda v=val: jnp.asarray(v)), "args": []})
            continue
        raise NotImplementedError(f"fx op {n.op} is unsupported")

    if output_name is None:
        raise NotImplementedError("traced graph has no output node")
    return ForeignGraphNet(input_names, nodes, output_name,
                           {"params": params, "state": state},
                           source="torch", nchw_input=nchw)


def _fx_function(n, shp, res, refargs, alias, flat_origin,
                 const_origin) -> Optional[Dict]:
    """Convert one fx call_function/call_method node; returns a graph node,
    records an alias (identity ops), or raises for unsupported ops."""
    import operator as op
    import torch
    import torch.nn.functional as F
    from torch import fx

    target = n.target
    tname = target if isinstance(target, str) else getattr(
        target, "__name__", str(target))
    is4d = (shp(n.args[0]) is not None and len(shp(n.args[0])) == 4
            if n.args and isinstance(n.args[0], fx.Node) else False)

    def node(fn, args):
        return {"name": n.name, "module": None, "fn": fn,
                "args": refargs(args)}

    def propagate_flat():
        # order-preserving op: a pending flatten-reorder flows through.
        # The first NODE operand carries it (a constant operand, e.g. the
        # 1.0 in "1.0 - x", has no env name).
        for a in n.args:
            if isinstance(a, fx.Node):
                src = res(a)
                if src in flat_origin:
                    flat_origin[n.name] = flat_origin[src]
                return

    # elementwise arithmetic (operator.*, torch.*, tensor methods)
    binops = {
        ("add", "iadd", "add_"): lambda a, b: a + b,
        ("sub", "isub", "sub_"): lambda a, b: a - b,
        ("rsub",): lambda a, b: b - a,  # torch.rsub(x, o) == o - x
        ("mul", "imul", "mul_"): lambda a, b: a * b,
        ("truediv", "div", "div_"): lambda a, b: a / b,
    }
    for names, fn in binops.items():
        if tname in names:
            # a flattened operand is in NHWC-flat element order; a
            # constant-derived operand (get_attr, or any chain of ops on
            # constants — tracked in const_origin) kept torch's NCHW-flat
            # order, so a non-scalar constant combined elementwise would
            # silently misorder (same hazard _POSITIONAL_PARAM_KINDS
            # guards for modules)
            operands = [a for a in n.args[:2] if isinstance(a, fx.Node)]
            if any(res(a) in flat_origin for a in operands):
                for a in operands:
                    if const_origin.get(res(a)):
                        raise NotImplementedError(
                            f"elementwise {tname} between a flattened "
                            "NCHW feature map and a non-scalar constant "
                            "tensor would need the constant reordered "
                            "to NHWC-flat order, which is unsupported; "
                            "use the escape hatch")
            propagate_flat()
            return node(fn, n.args[:2])

    unary = {
        "relu": jax.nn.relu, "relu_": jax.nn.relu, "tanh": jnp.tanh,
        "sigmoid": jax.nn.sigmoid, "silu": jax.nn.silu,
        "hardswish": jax.nn.hard_swish, "abs": jnp.abs, "exp": jnp.exp,
    }
    if tname in unary:
        propagate_flat()
        return node(unary[tname], n.args[:1])

    if tname == "gelu":
        approx = n.kwargs.get("approximate", "none") != "none"
        return node(lambda v, a=approx: jax.nn.gelu(v, approximate=a),
                    n.args[:1])

    if tname in ("contiguous", "clone", "detach", "dropout"):
        # F.dropout converts to identity ONLY when its training flag is a
        # trace-time-constant False (e.g. `F.dropout(x, p, self.training)`
        # traced under .eval()).  torch's own default is training=True —
        # F.dropout with the flag absent drops even in module .eval() — so
        # an absent, truthy, or dynamic flag must raise, not silently
        # become identity.
        if tname == "dropout":
            if len(n.args) > 2:
                train_flag = n.args[2]
            else:
                train_flag = n.kwargs.get("training", True)
            if train_flag is not False:
                raise NotImplementedError(
                    "F.dropout without a trace-time-constant training=False "
                    "has no converted equivalent (torch's default is "
                    "training=True even under .eval()); use nn.Dropout "
                    "modules instead")
        alias[n.name] = res(n.args[0])
        # identity preserves any pending flatten-reorder
        src = res(n.args[0])
        if src in flat_origin:
            flat_origin[n.name] = flat_origin[src]
        return None

    if tname == "flatten":
        start = (n.args[1] if len(n.args) > 1
                 else n.kwargs.get("start_dim", 0))
        if start != 1:
            raise NotImplementedError(
                "only flatten(start_dim=1) converts; see the escape hatch")
        in_shape = shp(n.args[0])
        if in_shape is not None and len(in_shape) == 4:
            flat_origin[n.name] = in_shape
        return node(lambda v: v.reshape(v.shape[0], -1), n.args[:1])

    if tname in ("view", "reshape"):
        tail = n.args[1:]
        if len(tail) == 1 and isinstance(tail[0], (tuple, list)):
            tail = tuple(tail[0])
        # x.view(B, -1) / x.view(x.size(0), -1): first arg may be an fx
        # node (the batch size); only the trailing -1 matters
        if len(tail) == 2 and tail[1] == -1:
            in_shape = shp(n.args[0])
            if in_shape is not None and len(in_shape) == 4:
                flat_origin[n.name] = in_shape
            return node(lambda v: v.reshape(v.shape[0], -1), n.args[:1])
        raise NotImplementedError(
            f"{tname}{tuple(tail)} is unsupported (only (B, -1) flattens "
            "convert); see the escape hatch")

    if tname == "size":
        # x.size(d): a static int at conversion time (shapes are traced).
        # For 4-D values only the batch dim keeps its index in NHWC.
        if len(n.args) < 2:
            raise NotImplementedError(
                "x.size() as a tuple is unsupported; use the escape hatch")
        d = n.args[1]
        if is4d and d not in (0, -4):
            raise NotImplementedError(
                f"x.size({d}) on a 4-D NCHW tensor has a layout-dependent "
                "meaning after NHWC conversion; use the escape hatch")
        return node(lambda v, dd=d: v.shape[dd], n.args[:1])

    if tname in ("cat", "concat"):
        tensors = n.args[0]
        dim = (n.args[1] if len(n.args) > 1 else n.kwargs.get("dim", 0))
        if any(res(t) in flat_origin for t in tensors
               if isinstance(t, fx.Node)):
            raise NotImplementedError(
                "cat of flattened NCHW feature maps feeding a Linear would "
                "need a per-segment kernel reorder, which is unsupported; "
                "use the escape hatch")
        shapes = [shp(t) for t in tensors]
        if all(s is not None and len(s) == 4 for s in shapes):
            if dim in (1, -3):
                axis = -1          # channel concat in NHWC
            elif dim == 0:
                axis = 0
            else:
                raise NotImplementedError(
                    f"cat over NCHW dim {dim} has no NHWC mapping here")
        else:
            axis = dim
        return {"name": n.name, "module": None,
                "fn": (lambda *vs, a=axis: jnp.concatenate(vs, axis=a)),
                "args": [(True, res(t)) for t in tensors]}

    if tname == "softmax":
        dim = (n.args[1] if len(n.args) > 1 else n.kwargs.get("dim", -1))
        if is4d:
            # full NCHW->NHWC axis map: batch 0->0, C 1->-1, H 2->1, W 3->2
            dim = {0: 0, 1: -1, 2: 1, 3: 2}[dim % 4]
        return node(lambda v, d=dim: jax.nn.softmax(v, axis=d), n.args[:1])

    if tname == "mean":
        dims = (n.args[1] if len(n.args) > 1 else n.kwargs.get("dim"))
        keep = (n.args[2] if len(n.args) > 2
                else n.kwargs.get("keepdim", False))
        if dims is None:
            return node(lambda v: v.mean(), n.args[:1])
        dims = [dims] if isinstance(dims, int) else list(dims)
        if is4d:
            if sorted(d % 4 for d in dims) == [2, 3]:
                axes = (1, 2)      # spatial mean in NHWC
            else:
                raise NotImplementedError(
                    f"mean over NCHW dims {dims} has no NHWC mapping here")
        else:
            axes = tuple(dims)
        return node(lambda v, a=axes, k=keep: v.mean(axis=a, keepdims=k),
                    n.args[:1])

    if tname == "adaptive_avg_pool2d":
        out = n.args[1] if len(n.args) > 1 else n.kwargs.get("output_size")
        out = (out, out) if isinstance(out, int) else tuple(out)
        if out != (1, 1):
            raise NotImplementedError(
                "adaptive_avg_pool2d converts only for output_size=1")
        return node(lambda v: v.mean(axis=(1, 2), keepdims=True),
                    n.args[:1])

    if tname in ("max_pool2d", "avg_pool2d"):
        k = n.args[1] if len(n.args) > 1 else n.kwargs.get("kernel_size")
        s = (n.args[2] if len(n.args) > 2
             else n.kwargs.get("stride")) or k
        # F.max_pool2d(x, k, s, pad, dilation, ceil_mode); avg_pool2d has
        # no dilation and ceil_mode at position 4
        ceil_pos = 5 if tname == "max_pool2d" else 4
        if (n.kwargs.get("ceil_mode", False)
                or (len(n.args) > ceil_pos and n.args[ceil_pos])):
            raise NotImplementedError(
                "functional pooling with ceil_mode=True has no exact "
                "equivalent here; use the escape hatch")
        dil = (n.args[4] if (tname == "max_pool2d" and len(n.args) > 4)
               else n.kwargs.get("dilation", 1))
        if dil not in (1, (1, 1)):
            raise NotImplementedError(
                "functional max_pool2d with dilation has no equivalent "
                "here; use the escape hatch")
        pad = (n.args[3] if len(n.args) > 3 else n.kwargs.get("padding", 0))
        pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
        if (tname == "avg_pool2d" and pad != (0, 0)
                and not (n.args[5] if len(n.args) > 5
                         else n.kwargs.get("count_include_pad", True))):
            raise NotImplementedError(
                "avg_pool2d(count_include_pad=False) with padding has no "
                "exact equivalent here; use the escape hatch")
        padding: Any = ("valid" if pad == (0, 0)
                        else ((pad[0], pad[0]), (pad[1], pad[1])))
        k = (k, k) if isinstance(k, int) else tuple(k)
        s = (s, s) if isinstance(s, int) else tuple(s)
        cls = (nn.MaxPooling2D if tname == "max_pool2d"
               else nn.AveragePooling2D)
        return {"name": n.name, "module": cls(k, s, padding=padding),
                "fn": None, "args": refargs(n.args[:1])}

    raise NotImplementedError(
        f"torch op {tname!r} is not in the supported conversion set; see "
        "the escape hatch in analytics_zoo_tpu.models.net's docstring")


# -- keras helpers -------------------------------------------------------------

def _k_weights(layer) -> List[np.ndarray]:
    return [np.asarray(w) for w in layer.get_weights()]


# merge layers (functional graphs only): pure functions over the inbound list
_K_MERGES: Dict[str, Callable] = {
    "Add": lambda cfg: (lambda *vs: sum(vs[1:], vs[0])),
    "Subtract": lambda cfg: (lambda a, b: a - b),
    "Multiply": lambda cfg: (lambda *vs: _reduce(jnp.multiply, vs)),
    "Average": lambda cfg: (lambda *vs: sum(vs[1:], vs[0]) / len(vs)),
    "Maximum": lambda cfg: (lambda *vs: _reduce(jnp.maximum, vs)),
    "Minimum": lambda cfg: (lambda *vs: _reduce(jnp.minimum, vs)),
    "Concatenate": lambda cfg: (
        lambda *vs, a=cfg.get("axis", -1): jnp.concatenate(vs, axis=a)),
}


def _reduce(fn, vs):
    out = vs[0]
    for v in vs[1:]:
        out = fn(out, v)
    return out


def _keras_inbound(layer_cfg) -> List[str]:
    """Producer layer names feeding one layer, from its serialized inbound
    nodes.  Handles both the Keras 3 ``__keras_tensor__`` format and the
    legacy Keras 2 nested-list format."""
    nodes = layer_cfg.get("inbound_nodes", [])
    if len(nodes) != 1:
        raise NotImplementedError(
            f"layer {layer_cfg.get('name')!r} is applied {len(nodes)} times "
            "(shared layers are unsupported in conversion); see the escape "
            "hatch in analytics_zoo_tpu.models.net")
    names: List[str] = []

    def walk(obj):
        if isinstance(obj, dict):
            if obj.get("class_name") == "__keras_tensor__":
                hist = obj["config"]["keras_history"]
                if hist[1] != 0:
                    raise NotImplementedError(
                        "shared-layer tensors are unsupported in conversion")
                names.append(hist[0])
                return
            for v in obj.values():
                walk(v)
        elif isinstance(obj, (list, tuple)):
            # keras-2 format: ["layer_name", node_idx, tensor_idx, {...}]
            if (len(obj) >= 3 and isinstance(obj[0], str)
                    and isinstance(obj[1], int) and isinstance(obj[2], int)):
                names.append(obj[0])
                return
            for v in obj:
                walk(v)

    walk(nodes)
    return names


def _load_keras_functional(model) -> ForeignGraphNet:
    """Convert a functional tf.keras model (skip connections, merges) by
    walking its config DAG.  Keras is channels-last already, so no layout
    remapping is needed — node layers use the same converter table as the
    Sequential path, merge layers become pure functions."""
    cfg = model.get_config()
    by_name = {l.name: l for l in model.layers}
    out_spec = cfg.get("output_layers")
    # keras 3 flattens a single output to [name, node, tensor]; keras 2
    # keeps a list of such triples
    if (isinstance(out_spec, (list, tuple)) and len(out_spec) == 3
            and isinstance(out_spec[0], str)):
        out_spec = [out_spec]
    if not out_spec or len(out_spec) != 1:
        raise NotImplementedError(
            "multi-output functional models are unsupported in conversion; "
            "see the escape hatch in analytics_zoo_tpu.models.net")
    output_name = out_spec[0][0]

    input_names: List[str] = []
    nodes: List[Dict] = []
    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    alias: Dict[str, str] = {}

    def res(name: str) -> str:
        while name in alias:
            name = alias[name]
        return name

    # topological order over the config (config order is build order, but
    # sort explicitly so partial configs still convert)
    layer_cfgs = {l["name"]: l for l in cfg["layers"]}
    done: set = set()
    order: List[str] = []

    def visit(name: str, stack=()):
        if name in done:
            return
        if name in stack:
            raise ValueError(f"cycle at layer {name!r}")
        lc = layer_cfgs[name]
        if lc["class_name"] != "InputLayer":
            for dep in _keras_inbound(lc):
                visit(dep, stack + (name,))
        done.add(name)
        order.append(name)

    for l in cfg["layers"]:
        visit(l["name"])

    # input ORDER must come from the model spec (cfg['input_layers']), not
    # graph-walk order — Model(inputs=[a, b]) binds positionally
    in_spec = cfg.get("input_layers")
    if (isinstance(in_spec, (list, tuple)) and len(in_spec) == 3
            and isinstance(in_spec[0], str)):
        in_spec = [in_spec]
    declared_inputs = [t[0] for t in (in_spec or [])]

    for name in order:
        lc = layer_cfgs[name]
        kind = lc["class_name"]
        if kind == "InputLayer":
            input_names.append(name)
            continue
        inbound = [res(p) for p in _keras_inbound(lc)]
        if kind in _K_MERGES:
            fn = _K_MERGES[kind](lc.get("config", {}))
            nodes.append({"name": name, "module": None, "fn": fn,
                          "args": [(True, p) for p in inbound]})
            continue
        conv = _TF_CONVERTERS.get(kind)
        if conv is None:
            raise NotImplementedError(
                f"keras layer {kind} is not in the supported conversion "
                f"set {sorted(_TF_CONVERTERS) + sorted(_K_MERGES)}; see "
                "the escape hatch in analytics_zoo_tpu.models.net")
        mod, p, s = conv(by_name[name])
        if mod is None:
            alias[name] = inbound[0]
            continue
        nodes.append({"name": name, "module": mod, "fn": None,
                      "args": [(True, p) for p in inbound]})
        if p:
            params[name] = p
        if s:
            state[name] = s

    if declared_inputs and set(declared_inputs) == set(input_names):
        input_names = declared_inputs
    return ForeignGraphNet(input_names, nodes, res(output_name),
                           {"params": params, "state": state}, source="tf")


def _k_dense(layer):
    w = _k_weights(layer)
    cfg = layer.get_config()
    p = {"kernel": w[0]}
    if cfg.get("use_bias", True):
        p["bias"] = w[1]
    return (nn.Dense(cfg["units"], activation=cfg.get("activation"),
                     use_bias=cfg.get("use_bias", True)), p, {})


def _k_conv2d(layer):
    w = _k_weights(layer)
    cfg = layer.get_config()
    p = {"kernel": w[0]}  # keras stores HWIO already
    if cfg.get("use_bias", True):
        p["bias"] = w[1]
    return (nn.Conv2D(cfg["filters"], tuple(cfg["kernel_size"]),
                      tuple(cfg["strides"]), cfg["padding"],
                      activation=cfg.get("activation"),
                      use_bias=cfg.get("use_bias", True),
                      dilation=tuple(cfg.get("dilation_rate", (1, 1))),
                      groups=cfg.get("groups", 1)), p, {})


def _k_batchnorm(layer):
    cfg = layer.get_config()
    if cfg.get("axis") not in (-1, [len(layer.input.shape) - 1],
                               len(layer.input.shape) - 1, [-1], 3, [3]):
        raise NotImplementedError("BatchNormalization converts on the "
                                  "channel-last axis only")
    w = _k_weights(layer)
    i = 0
    p = {}
    if cfg.get("scale", True):
        p["gamma"] = w[i]; i += 1  # noqa: E702
    if cfg.get("center", True):
        p["beta"] = w[i]; i += 1  # noqa: E702
    s = {"mean": w[i], "var": w[i + 1]}
    return (nn.BatchNormalization(momentum=cfg["momentum"],
                                  epsilon=cfg["epsilon"],
                                  center=cfg.get("center", True),
                                  scale=cfg.get("scale", True)), p, s)


def _k_layernorm(layer):
    cfg = layer.get_config()
    w = _k_weights(layer)
    return (nn.LayerNormalization(epsilon=cfg["epsilon"]),
            {"gamma": w[0], "beta": w[1]}, {})


def _k_embedding(layer):
    cfg = layer.get_config()
    return (nn.Embedding(cfg["input_dim"], cfg["output_dim"]),
            {"embeddings": _k_weights(layer)[0]}, {})


def _k_pool(cls):
    def conv(layer):
        cfg = layer.get_config()
        return (cls(tuple(cfg["pool_size"]), tuple(cfg["strides"]),
                    cfg["padding"]), {}, {})
    return conv


def _k_simple(factory):
    return lambda layer: (factory(layer), {}, {})


_TF_CONVERTERS: Dict[str, Callable] = {
    "Dense": _k_dense,
    "Conv2D": _k_conv2d,
    "BatchNormalization": _k_batchnorm,
    "LayerNormalization": _k_layernorm,
    "Embedding": _k_embedding,
    "MaxPooling2D": _k_pool(nn.MaxPooling2D),
    "AveragePooling2D": _k_pool(nn.AveragePooling2D),
    "GlobalAveragePooling2D": _k_simple(
        lambda l: nn.GlobalAveragePooling2D()),
    "GlobalMaxPooling2D": _k_simple(lambda l: nn.GlobalMaxPooling2D()),
    "GlobalAveragePooling1D": _k_simple(
        lambda l: nn.GlobalAveragePooling1D()),
    "Flatten": _k_simple(lambda l: nn.Flatten()),
    "Dropout": _k_simple(lambda l: nn.Dropout(l.get_config()["rate"])),
    "Activation": _k_simple(
        lambda l: nn.Activation(l.get_config()["activation"])),
    "ReLU": _k_simple(lambda l: nn.Activation("relu")),
    "Softmax": _k_simple(lambda l: nn.Activation("softmax")),
}
