"""Foreign-model import: TF/Keras and PyTorch models → the nn module system.

Reference (SURVEY.md §2.3): the reference ran foreign models through JNI
engine bridges — TFNet executed frozen TF graphs via libtensorflow
(zoo/.../pipeline/api/net/TFNet.scala), TorchNet ran TorchScript via
libtorch (Torch*.scala), loaded from Python by ``Net.load_tf`` /
``Net.load_torch`` (pyzoo/zoo/pipeline/api/net.py).

TPU-native redesign: there is no second engine to bridge to — a foreign
model is *converted* into this framework's pure-function modules + a baked
variables pytree, then jit-compiles onto the TPU like any native model
(and can be fine-tuned by the Estimator, which the JNI bridges could not).
Conversion covers the common layer vocabulary (dense/conv/pool/norm/
embedding/activation chains — the zoo.models-scale subset); anything else
raises with pointers to the escape hatch:

  ESCAPE HATCH: write the forward as an ``nn.Module`` yourself and pour the
  foreign weights in via ``Net.torch_params_to_tree(mod)`` (name→array dict
  of every torch parameter/buffer) or ``model.get_weights()`` on the Keras
  side, then construct variables for your module directly.

Differential tests (tests/test_net.py) assert converted outputs match the
source framework within float tolerance — the SURVEY §4.4 pattern.
"""

from __future__ import annotations

import copy
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Params, Scope


class ForeignNet(Module):
    """A converted foreign model: a linear chain of native layers whose
    weights came from the source framework (baked into ``init``)."""

    def __init__(self, stages: Sequence[Tuple[str, Module]],
                 variables: Params, source: str, nchw_input: bool = False):
        super().__init__(name=None)
        self.stages = list(stages)
        self._variables = variables
        self.source = source
        #: torch convnets take NCHW; the converted net transposes to NHWC at
        #: the boundary so callers keep feeding torch-layout arrays
        self.nchw_input = nchw_input

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        if self.nchw_input and x.ndim == 4:
            x = jnp.transpose(x, (0, 2, 3, 1))
        for name, mod in self.stages:
            x = scope.child(mod, x, name=name)
        if self.nchw_input and x.ndim == 4:
            # symmetric boundary: a conv-ending net hands back torch layout
            x = jnp.transpose(x, (0, 3, 1, 2))
        return x

    def init(self, rng: jax.Array, *args: Any, **kwargs: Any) -> Params:
        """The imported weights, not a random init."""
        return jax.tree_util.tree_map(jnp.asarray,
                                      copy.deepcopy(self._variables))


class Net:
    """Loader namespace (reference: ``Net.load_tf/load_torch/load_bigdl``)."""

    # -- torch -----------------------------------------------------------------

    @staticmethod
    def load_torch(module: Any, example_input: Any) -> ForeignNet:
        """Convert a ``torch.nn.Module`` (or TorchScript file path) whose
        execution is a Sequential chain of supported leaf layers.

        ``example_input``: one real input batch (torch NCHW layout for conv
        nets) — used to trace per-layer input shapes, which the conversion
        needs (e.g. reordering Linear weights that follow a Flatten of NCHW
        feature maps into NHWC order)."""
        import torch
        if isinstance(module, str):
            try:
                module = torch.jit.load(module)
            except RuntimeError:
                module = torch.load(module, weights_only=False)
        module = module.eval()
        leaves = _torch_leaves(module)
        x = torch.as_tensor(np.asarray(example_input))
        shapes = _torch_trace_shapes(module, leaves, x)
        nchw = x.ndim == 4
        stages: List[Tuple[str, Module]] = []
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        # NCHW shape the last Flatten consumed; carried through order-
        # preserving layers (Dropout/activations) until the first Linear
        # uses it to reorder its kernel rows into NHWC flatten order
        flat_origin: Optional[Tuple[int, ...]] = None
        for i, leaf in enumerate(leaves):
            kind = _torch_kind(leaf)
            name = f"{i}_{kind.lower()}"
            conv = _TORCH_CONVERTERS.get(kind)
            if conv is None:
                raise NotImplementedError(
                    f"torch layer {kind} is not in the supported conversion "
                    f"set {sorted(_TORCH_CONVERTERS)}; see the escape hatch "
                    "in analytics_zoo_tpu.models.net's docstring")
            mod, p, s = conv(leaf, shapes[i], flat_origin)
            if kind == "Flatten" and len(shapes[i]) == 4:
                flat_origin = tuple(shapes[i])
            elif kind == "Linear":
                flat_origin = None  # consumed: later Linears see mixed space
            if mod is None:
                continue  # identity (e.g. Dropout at inference keeps staged)
            stages.append((name, mod))
            if p:
                params[name] = p
            if s:
                state[name] = s
        return ForeignNet(stages, {"params": params, "state": state},
                          source="torch", nchw_input=nchw)

    @staticmethod
    def torch_params_to_tree(module: Any) -> Dict[str, np.ndarray]:
        """Escape hatch: every parameter and buffer as {dotted_name: array}."""
        out = {}
        for n, p in module.state_dict().items():
            out[n] = p.detach().cpu().numpy()
        return out

    # -- tf/keras --------------------------------------------------------------

    @staticmethod
    def load_tf(model_or_path: Any) -> ForeignNet:
        """Convert a ``tf.keras`` model (object, .h5/.keras file, or a
        SavedModel/keras dir) built as a Sequential chain of supported
        layers.  Non-Keras SavedModels (raw ConcreteFunctions) are not
        convertible — re-export through tf.keras or use the escape hatch."""
        import tensorflow as tf
        model = model_or_path
        if isinstance(model, str):
            model = tf.keras.models.load_model(model)
        layers = [l for l in model.layers
                  if type(l).__name__ != "InputLayer"]
        if not isinstance(model, tf.keras.Sequential):
            # a functional graph can branch/merge in ways model.layers
            # order does not represent — inbound-node counting cannot
            # detect fan-out reliably, so only Sequential converts
            raise NotImplementedError(
                "only tf.keras.Sequential models convert automatically "
                "(functional graphs may branch); see the escape hatch in "
                "analytics_zoo_tpu.models.net's docstring")
        stages: List[Tuple[str, Module]] = []
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        for i, layer in enumerate(layers):
            kind = type(layer).__name__
            name = f"{i}_{kind.lower()}"
            conv = _TF_CONVERTERS.get(kind)
            if conv is None:
                raise NotImplementedError(
                    f"keras layer {kind} is not in the supported conversion "
                    f"set {sorted(_TF_CONVERTERS)}; see the escape hatch in "
                    "analytics_zoo_tpu.models.net's docstring")
            mod, p, s = conv(layer)
            if mod is None:
                continue
            stages.append((name, mod))
            if p:
                params[name] = p
            if s:
                state[name] = s
        return ForeignNet(stages, {"params": params, "state": state},
                          source="tf")

    # -- consciously dropped formats ------------------------------------------

    @staticmethod
    def load_bigdl(*a: Any, **k: Any) -> None:
        raise NotImplementedError(
            "BigDL protobuf serialization is a JVM-era format with no "
            "TPU-side runtime; retrain or re-export via torch/keras "
            "(consciously dropped, SURVEY.md §2.3)")

    load_caffe = load_bigdl


# -- torch helpers -------------------------------------------------------------

def _torch_kind(m: Any) -> str:
    n = type(m).__name__
    if n == "RecursiveScriptModule":  # TorchScript wrapper
        return m.original_name
    return n


def _torch_leaves(m: Any) -> List[Any]:
    kids = list(m.children())
    if not kids:
        return [m]
    kind = _torch_kind(m)
    if kind not in ("Sequential", "ModuleList"):
        raise NotImplementedError(
            f"torch container {kind} does not guarantee Sequential "
            "execution; only nn.Sequential trees convert automatically "
            "(see the escape hatch in analytics_zoo_tpu.models.net)")
    out: List[Any] = []
    for k in kids:
        out.extend(_torch_leaves(k))
    return out


def _torch_trace_shapes(module: Any, leaves: List[Any], x: Any
                        ) -> List[Tuple[int, ...]]:
    """Input shape of every leaf, by running the chain leaf-by-leaf (valid
    because only Sequential trees are accepted; forward hooks would be the
    general tool but ScriptModules don't support them)."""
    import torch
    shapes: List[Tuple[int, ...]] = []
    with torch.no_grad():
        for leaf in leaves:
            shapes.append(tuple(x.shape))
            x = leaf(x)
    return shapes


def _np(t: Any) -> np.ndarray:
    return t.detach().cpu().numpy()


def _t_linear(m, in_shape, prev_flat):
    w = _np(m.weight)                    # [out, in]
    kernel = w.T.copy()                  # [in, out]
    if prev_flat is not None:
        # the Linear consumed a Flatten of NCHW maps, but the converted net
        # flattens NHWC: reorder kernel rows c*H*W+h*W+w → h*W*C+w*C+c
        _, c, h, wid = prev_flat
        perm = np.arange(c * h * wid).reshape(c, h, wid)
        perm = perm.transpose(1, 2, 0).reshape(-1)  # NHWC order → NCHW index
        kernel = kernel[perm]
    p = {"kernel": kernel}
    if m.bias is not None:
        p["bias"] = _np(m.bias)
    return nn.Dense(m.out_features, use_bias=m.bias is not None), p, {}


def _t_conv2d(m, in_shape, prev_flat):
    stride = tuple(m.stride)
    pad = m.padding
    k = tuple(m.kernel_size)
    if isinstance(pad, str):        # torch accepts 'same'/'valid' directly
        pad = ((0, 0) if pad == "valid"
               else (k[0] // 2, k[1] // 2) if stride == (1, 1)
               else pad)            # 'same' at stride>1: fall through/raise
    elif isinstance(pad, int):
        pad = (pad, pad)
    else:
        pad = tuple(pad)
    if pad == (0, 0):
        padding = "valid"
    elif (stride == (1, 1) and k[0] % 2 == 1 and k[1] % 2 == 1
          and pad == (k[0] // 2, k[1] // 2)):
        padding = "same"   # exact equivalence only at stride 1 / odd kernel
    else:
        raise NotImplementedError(
            f"torch Conv2d padding={pad} stride={stride} has no exact "
            "same/valid equivalent; use the escape hatch")
    p = {"kernel": _np(m.weight).transpose(2, 3, 1, 0)}  # OIHW → HWIO
    if m.bias is not None:
        p["bias"] = _np(m.bias)
    return (nn.Conv2D(m.out_channels, k, stride, padding,
                      use_bias=m.bias is not None, groups=m.groups,
                      dilation=tuple(m.dilation)), p, {})


def _t_batchnorm(m, in_shape, prev_flat):
    if m.running_mean is None:
        raise NotImplementedError(
            "BatchNorm with track_running_stats=False evaluates on batch "
            "statistics, which this converter's inference semantics don't "
            "replicate; use the escape hatch")
    if m.momentum is None:
        raise NotImplementedError(
            "BatchNorm with momentum=None (cumulative averaging) has no "
            "equivalent here; use the escape hatch")
    affine = m.weight is not None
    # torch: running = (1-mom)*running + mom*batch; ours: m*run + (1-m)*batch
    mod = nn.BatchNormalization(momentum=1.0 - m.momentum, epsilon=m.eps,
                                center=affine, scale=affine)
    p = ({"gamma": _np(m.weight), "beta": _np(m.bias)} if affine else {})
    s = {"mean": _np(m.running_mean), "var": _np(m.running_var)}
    return mod, p, s


def _t_layernorm(m, in_shape, prev_flat):
    if len(m.normalized_shape) != 1:
        raise NotImplementedError(
            f"LayerNorm over {len(m.normalized_shape)} trailing dims has no "
            "equivalent (last-axis only); use the escape hatch")
    if m.weight is None:
        raise NotImplementedError(
            "LayerNorm(elementwise_affine=False) is unsupported; use the "
            "escape hatch")
    return (nn.LayerNormalization(epsilon=m.eps),
            {"gamma": _np(m.weight), "beta": _np(m.bias)}, {})


def _t_embedding(m, in_shape, prev_flat):
    return (nn.Embedding(m.num_embeddings, m.embedding_dim),
            {"embeddings": _np(m.weight)}, {})


def _t_act(name):
    def conv(m, in_shape, prev_flat):
        return nn.Activation(name), {}, {}
    return conv


def _t_pool(kind):
    def conv(m, in_shape, prev_flat):
        k = m.kernel_size
        k = (k, k) if isinstance(k, int) else tuple(k)
        s = m.stride or k
        s = (s, s) if isinstance(s, int) else tuple(s)
        pad = m.padding
        pad = (pad, pad) if isinstance(pad, int) else tuple(pad)
        if pad != (0, 0):
            raise NotImplementedError(
                "torch pooling with padding has no exact equivalent here; "
                "use the escape hatch")
        cls = nn.MaxPooling2D if kind == "max" else nn.AveragePooling2D
        return cls(k, s, padding="valid"), {}, {}
    return conv


def _t_flatten(m, in_shape, prev_flat):
    return nn.Flatten(), {}, {}


def _t_dropout(m, in_shape, prev_flat):
    return nn.Dropout(m.p), {}, {}


def _t_adaptive_avg(m, in_shape, prev_flat):
    out = m.output_size
    out = (out, out) if isinstance(out, int) else tuple(out)
    if out not in ((1, 1), (1,)):
        raise NotImplementedError(
            "AdaptiveAvgPool2d converts only for output_size=1 "
            "(global average)")

    class _Glob(Module):
        def forward(self, scope, x):
            return x.mean(axis=(1, 2), keepdims=True)

    return _Glob(), {}, {}


_TORCH_CONVERTERS: Dict[str, Callable] = {
    "Linear": _t_linear,
    "Conv2d": _t_conv2d,
    "BatchNorm1d": _t_batchnorm,
    "BatchNorm2d": _t_batchnorm,
    "LayerNorm": _t_layernorm,
    "Embedding": _t_embedding,
    "ReLU": _t_act("relu"),
    # torch GELU defaults to the exact erf form; jax.nn.gelu defaults to
    # the tanh approximation — pick by the module's own setting
    "GELU": lambda m, s, f: (nn.Activation(
        (lambda x: jax.nn.gelu(x, approximate=False))
        if getattr(m, "approximate", "none") == "none"
        else (lambda x: jax.nn.gelu(x, approximate=True))), {}, {}),
    "Tanh": _t_act("tanh"),
    "Sigmoid": _t_act("sigmoid"),
    "Softmax": _t_act("softmax"),
    "Flatten": _t_flatten,
    "Dropout": _t_dropout,
    "MaxPool2d": _t_pool("max"),
    "AvgPool2d": _t_pool("avg"),
    "AdaptiveAvgPool2d": _t_adaptive_avg,
    "Identity": lambda m, s, f: (None, {}, {}),
}


# -- keras helpers -------------------------------------------------------------

def _k_weights(layer) -> List[np.ndarray]:
    return [np.asarray(w) for w in layer.get_weights()]


def _k_dense(layer):
    w = _k_weights(layer)
    cfg = layer.get_config()
    p = {"kernel": w[0]}
    if cfg.get("use_bias", True):
        p["bias"] = w[1]
    return (nn.Dense(cfg["units"], activation=cfg.get("activation"),
                     use_bias=cfg.get("use_bias", True)), p, {})


def _k_conv2d(layer):
    w = _k_weights(layer)
    cfg = layer.get_config()
    p = {"kernel": w[0]}  # keras stores HWIO already
    if cfg.get("use_bias", True):
        p["bias"] = w[1]
    return (nn.Conv2D(cfg["filters"], tuple(cfg["kernel_size"]),
                      tuple(cfg["strides"]), cfg["padding"],
                      activation=cfg.get("activation"),
                      use_bias=cfg.get("use_bias", True),
                      dilation=tuple(cfg.get("dilation_rate", (1, 1))),
                      groups=cfg.get("groups", 1)), p, {})


def _k_batchnorm(layer):
    cfg = layer.get_config()
    if cfg.get("axis") not in (-1, [len(layer.input.shape) - 1],
                               len(layer.input.shape) - 1, [-1], 3, [3]):
        raise NotImplementedError("BatchNormalization converts on the "
                                  "channel-last axis only")
    w = _k_weights(layer)
    i = 0
    p = {}
    if cfg.get("scale", True):
        p["gamma"] = w[i]; i += 1  # noqa: E702
    if cfg.get("center", True):
        p["beta"] = w[i]; i += 1  # noqa: E702
    s = {"mean": w[i], "var": w[i + 1]}
    return (nn.BatchNormalization(momentum=cfg["momentum"],
                                  epsilon=cfg["epsilon"],
                                  center=cfg.get("center", True),
                                  scale=cfg.get("scale", True)), p, s)


def _k_layernorm(layer):
    cfg = layer.get_config()
    w = _k_weights(layer)
    return (nn.LayerNormalization(epsilon=cfg["epsilon"]),
            {"gamma": w[0], "beta": w[1]}, {})


def _k_embedding(layer):
    cfg = layer.get_config()
    return (nn.Embedding(cfg["input_dim"], cfg["output_dim"]),
            {"embeddings": _k_weights(layer)[0]}, {})


def _k_pool(cls):
    def conv(layer):
        cfg = layer.get_config()
        return (cls(tuple(cfg["pool_size"]), tuple(cfg["strides"]),
                    cfg["padding"]), {}, {})
    return conv


def _k_simple(factory):
    return lambda layer: (factory(layer), {}, {})


_TF_CONVERTERS: Dict[str, Callable] = {
    "Dense": _k_dense,
    "Conv2D": _k_conv2d,
    "BatchNormalization": _k_batchnorm,
    "LayerNormalization": _k_layernorm,
    "Embedding": _k_embedding,
    "MaxPooling2D": _k_pool(nn.MaxPooling2D),
    "AveragePooling2D": _k_pool(nn.AveragePooling2D),
    "GlobalAveragePooling2D": _k_simple(
        lambda l: nn.GlobalAveragePooling2D()),
    "GlobalMaxPooling2D": _k_simple(lambda l: nn.GlobalMaxPooling2D()),
    "GlobalAveragePooling1D": _k_simple(
        lambda l: nn.GlobalAveragePooling1D()),
    "Flatten": _k_simple(lambda l: nn.Flatten()),
    "Dropout": _k_simple(lambda l: nn.Dropout(l.get_config()["rate"])),
    "Activation": _k_simple(
        lambda l: nn.Activation(l.get_config()["activation"])),
    "ReLU": _k_simple(lambda l: nn.Activation("relu")),
    "Softmax": _k_simple(lambda l: nn.Activation("softmax")),
}
