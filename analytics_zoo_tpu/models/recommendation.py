"""Recommendation models (reference: zoo.models.recommendation —
Scala models/recommendation/ + pyzoo/zoo/models/recommendation/).

NeuralCF (GMF + MLP twin towers), WideAndDeep (wide cross features + deep
embeddings), SessionRecommender (GRU over session clicks, optional history
feedback), plus the UserItemFeature/UserItemPrediction record helpers and the
``recommend_for_user`` / ``recommend_for_item`` APIs.

TPU-native notes: embeddings gather onto the MXU-friendly [B, D] layout; the
recommend_* APIs batch all candidate pairs into one device sweep instead of
the reference's per-RDD-record scoring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from .common import ZooModel


@dataclass
class UserItemFeature:
    user_id: int
    item_id: int
    label: Optional[int] = None


@dataclass
class UserItemPrediction:
    user_id: int
    item_id: int
    prediction: int
    probability: float


def _make_embedding(count: int, dim: int, sharded: bool):
    """The embedding layer behind one id column: the classic replicated
    ``nn.Embedding`` (default — bit-identical to the pre-sharding models),
    or ``parallel.ShardedEmbedding`` (row-partitioned table, deduped
    gather, sparse scatter-add grads) when ``sharded_embeddings=True``."""
    if sharded:
        from analytics_zoo_tpu.parallel.embedding import ShardedEmbedding
        return ShardedEmbedding(count, dim)
    return nn.Embedding(count, dim)


class NeuralCF(ZooModel):
    """Neural Collaborative Filtering: GMF ⊙ + MLP concat towers
    (reference: models/recommendation/NeuralCF.scala; He et al. NCF).

    ``sharded_embeddings=True`` swaps every id table for a
    ``parallel.ShardedEmbedding`` — same child names and initializer, so
    checkpoints keep their paths — enabling mesh-partitioned tables and
    the estimator's sparse-gradient path for user/item counts too large
    to replicate.  Default off; the default path is unchanged."""

    def __init__(self, user_count: int, item_count: int, class_num: int = 2,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20,
                 sharded_embeddings: bool = False):
        super().__init__()
        self._config = dict(user_count=user_count, item_count=item_count,
                            class_num=class_num, user_embed=user_embed,
                            item_embed=item_embed,
                            hidden_layers=list(hidden_layers),
                            include_mf=include_mf, mf_embed=mf_embed,
                            sharded_embeddings=sharded_embeddings)
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        self.user_embed = user_embed
        self.item_embed = item_embed
        self.hidden_layers = list(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = mf_embed
        self.sharded_embeddings = sharded_embeddings

    def forward(self, scope, x):
        """x: int [B, 2] — (user_id, item_id), ids in [0, count)."""
        users, items = x[:, 0], x[:, 1]
        sh = self.sharded_embeddings
        ue = scope.child(_make_embedding(self.user_count, self.user_embed,
                                         sh), users, name="mlp_user_embed")
        ie = scope.child(_make_embedding(self.item_count, self.item_embed,
                                         sh), items, name="mlp_item_embed")
        h = jnp.concatenate([ue, ie], axis=-1)
        for i, units in enumerate(self.hidden_layers):
            h = scope.child(nn.Dense(units, activation="relu"), h,
                            name=f"mlp_{i}")
        if self.include_mf:
            mu = scope.child(_make_embedding(self.user_count, self.mf_embed,
                                             sh), users,
                             name="mf_user_embed")
            mi = scope.child(_make_embedding(self.item_count, self.mf_embed,
                                             sh), items,
                             name="mf_item_embed")
            h = jnp.concatenate([mu * mi, h], axis=-1)
        return scope.child(nn.Dense(self.class_num), h, name="head")

    # -- cached-serving split -------------------------------------------------

    def embedding_columns(self):
        """The id-table columns this model gathers per example, in the
        order ``NCFTail`` consumes them: (name, which id, table param
        path) — the contract between ``serving_split`` tables and the
        serving-side cache."""
        cols = [("mlp_user_embed", "user"), ("mlp_item_embed", "item")]
        if self.include_mf:
            cols += [("mf_user_embed", "user"), ("mf_item_embed", "item")]
        return cols

    def serving_split(self, variables):
        """Split trained ``variables`` for the cached serving path:
        ``(tables, tail_module, tail_variables)`` where ``tables`` maps
        embedding child name → host ``np.ndarray`` table and the tail is
        an ``NCFTail`` whose child names match this model's — trained
        dense params apply to it unchanged.  The serving side gathers
        rows (through ``serving.EmbedCache``) and runs only the tail on
        device."""
        params = variables["params"]
        leaf = ("sharded_embeddings" if self.sharded_embeddings
                else "embeddings")
        tables = {name: np.asarray(params[name][leaf])
                  for name, _ in self.embedding_columns()}
        tail_keys = [f"mlp_{i}" for i in range(len(self.hidden_layers))]
        tail_keys.append("head")
        tail_vars = {"params": {k: params[k] for k in tail_keys},
                     "state": {}}
        return tables, NCFTail(self), tail_vars

    # -- reference recommend APIs --------------------------------------------

    def recommend_for_user(self, user_ids: Sequence[int], max_items: int = 5
                           ) -> List[UserItemPrediction]:
        """Score every item for each user; top-k per user."""
        return _recommend(self, user_ids, np.arange(self.item_count),
                          per="user", k=max_items)

    def recommend_for_item(self, item_ids: Sequence[int], max_users: int = 5
                           ) -> List[UserItemPrediction]:
        return _recommend(self, np.arange(self.user_count), item_ids,
                          per="item", k=max_users)


class NCFTail(ZooModel):
    """NeuralCF minus its embedding gathers: input is the concatenation
    of the already-gathered embedding vectors ``[ue | ie | mu | mi]``
    (``[ue | ie]`` without MF), output the class logits.  Child names
    (``mlp_i``, ``head``) mirror ``NeuralCF``'s, so the dense params from
    a trained NCF apply directly — ``NeuralCF.serving_split`` extracts
    both halves.  This is the on-device part of the cached serving path:
    the host cache supplies rows, the tail is a small dense MLP."""

    def __init__(self, ncf: "NeuralCF"):
        super().__init__()
        self.user_embed = ncf.user_embed
        self.item_embed = ncf.item_embed
        self.hidden_layers = list(ncf.hidden_layers)
        self.include_mf = ncf.include_mf
        self.mf_embed = ncf.mf_embed
        self.class_num = ncf.class_num

    def input_dim(self) -> int:
        return (self.user_embed + self.item_embed
                + (2 * self.mf_embed if self.include_mf else 0))

    def forward(self, scope, x):
        cut = self.user_embed + self.item_embed
        h = x[:, :cut]
        for i, units in enumerate(self.hidden_layers):
            h = scope.child(nn.Dense(units, activation="relu"), h,
                            name=f"mlp_{i}")
        if self.include_mf:
            mu = x[:, cut:cut + self.mf_embed]
            mi = x[:, cut + self.mf_embed:cut + 2 * self.mf_embed]
            h = jnp.concatenate([mu * mi, h], axis=-1)
        return scope.child(nn.Dense(self.class_num), h, name="head")


class WideAndDeep(ZooModel):
    """Wide & Deep (reference: models/recommendation/WideAndDeep.scala).

    Wide: sparse cross/base columns via a linear hashed-feature layer.
    Deep: embedded categorical + dense numeric columns through an MLP.
    Input x: float [B, wide_dim + n_embed_cols + cont_dim] laid out as
    [wide multi-hot | embed col ids | continuous].
    """

    def __init__(self, class_num: int = 2, model_type: str = "wide_n_deep",
                 wide_base_dims: Sequence[int] = (),
                 wide_cross_dims: Sequence[int] = (),
                 indicator_dims: Sequence[int] = (),
                 embed_in_dims: Sequence[int] = (),
                 embed_out_dims: Sequence[int] = (),
                 continuous_cols: int = 0,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 sharded_embeddings: bool = False):
        super().__init__()
        self._config = dict(class_num=class_num, model_type=model_type,
                            wide_base_dims=list(wide_base_dims),
                            wide_cross_dims=list(wide_cross_dims),
                            indicator_dims=list(indicator_dims),
                            embed_in_dims=list(embed_in_dims),
                            embed_out_dims=list(embed_out_dims),
                            continuous_cols=continuous_cols,
                            hidden_layers=list(hidden_layers),
                            sharded_embeddings=sharded_embeddings)
        for k, v in self._config.items():
            setattr(self, k, v)
        self.wide_dim = sum(wide_base_dims) + sum(wide_cross_dims)
        self.indicator_dim = sum(indicator_dims)

    def forward(self, scope, x):
        parts = []
        ofs = 0
        wide = x[:, ofs:ofs + self.wide_dim]
        ofs += self.wide_dim
        indicator = x[:, ofs:ofs + self.indicator_dim]
        ofs += self.indicator_dim
        embeds = []
        for i, (in_dim, out_dim) in enumerate(zip(self.embed_in_dims,
                                                  self.embed_out_dims)):
            ids = x[:, ofs].astype(jnp.int32)
            ofs += 1
            embeds.append(scope.child(
                _make_embedding(in_dim, out_dim, self.sharded_embeddings),
                ids, name=f"embed_{i}"))
        cont = x[:, ofs:ofs + self.continuous_cols]

        if self.model_type in ("wide", "wide_n_deep"):
            parts.append(scope.child(nn.Dense(self.class_num, use_bias=False),
                                     wide, name="wide"))
        if self.model_type in ("deep", "wide_n_deep"):
            deep_in = jnp.concatenate(
                [indicator] + embeds + ([cont] if self.continuous_cols else []),
                axis=-1)
            h = deep_in
            for i, units in enumerate(self.hidden_layers):
                h = scope.child(nn.Dense(units, activation="relu"), h,
                                name=f"deep_{i}")
            parts.append(scope.child(nn.Dense(self.class_num), h,
                                     name="deep_out"))
        out = parts[0]
        for p in parts[1:]:
            out = out + p
        return out


class SessionRecommender(ZooModel):
    """GRU session-based recommender (reference:
    models/recommendation/SessionRecommender.scala): GRU over the session
    click sequence, optional MLP over the longer-term purchase history."""

    def __init__(self, item_count: int, item_embed: int = 32,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 10, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 5):
        super().__init__()
        self._config = dict(item_count=item_count, item_embed=item_embed,
                            rnn_hidden_layers=list(rnn_hidden_layers),
                            session_length=session_length,
                            include_history=include_history,
                            mlp_hidden_layers=list(mlp_hidden_layers),
                            history_length=history_length)
        for k, v in self._config.items():
            setattr(self, k, v)

    def forward(self, scope, x):
        """x: int [B, session_length(+history_length)] item ids."""
        sess = x[:, :self.session_length]
        e = scope.child(nn.Embedding(self.item_count, self.item_embed),
                        sess, name="item_embed")
        h = e
        for i, units in enumerate(self.rnn_hidden_layers[:-1]):
            h = scope.child(nn.GRU(units, return_sequences=True), h,
                            name=f"gru_{i}")
        h = scope.child(nn.GRU(self.rnn_hidden_layers[-1]), h, name="gru_out")
        if self.include_history:
            hist = x[:, self.session_length:
                     self.session_length + self.history_length]
            he = scope.child(nn.Embedding(self.item_count, self.item_embed),
                             hist, name="hist_embed").mean(axis=1)
            m = he
            for i, units in enumerate(self.mlp_hidden_layers):
                m = scope.child(nn.Dense(units, activation="relu"), m,
                                name=f"mlp_{i}")
            h = jnp.concatenate([h, m], axis=-1)
        return scope.child(nn.Dense(self.item_count), h, name="head")

    def recommend_for_session(self, sessions: np.ndarray, max_items: int = 5
                              ) -> List[List[tuple]]:
        """Top-k next items per session; returns [(item, prob), ...] rows."""
        probs = jax.nn.softmax(jnp.asarray(
            self.predict(np.asarray(sessions))), axis=-1)
        probs = np.asarray(probs)
        out = []
        for row in probs:
            top = np.argsort(-row)[:max_items]
            out.append([(int(i), float(row[i])) for i in top])
        return out


def _recommend(model: ZooModel, user_ids, item_ids, per: str, k: int
               ) -> List[UserItemPrediction]:
    user_ids = np.asarray(list(user_ids))
    item_ids = np.asarray(list(item_ids))
    pairs = np.stack([np.repeat(user_ids, len(item_ids)),
                      np.tile(item_ids, len(user_ids))], axis=1)
    logits = model.predict(pairs.astype(np.int32))
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    cls = probs.argmax(-1)
    results: List[UserItemPrediction] = []
    n_u, n_i = len(user_ids), len(item_ids)
    # rank AND report by P(positive) = 1 - P(class 0), so a confidently
    # negative item never surfaces with a high probability attached
    pos_prob = 1.0 - probs[:, 0]
    grid = pos_prob.reshape(n_u, n_i)
    if per == "user":
        for ui, u in enumerate(user_ids):
            top = np.argsort(-grid[ui])[:k]
            for ii in top:
                idx = ui * n_i + ii
                results.append(UserItemPrediction(
                    int(u), int(item_ids[ii]), int(cls[idx]),
                    float(pos_prob[idx])))
    else:
        for ii, it in enumerate(item_ids):
            top = np.argsort(-grid[:, ii])[:k]
            for ui in top:
                idx = ui * n_i + ii
                results.append(UserItemPrediction(
                    int(user_ids[ui]), int(it), int(cls[idx]),
                    float(pos_prob[idx])))
    return results
