"""Recommendation models (reference: zoo.models.recommendation —
Scala models/recommendation/ + pyzoo/zoo/models/recommendation/).

NeuralCF (GMF + MLP twin towers), WideAndDeep (wide cross features + deep
embeddings), SessionRecommender (GRU over session clicks, optional history
feedback), plus the UserItemFeature/UserItemPrediction record helpers and the
``recommend_for_user`` / ``recommend_for_item`` APIs.

TPU-native notes: embeddings gather onto the MXU-friendly [B, D] layout; the
recommend_* APIs batch all candidate pairs into one device sweep instead of
the reference's per-RDD-record scoring.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from .common import ZooModel


@dataclass
class UserItemFeature:
    user_id: int
    item_id: int
    label: Optional[int] = None


@dataclass
class UserItemPrediction:
    user_id: int
    item_id: int
    prediction: int
    probability: float


class NeuralCF(ZooModel):
    """Neural Collaborative Filtering: GMF ⊙ + MLP concat towers
    (reference: models/recommendation/NeuralCF.scala; He et al. NCF)."""

    def __init__(self, user_count: int, item_count: int, class_num: int = 2,
                 user_embed: int = 20, item_embed: int = 20,
                 hidden_layers: Sequence[int] = (40, 20, 10),
                 include_mf: bool = True, mf_embed: int = 20):
        super().__init__()
        self._config = dict(user_count=user_count, item_count=item_count,
                            class_num=class_num, user_embed=user_embed,
                            item_embed=item_embed,
                            hidden_layers=list(hidden_layers),
                            include_mf=include_mf, mf_embed=mf_embed)
        self.user_count = user_count
        self.item_count = item_count
        self.class_num = class_num
        self.user_embed = user_embed
        self.item_embed = item_embed
        self.hidden_layers = list(hidden_layers)
        self.include_mf = include_mf
        self.mf_embed = mf_embed

    def forward(self, scope, x):
        """x: int [B, 2] — (user_id, item_id), ids in [0, count)."""
        users, items = x[:, 0], x[:, 1]
        ue = scope.child(nn.Embedding(self.user_count, self.user_embed),
                         users, name="mlp_user_embed")
        ie = scope.child(nn.Embedding(self.item_count, self.item_embed),
                         items, name="mlp_item_embed")
        h = jnp.concatenate([ue, ie], axis=-1)
        for i, units in enumerate(self.hidden_layers):
            h = scope.child(nn.Dense(units, activation="relu"), h,
                            name=f"mlp_{i}")
        if self.include_mf:
            mu = scope.child(nn.Embedding(self.user_count, self.mf_embed),
                             users, name="mf_user_embed")
            mi = scope.child(nn.Embedding(self.item_count, self.mf_embed),
                             items, name="mf_item_embed")
            h = jnp.concatenate([mu * mi, h], axis=-1)
        return scope.child(nn.Dense(self.class_num), h, name="head")

    # -- reference recommend APIs --------------------------------------------

    def recommend_for_user(self, user_ids: Sequence[int], max_items: int = 5
                           ) -> List[UserItemPrediction]:
        """Score every item for each user; top-k per user."""
        return _recommend(self, user_ids, np.arange(self.item_count),
                          per="user", k=max_items)

    def recommend_for_item(self, item_ids: Sequence[int], max_users: int = 5
                           ) -> List[UserItemPrediction]:
        return _recommend(self, np.arange(self.user_count), item_ids,
                          per="item", k=max_users)


class WideAndDeep(ZooModel):
    """Wide & Deep (reference: models/recommendation/WideAndDeep.scala).

    Wide: sparse cross/base columns via a linear hashed-feature layer.
    Deep: embedded categorical + dense numeric columns through an MLP.
    Input x: float [B, wide_dim + n_embed_cols + cont_dim] laid out as
    [wide multi-hot | embed col ids | continuous].
    """

    def __init__(self, class_num: int = 2, model_type: str = "wide_n_deep",
                 wide_base_dims: Sequence[int] = (),
                 wide_cross_dims: Sequence[int] = (),
                 indicator_dims: Sequence[int] = (),
                 embed_in_dims: Sequence[int] = (),
                 embed_out_dims: Sequence[int] = (),
                 continuous_cols: int = 0,
                 hidden_layers: Sequence[int] = (40, 20, 10)):
        super().__init__()
        self._config = dict(class_num=class_num, model_type=model_type,
                            wide_base_dims=list(wide_base_dims),
                            wide_cross_dims=list(wide_cross_dims),
                            indicator_dims=list(indicator_dims),
                            embed_in_dims=list(embed_in_dims),
                            embed_out_dims=list(embed_out_dims),
                            continuous_cols=continuous_cols,
                            hidden_layers=list(hidden_layers))
        for k, v in self._config.items():
            setattr(self, k, v)
        self.wide_dim = sum(wide_base_dims) + sum(wide_cross_dims)
        self.indicator_dim = sum(indicator_dims)

    def forward(self, scope, x):
        parts = []
        ofs = 0
        wide = x[:, ofs:ofs + self.wide_dim]
        ofs += self.wide_dim
        indicator = x[:, ofs:ofs + self.indicator_dim]
        ofs += self.indicator_dim
        embeds = []
        for i, (in_dim, out_dim) in enumerate(zip(self.embed_in_dims,
                                                  self.embed_out_dims)):
            ids = x[:, ofs].astype(jnp.int32)
            ofs += 1
            embeds.append(scope.child(nn.Embedding(in_dim, out_dim), ids,
                                      name=f"embed_{i}"))
        cont = x[:, ofs:ofs + self.continuous_cols]

        if self.model_type in ("wide", "wide_n_deep"):
            parts.append(scope.child(nn.Dense(self.class_num, use_bias=False),
                                     wide, name="wide"))
        if self.model_type in ("deep", "wide_n_deep"):
            deep_in = jnp.concatenate(
                [indicator] + embeds + ([cont] if self.continuous_cols else []),
                axis=-1)
            h = deep_in
            for i, units in enumerate(self.hidden_layers):
                h = scope.child(nn.Dense(units, activation="relu"), h,
                                name=f"deep_{i}")
            parts.append(scope.child(nn.Dense(self.class_num), h,
                                     name="deep_out"))
        out = parts[0]
        for p in parts[1:]:
            out = out + p
        return out


class SessionRecommender(ZooModel):
    """GRU session-based recommender (reference:
    models/recommendation/SessionRecommender.scala): GRU over the session
    click sequence, optional MLP over the longer-term purchase history."""

    def __init__(self, item_count: int, item_embed: int = 32,
                 rnn_hidden_layers: Sequence[int] = (40, 20),
                 session_length: int = 10, include_history: bool = False,
                 mlp_hidden_layers: Sequence[int] = (40, 20),
                 history_length: int = 5):
        super().__init__()
        self._config = dict(item_count=item_count, item_embed=item_embed,
                            rnn_hidden_layers=list(rnn_hidden_layers),
                            session_length=session_length,
                            include_history=include_history,
                            mlp_hidden_layers=list(mlp_hidden_layers),
                            history_length=history_length)
        for k, v in self._config.items():
            setattr(self, k, v)

    def forward(self, scope, x):
        """x: int [B, session_length(+history_length)] item ids."""
        sess = x[:, :self.session_length]
        e = scope.child(nn.Embedding(self.item_count, self.item_embed),
                        sess, name="item_embed")
        h = e
        for i, units in enumerate(self.rnn_hidden_layers[:-1]):
            h = scope.child(nn.GRU(units, return_sequences=True), h,
                            name=f"gru_{i}")
        h = scope.child(nn.GRU(self.rnn_hidden_layers[-1]), h, name="gru_out")
        if self.include_history:
            hist = x[:, self.session_length:
                     self.session_length + self.history_length]
            he = scope.child(nn.Embedding(self.item_count, self.item_embed),
                             hist, name="hist_embed").mean(axis=1)
            m = he
            for i, units in enumerate(self.mlp_hidden_layers):
                m = scope.child(nn.Dense(units, activation="relu"), m,
                                name=f"mlp_{i}")
            h = jnp.concatenate([h, m], axis=-1)
        return scope.child(nn.Dense(self.item_count), h, name="head")

    def recommend_for_session(self, sessions: np.ndarray, max_items: int = 5
                              ) -> List[List[tuple]]:
        """Top-k next items per session; returns [(item, prob), ...] rows."""
        probs = jax.nn.softmax(jnp.asarray(
            self.predict(np.asarray(sessions))), axis=-1)
        probs = np.asarray(probs)
        out = []
        for row in probs:
            top = np.argsort(-row)[:max_items]
            out.append([(int(i), float(row[i])) for i in top])
        return out


def _recommend(model: ZooModel, user_ids, item_ids, per: str, k: int
               ) -> List[UserItemPrediction]:
    user_ids = np.asarray(list(user_ids))
    item_ids = np.asarray(list(item_ids))
    pairs = np.stack([np.repeat(user_ids, len(item_ids)),
                      np.tile(item_ids, len(user_ids))], axis=1)
    logits = model.predict(pairs.astype(np.int32))
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    cls = probs.argmax(-1)
    results: List[UserItemPrediction] = []
    n_u, n_i = len(user_ids), len(item_ids)
    # rank AND report by P(positive) = 1 - P(class 0), so a confidently
    # negative item never surfaces with a high probability attached
    pos_prob = 1.0 - probs[:, 0]
    grid = pos_prob.reshape(n_u, n_i)
    if per == "user":
        for ui, u in enumerate(user_ids):
            top = np.argsort(-grid[ui])[:k]
            for ii in top:
                idx = ui * n_i + ii
                results.append(UserItemPrediction(
                    int(u), int(item_ids[ii]), int(cls[idx]),
                    float(pos_prob[idx])))
    else:
        for ii, it in enumerate(item_ids):
            top = np.argsort(-grid[:, ii])[:k]
            for ui in top:
                idx = ui * n_i + ii
                results.append(UserItemPrediction(
                    int(user_ids[ui]), int(it), int(cls[idx]),
                    float(pos_prob[idx])))
    return results
