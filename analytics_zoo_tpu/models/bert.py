"""BERT family (reference: the Keras-zoo BERT layer — Scala
pipeline/api/keras/layers self-attention area — and TFPark's BERT
estimators: pyzoo/zoo/tfpark/text/estimator/bert_*.py — BERTClassifier,
BERTNER, BERTSQuAD).

TPU-native: the encoder is a stack of TransformerLayers (pre-LN, bf16-ready,
optional flash attention / ring attention for long sequences), learned
positional + segment embeddings, [CLS] pooler.  BERTClassifier and BERTSQuAD
put the reference's task heads on top.  This is the BASELINE BERT-SQuAD
fine-tune config's model.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Scope
from .common import ZooModel


class BERT(Module):
    """Encoder trunk: ids [B, T] (+ optional segment ids) → [B, T, H]."""

    def __init__(self, vocab_size: int = 30522, hidden_size: int = 768,
                 n_layers: int = 12, n_heads: int = 12,
                 intermediate_mult: int = 4, max_position: int = 512,
                 type_vocab: int = 2, dropout: float = 0.1,
                 use_flash: bool = False, use_ring: bool = False,
                 remat: bool = False, remat_attention: bool = False,
                 dtype: Any = None, name: Optional[str] = None):
        """``remat``: gradient-checkpoint each WHOLE encoder block
        (nn.Remat) — activation memory drops to O(layers * [B,T,H]) at
        ~1.3x compute, the long-sequence training recipe.

        ``remat_attention``: checkpoint only the attention core
        (logits/softmax recomputed in backward) — the measured training
        throughput default at seq 512 (bench.py bert: 53.5% -> 62.9%
        MFU on v5e); exact, and much cheaper recompute than ``remat``."""
        super().__init__(name)
        self.remat = remat
        self.remat_attention = remat_attention
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.n_layers = n_layers
        self.n_heads = n_heads
        self.intermediate_mult = intermediate_mult
        self.max_position = max_position
        self.type_vocab = type_vocab
        self.dropout = dropout
        self.use_flash = use_flash
        self.use_ring = use_ring
        self.dtype = dtype

    def forward(self, scope: Scope, ids: jax.Array,
                segment_ids: Optional[jax.Array] = None,
                mask: Optional[jax.Array] = None) -> jax.Array:
        t = ids.shape[1]
        x = scope.child(nn.Embedding(self.vocab_size, self.hidden_size),
                        ids, name="tok_embed")
        pos = scope.param("pos_embed", nn.initializers.get("normal"),
                          (1, self.max_position, self.hidden_size))
        x = x + pos[:, :t]
        if segment_ids is not None:
            x = x + scope.child(
                nn.Embedding(self.type_vocab, self.hidden_size),
                segment_ids, name="seg_embed")
        x = scope.child(nn.LayerNormalization(), x, name="embed_ln")
        x = scope.child(nn.Dropout(self.dropout), x, name="embed_drop")
        if self.dtype is not None:
            x = x.astype(self.dtype)
        for i in range(self.n_layers):
            block = nn.TransformerLayer(self.n_heads,
                                        hidden_mult=self.intermediate_mult,
                                        dropout=self.dropout, pre_ln=True,
                                        use_flash=self.use_flash,
                                        use_ring=self.use_ring,
                                        remat_attention=(
                                            self.remat_attention
                                            and not self.remat),
                                        name=f"layer_{i}")
            if self.remat:
                x = scope.child(nn.Remat(block), x, mask=mask,
                                name=f"remat_{i}")
            else:
                x = scope.child(block, x, mask=mask, name=f"layer_{i}")
        return x.astype(jnp.float32)


class BERTClassifier(ZooModel):
    """[CLS] pooler + linear head (reference: tfpark BERTClassifier)."""

    def __init__(self, class_num: int, **bert_kwargs: Any):
        super().__init__()
        self._config = dict(class_num=class_num, **bert_kwargs)
        self.class_num = class_num
        self.bert = BERT(**bert_kwargs)

    def forward(self, scope: Scope, ids: jax.Array) -> jax.Array:
        h = scope.child(self.bert, ids, name="bert")
        pooled = scope.child(nn.Dense(self.bert.hidden_size,
                                      activation="tanh"),
                             h[:, 0], name="pooler")
        return scope.child(nn.Dense(self.class_num), pooled, name="head")


class BERTSQuAD(ZooModel):
    """Span head: per-token (start, end) logits (reference: tfpark
    BERTSQuAD).  Output [B, T, 2]; train with the sum of start/end sparse
    cross-entropies (losses.squad_span_loss)."""

    def __init__(self, **bert_kwargs: Any):
        super().__init__()
        self._config = dict(**bert_kwargs)
        self.bert = BERT(**bert_kwargs)

    def forward(self, scope: Scope, ids: jax.Array) -> jax.Array:
        h = scope.child(self.bert, ids, name="bert")
        return scope.child(nn.Dense(2), h, name="span_head")


class BERTNER(ZooModel):
    """Token-classification head: per-token entity logits (reference:
    tfpark text/estimator BERTNER — the named-entity-recognition
    estimator).  Output [B, T, num_entities]; train with sparse
    cross-entropy over tokens."""

    def __init__(self, entity_num: int, **bert_kwargs: Any):
        super().__init__()
        self._config = dict(entity_num=entity_num, **bert_kwargs)
        self.entity_num = entity_num
        self.bert = BERT(**bert_kwargs)

    def forward(self, scope: Scope, ids: jax.Array) -> jax.Array:
        h = scope.child(self.bert, ids, name="bert")
        return scope.child(nn.Dense(self.entity_num), h, name="ner_head")


def squad_span_loss(y_pred: jax.Array, y_true: jax.Array) -> jax.Array:
    """y_pred [B, T, 2]; y_true int [B, 2] = (start_idx, end_idx)."""
    start_logits = y_pred[..., 0]
    end_logits = y_pred[..., 1]
    y_true = y_true.astype(jnp.int32)

    def nll(logits, idx):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, idx[:, None], axis=1)[:, 0]

    return (nll(start_logits, y_true[:, 0]) +
            nll(end_logits, y_true[:, 1])).mean() / 2.0
