"""TextClassifier (reference: zoo.models.textclassification —
models/textclassification/TextClassifier.scala + py twin).

encoder="cnn": embedding → temporal conv → global max pool (the reference's
default CNN text classifier); "lstm"/"gru": recurrent encoder, last output.
Input: int token ids [B, T] (from feature.text.TextSet's word2idx pipeline).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

import analytics_zoo_tpu.nn as nn
from .common import ZooModel


class TextClassifier(ZooModel):
    def __init__(self, class_num: int, vocab_size: int = 20000,
                 token_length: int = 200, sequence_length: int = 500,
                 encoder: str = "cnn", encoder_output_dim: int = 256):
        super().__init__()
        self._config = dict(class_num=class_num, vocab_size=vocab_size,
                            token_length=token_length,
                            sequence_length=sequence_length, encoder=encoder,
                            encoder_output_dim=encoder_output_dim)
        for k, v in self._config.items():
            setattr(self, k, v)
        if encoder not in ("cnn", "lstm", "gru"):
            raise ValueError(f"unknown encoder {encoder!r}")

    def forward(self, scope, ids):
        x = scope.child(nn.Embedding(self.vocab_size, self.token_length),
                        ids, name="embed")
        if self.encoder == "cnn":
            h = scope.child(nn.Conv1D(self.encoder_output_dim, 5,
                                      activation="relu"), x, name="conv")
            h = jnp.max(h, axis=1)  # global max pool over time
        elif self.encoder == "lstm":
            h = scope.child(nn.LSTM(self.encoder_output_dim), x, name="lstm")
        else:
            h = scope.child(nn.GRU(self.encoder_output_dim), x, name="gru")
        h = scope.child(nn.Dense(128, activation="relu"), h, name="fc1")
        h = scope.child(nn.Dropout(0.2), h, name="drop")
        return scope.child(nn.Dense(self.class_num), h, name="head")
