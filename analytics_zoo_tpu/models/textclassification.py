"""TextClassifier (reference: zoo.models.textclassification —
models/textclassification/TextClassifier.scala + py twin).

encoder="cnn": embedding → temporal conv → global max pool (the reference's
default CNN text classifier); "lstm"/"gru": recurrent encoder, last output.
Input: int token ids [B, T] (from feature.text.TextSet's word2idx pipeline).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

import analytics_zoo_tpu.nn as nn
from .common import ZooModel


class TextClassifier(ZooModel):
    def __init__(self, class_num: int, vocab_size: int = 20000,
                 token_length: int = 200, sequence_length: int = 500,
                 encoder: str = "cnn", encoder_output_dim: int = 256,
                 embedding_weights=None, embedding_trainable: bool = False,
                 embedding_shape=None):
        """``embedding_weights``: optional pre-trained [vocab, dim] table
        (e.g. ``nn.WordEmbedding.from_glove(...).weights``) — the
        reference's TextClassifier took a GloVe embedding file the same
        way; frozen unless ``embedding_trainable``.  ``embedding_shape``
        is the save/load round-trip of the table's shape (the values
        themselves travel in the saved variables)."""
        super().__init__()
        import numpy as np
        if embedding_weights is not None:
            embedding_weights = np.asarray(embedding_weights, np.float32)
            if embedding_weights.shape[0] != vocab_size:
                raise ValueError(
                    f"embedding_weights has {embedding_weights.shape[0]} "
                    f"rows but vocab_size={vocab_size}; out-of-range ids "
                    "would silently clamp to the last row")
            embedding_shape = list(embedding_weights.shape)
        elif embedding_shape is not None:
            # loading path: architecture only — saved variables carry the
            # actual table values
            embedding_weights = np.zeros(tuple(embedding_shape), np.float32)
        self._config = dict(class_num=class_num, vocab_size=vocab_size,
                            token_length=token_length,
                            sequence_length=sequence_length, encoder=encoder,
                            encoder_output_dim=encoder_output_dim,
                            embedding_shape=embedding_shape,
                            embedding_trainable=embedding_trainable)
        for k, v in self._config.items():
            setattr(self, k, v)
        self.embedding_weights = embedding_weights
        if encoder not in ("cnn", "lstm", "gru"):
            raise ValueError(f"unknown encoder {encoder!r}")

    def forward(self, scope, ids):
        if self.embedding_weights is not None:
            x = scope.child(
                nn.WordEmbedding(self.embedding_weights,
                                 trainable=self.embedding_trainable),
                ids, name="embed")
        else:
            x = scope.child(nn.Embedding(self.vocab_size, self.token_length),
                            ids, name="embed")
        if self.encoder == "cnn":
            h = scope.child(nn.Conv1D(self.encoder_output_dim, 5,
                                      activation="relu"), x, name="conv")
            h = jnp.max(h, axis=1)  # global max pool over time
        elif self.encoder == "lstm":
            h = scope.child(nn.LSTM(self.encoder_output_dim), x, name="lstm")
        else:
            h = scope.child(nn.GRU(self.encoder_output_dim), x, name="gru")
        h = scope.child(nn.Dense(128, activation="relu"), h, name="fc1")
        h = scope.child(nn.Dropout(0.2), h, name="drop")
        return scope.child(nn.Dense(self.class_num), h, name="head")
