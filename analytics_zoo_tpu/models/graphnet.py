"""GraphNet surgery: intermediate outputs + layer freezing for transfer
learning.

Reference (SURVEY.md §2.3 "Net loaders"): ``GraphNet`` in
zoo/.../pipeline/api/net/{Net,GraphNet}.scala — ``newGraph(output)`` cut a
loaded graph at a named layer (feature extraction) and
``freezeUpTo(names)`` stopped gradients flowing into the backbone, the
reference's canonical fine-tuning recipe.

TPU-native: models are pure functions, so "surgery" is functional —
``Module.apply_with_taps`` records every submodule output by scope path,
``GraphNet`` selects one as the new output, and freezing is an optimizer
mask (``Estimator.from_keras(..., frozen=[...])`` → optax.multi_transform
with set_to_zero on the frozen label), which XLA folds into the update
step.  No graph mutation, no weight copying.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax

from analytics_zoo_tpu.nn.module import Module, Params, Scope


class GraphNet(Module):
    """Wrap ``base`` and output the activations at ``outputs`` (scope paths
    relative to the base, e.g. ``["block3", "block3/mha"]``).

    ``GraphNet(resnet, ["stage3"])`` is the reference's
    ``net.new_graph(["stage3"])`` — reuse the backbone's variables
    unchanged and fine-tune a new head on the tapped features."""

    def __init__(self, base: Module, outputs: Sequence[str],
                 name: Optional[str] = None):
        super().__init__(name)
        self.base = base
        self.outputs = list(outputs)
        if not self.outputs:
            raise ValueError("GraphNet needs at least one output path")

    def _select(self, taps: Dict[str, Any], prefix: str = "") -> Any:
        sel = []
        for p in self.outputs:
            key = f"{prefix}{p}" if prefix else p
            if key not in taps:
                close = sorted(k for k in taps if k.endswith(p))
                if len(close) == 1:
                    key = close[0]
                else:
                    raise KeyError(
                        f"no submodule output at {p!r}; available: "
                        f"{sorted(taps)[:20]}")
            sel.append(taps[key])
        return sel[0] if len(sel) == 1 else tuple(sel)

    def init(self, rng: jax.Array, *args: Any, **kwargs: Any) -> Params:
        # variables are the BASE's tree: a pretrained checkpoint loads
        # straight in, exactly like the reference's shared-weights newGraph
        return self.base.init(rng, *args, **kwargs)

    def apply(self, variables: Params, *args: Any, training: bool = False,
              rng: Optional[jax.Array] = None, **kwargs: Any
              ) -> Tuple[Any, Params]:
        _, state, taps = self.base.apply_with_taps(
            variables, *args, training=training, rng=rng, **kwargs)
        return self._select(taps), state

    def forward(self, scope: Scope, *args: Any, **kwargs: Any) -> Any:
        # embedded inside another module: run the base as a child with taps
        # enabled, then select relative to this scope's path
        had = scope.taps
        scope.taps = {} if had is None else had
        try:
            scope.child(self.base, *args, name="base", **kwargs)
            prefix = "/".join(scope.path + ("base",)) + "/"
            return self._select(scope.taps, prefix)
        finally:
            scope.taps = had
