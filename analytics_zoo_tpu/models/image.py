"""Image classification (reference: zoo.models.image.imageclassification —
ImageClassifier wrapper over pretrained zoo/bigdl models).

TPU-native: ResNet v1.5 built in NHWC with bf16-friendly conv blocks — the
BASELINE ResNet-50/ImageNet config.  ``ImageClassifier`` wraps any backbone
with the reference's configure/predict API (top-k labels).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Scope
from .common import ZooModel

_SPECS: Dict[int, Tuple[Tuple[int, ...], bool]] = {
    # depth: (blocks per stage, bottleneck?)
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


class _ResBlock(Module):
    def __init__(self, filters: int, stride: int, bottleneck: bool,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.stride = stride
        self.bottleneck = bottleneck

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        f = self.filters
        out_f = f * 4 if self.bottleneck else f
        shortcut = x
        if x.shape[-1] != out_f or self.stride != 1:
            shortcut = scope.child(
                nn.Conv2D(out_f, 1, strides=self.stride, use_bias=False),
                x, name="proj")
            shortcut = scope.child(nn.BatchNormalization(), shortcut,
                                   name="proj_bn")
        if self.bottleneck:
            h = scope.child(nn.Conv2D(f, 1, use_bias=False), x, name="conv1")
            h = scope.child(nn.BatchNormalization(), h, name="bn1")
            h = jax.nn.relu(h)
            h = scope.child(nn.Conv2D(f, 3, strides=self.stride,
                                      use_bias=False), h, name="conv2")
            h = scope.child(nn.BatchNormalization(), h, name="bn2")
            h = jax.nn.relu(h)
            h = scope.child(nn.Conv2D(out_f, 1, use_bias=False), h,
                            name="conv3")
            h = scope.child(nn.BatchNormalization(), h, name="bn3")
        else:
            h = scope.child(nn.Conv2D(f, 3, strides=self.stride,
                                      use_bias=False), x, name="conv1")
            h = scope.child(nn.BatchNormalization(), h, name="bn1")
            h = jax.nn.relu(h)
            h = scope.child(nn.Conv2D(f, 3, use_bias=False), h, name="conv2")
            h = scope.child(nn.BatchNormalization(), h, name="bn2")
        return jax.nn.relu(h + shortcut)


class ResNet(ZooModel):
    """ResNet v1.5 (stride-2 on the 3x3), NHWC.  depth ∈ {18,34,50,101,152}."""

    def __init__(self, depth: int = 50, class_num: int = 1000,
                 width: int = 64, include_top: bool = True,
                 return_stages: bool = False, dtype: str = "float32"):
        super().__init__()
        self._config = dict(depth=depth, class_num=class_num, width=width,
                            include_top=include_top,
                            return_stages=return_stages, dtype=dtype)
        if depth not in _SPECS:
            raise ValueError(f"depth must be one of {sorted(_SPECS)}")
        self.depth = depth
        self.class_num = class_num
        self.width = width
        self.include_top = include_top
        self.return_stages = return_stages
        self.dtype = dtype

    def forward(self, scope: Scope, x: jax.Array):
        """x: [B, H, W, C] images (NHWC — TPU-native layout; the reference
        used NCHW for MKL-DNN).  return_stages=True yields the per-stage
        feature maps (stages 1..3) for detection heads."""
        blocks, bottleneck = _SPECS[self.depth]
        if self.dtype == "bfloat16":
            x = x.astype(jnp.bfloat16)
        h = scope.child(nn.Conv2D(self.width, 7, strides=2, use_bias=False),
                        x, name="stem")
        h = scope.child(nn.BatchNormalization(), h, name="stem_bn")
        h = jax.nn.relu(h)
        h = scope.child(nn.MaxPooling2D(3, strides=2, padding="same"), h,
                        name="stem_pool")
        taps = []
        for stage, n_blocks in enumerate(blocks):
            f = self.width * (2 ** stage)
            for b in range(n_blocks):
                stride = 2 if (b == 0 and stage > 0) else 1
                h = scope.child(_ResBlock(f, stride, bottleneck), h,
                                name=f"stage{stage}_block{b}")
            if stage >= 1:
                taps.append(h)
        if self.return_stages:
            return taps
        h = jnp.mean(h, axis=(1, 2))  # global average pool
        if not self.include_top:
            return h
        return scope.child(nn.Dense(self.class_num),
                           h.astype(jnp.float32), name="head")


class ImageClassifier(ZooModel):
    """Reference API wrapper: backbone + labels + topN predict
    (models/image/imageclassification/ImageClassifier.scala)."""

    def __init__(self, depth: int = 50, class_num: int = 1000,
                 labels: Optional[Sequence[str]] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._config = dict(depth=depth, class_num=class_num,
                            labels=list(labels) if labels else None,
                            dtype=dtype)
        self.backbone = ResNet(depth=depth, class_num=class_num, dtype=dtype)
        self.labels = list(labels) if labels else None
        self.class_num = class_num

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return scope.child(self.backbone, x, name="resnet")

    def predict_image_set(self, images: np.ndarray, top_n: int = 5
                          ) -> List[List[Tuple[Any, float]]]:
        probs = np.asarray(jax.nn.softmax(
            jnp.asarray(self.predict(images)), axis=-1))
        out = []
        for row in probs:
            top = np.argsort(-row)[:top_n]
            out.append([(self.labels[i] if self.labels else int(i),
                         float(row[i])) for i in top])
        return out
