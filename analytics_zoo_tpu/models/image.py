"""Image classification (reference: zoo.models.image.imageclassification —
ImageClassifier wrapper over pretrained zoo/bigdl models).

TPU-native: ResNet v1.5 built in NHWC with bf16-friendly conv blocks — the
BASELINE ResNet-50/ImageNet config.  ``ImageClassifier`` wraps any backbone
with the reference's configure/predict API (top-k labels).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Scope
from .common import ZooModel

_SPECS: Dict[int, Tuple[Tuple[int, ...], bool]] = {
    # depth: (blocks per stage, bottleneck?)
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
    101: ((3, 4, 23, 3), True),
    152: ((3, 8, 36, 3), True),
}


class _SpaceToDepthStem(Module):
    """The 7x7/stride-2 stem conv computed as a 4x4/stride-1 conv over a
    2x2 space-to-depth rearrangement of the image — the standard TPU
    ResNet trick: a C=3 conv leaves the MXU's input lanes mostly padding
    and forces XLA into layout copies; at C=12 the same FLOPs run dense.

    Numerically IDENTICAL to ``Conv2D(f, 7, strides=2, padding="same")``:
    the kernel is stored in the canonical (7, 7, C, F) shape (checkpoints
    interchange with the plain stem) and zero-padded to 8x8 = 4x4 blocks
    of 2x2; the image takes the SAME pads (2, 3) plus one bottom/right
    zero row that only ever meets the kernel's zero taps.
    """

    def __init__(self, filters: int, kernel_init: Any = "he_normal",
                 weight_standardized: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.kernel_init = kernel_init
        self.weight_standardized = weight_standardized

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        b, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"space-to-depth stem wants even H/W, got "
                             f"{x.shape}")
        f = self.filters
        k = scope.param("kernel", nn.initializers.get(self.kernel_init),
                        (7, 7, c, f))
        if self.weight_standardized:  # NF variant: see ScaledWSConv2D
            gain = scope.param("ws_gain", nn.initializers.get("ones"),
                               (f,))
            k = nn.layers.scaled_ws_kernel(k, gain)
        k = k.astype(x.dtype)
        k8 = jnp.pad(k, ((0, 1), (0, 1), (0, 0), (0, 0)))
        k2 = (k8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
              .reshape(4, 4, 4 * c, f))
        xp = jnp.pad(x, ((0, 0), (2, 4), (2, 4), (0, 0)))
        hb, wb = (h + 6) // 2, (w + 6) // 2
        x2 = (xp.reshape(b, hb, 2, wb, 2, c).transpose(0, 1, 3, 2, 4, 5)
              .reshape(b, hb, wb, 4 * c))
        return jax.lax.conv_general_dilated(
            x2, k2, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


_NF_RELU_GAIN = 1.7139588594436646  # sqrt(2 / (1 - 1/pi)): relu VP gain


def _nf_transition(in_channels: int, out_channels: int,
                   stride: int) -> bool:
    """Whether an NF block needs a projected (transition) shortcut: the
    channel count changes or the block strides.  ONE definition, used by
    both ``_NFResBlock`` (to create the shortcut) and ``ResNet.forward``
    (to reset the analytic variance tracker) — the two must agree, or
    the tracked ``beta`` drifts from the variance the shortcuts actually
    carry."""
    return in_channels != out_channels or stride != 1


class _NFResBlock(Module):
    """Normalizer-free bottleneck block (public technique: Brock et al.
    2021, NF-ResNet): pre-activation ``h = x + alpha * f(relu(x) *
    gain / beta)`` with Scaled WS convs inside f, a zero-initialised
    learnable scalar on the residual branch (SkipInit), and analytically
    tracked input std ``beta``.  No activation statistics are ever
    reduced — normalization lives in weight space (see ScaledWSConv2D),
    which on TPU removes batch norm's full feature-map reduction
    traffic from every training step."""

    def __init__(self, filters: int, stride: int, bottleneck: bool,
                 beta: float, alpha: float,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.stride = stride
        self.bottleneck = bottleneck
        self.beta = beta
        self.alpha = alpha

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        f = self.filters
        out_f = f * 4 if self.bottleneck else f
        pre = jax.nn.relu(x) * jnp.asarray(
            _NF_RELU_GAIN / self.beta, x.dtype)
        transition = _nf_transition(x.shape[-1], out_f, self.stride)
        # Transition shortcuts branch from the SCALED activation (resets
        # the analytic variance); identity shortcuts keep x itself.
        shortcut = x
        if transition:
            shortcut = scope.child(
                nn.ScaledWSConv2D(out_f, 1, strides=self.stride,
                                  use_bias=False),
                pre, name="proj")
        # The residual branch's SkipInit scalar (x alpha) is folded into
        # the LAST conv's weight scale (see ScaledWSConv2D.skip_init):
        # identical math, but dL/d(skip_gain) is a weight-space adjoint
        # instead of a full-map scalar reduction.
        h = pre
        if self.bottleneck:
            h = scope.child(nn.ScaledWSConv2D(f, 1, use_bias=False), h,
                            name="conv1")
            h = jax.nn.relu(h) * jnp.asarray(_NF_RELU_GAIN, x.dtype)
            h = scope.child(nn.ScaledWSConv2D(f, 3, strides=self.stride,
                                              use_bias=False), h,
                            name="conv2")
            h = jax.nn.relu(h) * jnp.asarray(_NF_RELU_GAIN, x.dtype)
            h = scope.child(nn.ScaledWSConv2D(out_f, 1, use_bias=False,
                                              skip_init=True,
                                              branch_scale=self.alpha),
                            h, name="conv3")
        else:
            h = scope.child(nn.ScaledWSConv2D(f, 3, strides=self.stride,
                                              use_bias=False), h,
                            name="conv1")
            h = jax.nn.relu(h) * jnp.asarray(_NF_RELU_GAIN, x.dtype)
            h = scope.child(nn.ScaledWSConv2D(f, 3, use_bias=False,
                                              skip_init=True,
                                              branch_scale=self.alpha),
                            h, name="conv2")
        return shortcut + h


class _ResBlock(Module):
    def __init__(self, filters: int, stride: int, bottleneck: bool,
                 name: Optional[str] = None):
        super().__init__(name)
        self.filters = filters
        self.stride = stride
        self.bottleneck = bottleneck

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        f = self.filters
        out_f = f * 4 if self.bottleneck else f
        shortcut = x
        if x.shape[-1] != out_f or self.stride != 1:
            shortcut = scope.child(
                nn.Conv2D(out_f, 1, strides=self.stride, use_bias=False),
                x, name="proj")
            shortcut = scope.child(nn.BatchNormalization(), shortcut,
                                   name="proj_bn")
        if self.bottleneck:
            h = scope.child(nn.Conv2D(f, 1, use_bias=False), x, name="conv1")
            h = scope.child(nn.BatchNormalization(), h, name="bn1")
            h = jax.nn.relu(h)
            h = scope.child(nn.Conv2D(f, 3, strides=self.stride,
                                      use_bias=False), h, name="conv2")
            h = scope.child(nn.BatchNormalization(), h, name="bn2")
            h = jax.nn.relu(h)
            h = scope.child(nn.Conv2D(out_f, 1, use_bias=False), h,
                            name="conv3")
            h = scope.child(nn.BatchNormalization(), h, name="bn3")
        else:
            h = scope.child(nn.Conv2D(f, 3, strides=self.stride,
                                      use_bias=False), x, name="conv1")
            h = scope.child(nn.BatchNormalization(), h, name="bn1")
            h = jax.nn.relu(h)
            h = scope.child(nn.Conv2D(f, 3, use_bias=False), h, name="conv2")
            h = scope.child(nn.BatchNormalization(), h, name="bn2")
        return jax.nn.relu(h + shortcut)


class ResNet(ZooModel):
    """ResNet v1.5 (stride-2 on the 3x3), NHWC.  depth ∈ {18,34,50,101,152}."""

    def __init__(self, depth: int = 50, class_num: int = 1000,
                 width: int = 64, include_top: bool = True,
                 return_stages: bool = False, dtype: str = "float32",
                 stem: str = "conv", norm: str = "batch"):
        super().__init__()
        self._config = dict(depth=depth, class_num=class_num, width=width,
                            include_top=include_top,
                            return_stages=return_stages, dtype=dtype,
                            stem=stem, norm=norm)
        if depth not in _SPECS:
            raise ValueError(f"depth must be one of {sorted(_SPECS)}")
        if stem not in ("conv", "space_to_depth"):
            raise ValueError("stem must be 'conv' or 'space_to_depth'")
        if norm not in ("batch", "nf"):
            raise ValueError("norm must be 'batch' (classic BN ResNet) "
                             "or 'nf' (normalizer-free, Scaled WS convs)")
        self.depth = depth
        self.class_num = class_num
        self.width = width
        self.include_top = include_top
        self.return_stages = return_stages
        self.dtype = dtype
        self.stem = stem
        self.norm = norm

    def forward(self, scope: Scope, x: jax.Array):
        """x: [B, H, W, C] images (NHWC — TPU-native layout; the reference
        used NCHW for MKL-DNN).  return_stages=True yields the per-stage
        feature maps (stages 1..3) for detection heads.

        NF tap semantics: with ``norm='nf'`` the stage taps are
        PRE-activation residual-sum maps whose analytic std grows
        ~sqrt(1 + k*alpha^2) within a stage (no final relu, no
        normalization) — unlike the BN path's post-relu normalized taps.
        A detection head moving between norms should expect differently
        scaled features (apply its own norm, or relu + rescale)."""
        blocks, bottleneck = _SPECS[self.depth]
        nf = self.norm == "nf"
        if self.dtype == "bfloat16":
            x = x.astype(jnp.bfloat16)
        if self.stem == "space_to_depth":
            h = scope.child(
                _SpaceToDepthStem(self.width, weight_standardized=nf),
                x, name="stem")
        elif nf:
            h = scope.child(nn.ScaledWSConv2D(self.width, 7, strides=2,
                                              use_bias=False), x,
                            name="stem")
        else:
            h = scope.child(nn.Conv2D(self.width, 7, strides=2,
                                      use_bias=False), x, name="stem")
        if not nf:
            h = scope.child(nn.BatchNormalization(), h, name="stem_bn")
        h = jax.nn.relu(h)
        h = scope.child(nn.MaxPooling2D(3, strides=2, padding="same"), h,
                        name="stem_pool")
        taps = []
        alpha, var = 0.2, 1.0  # NF analytic variance tracking
        for stage, n_blocks in enumerate(blocks):
            f = self.width * (2 ** stage)
            for b in range(n_blocks):
                stride = 2 if (b == 0 and stage > 0) else 1
                if nf:
                    # reset iff THIS block takes a projected shortcut —
                    # the same channel-change-or-stride predicate the
                    # block itself uses (a projected shortcut branches
                    # from the scaled activation, restarting the
                    # analytic variance; an identity shortcut carries
                    # it).  Notably depth-18/34 stage 0 block 0 is an
                    # IDENTITY shortcut (stem channels == f, stride 1),
                    # not a transition.
                    out_f = f * 4 if bottleneck else f
                    transition = _nf_transition(h.shape[-1], out_f,
                                                stride)
                    h = scope.child(
                        _NFResBlock(f, stride, bottleneck,
                                    beta=float(np.sqrt(var)),
                                    alpha=alpha), h,
                        name=f"stage{stage}_block{b}")
                    var = (1.0 if transition else var) + alpha * alpha
                else:
                    h = scope.child(_ResBlock(f, stride, bottleneck), h,
                                    name=f"stage{stage}_block{b}")
            if stage >= 1:
                taps.append(h)
        if self.return_stages:
            return taps
        if nf:
            # NF blocks are pre-activation: one final relu before pooling
            h = jax.nn.relu(h)
        h = jnp.mean(h, axis=(1, 2))  # global average pool
        if not self.include_top:
            return h
        return scope.child(nn.Dense(self.class_num),
                           h.astype(jnp.float32), name="head")


class ImageClassifier(ZooModel):
    """Reference API wrapper: backbone + labels + topN predict
    (models/image/imageclassification/ImageClassifier.scala)."""

    def __init__(self, depth: int = 50, class_num: int = 1000,
                 labels: Optional[Sequence[str]] = None,
                 dtype: str = "float32"):
        super().__init__()
        self._config = dict(depth=depth, class_num=class_num,
                            labels=list(labels) if labels else None,
                            dtype=dtype)
        self.backbone = ResNet(depth=depth, class_num=class_num, dtype=dtype)
        self.labels = list(labels) if labels else None
        self.class_num = class_num

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return scope.child(self.backbone, x, name="resnet")

    def predict_image_set(self, images: np.ndarray, top_n: int = 5
                          ) -> List[List[Tuple[Any, float]]]:
        probs = np.asarray(jax.nn.softmax(
            jnp.asarray(self.predict(images)), axis=-1))
        out = []
        for row in probs:
            top = np.argsort(-row)[:top_n]
            out.append([(self.labels[i] if self.labels else int(i),
                         float(row[i])) for i in top])
        return out
