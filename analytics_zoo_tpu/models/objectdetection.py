"""Object detection (reference: zoo.models.image.objectdetection —
SSD-VGG/MobileNet pipelines: ObjectDetector load + ImageConfigure +
postprocess NMS/ScaleDetection + Visualizer).

TPU-native redesign: ``SSDLite`` — an SSD head over a ResNet backbone's
multi-scale feature maps, anchors generated per level; the conv trunk +
box/class heads run compiled on device, decode + class-wise NMS run on host
numpy (small, latency-bound — the reference also postprocessed on CPU).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module, Scope
from .common import ZooModel
from .image import ResNet


def _make_anchors(fm_sizes: Sequence[Tuple[int, int]],
                  scales: Sequence[float],
                  ratios: Sequence[float] = (1.0, 2.0, 0.5)) -> np.ndarray:
    """Center-form anchors [(cx, cy, w, h)] normalized to [0,1]."""
    out = []
    for (fh, fw), scale in zip(fm_sizes, scales):
        for i in range(fh):
            for j in range(fw):
                cx, cy = (j + 0.5) / fw, (i + 0.5) / fh
                for r in ratios:
                    w = scale * np.sqrt(r)
                    h = scale / np.sqrt(r)
                    out.append([cx, cy, w, h])
    return np.asarray(out, np.float32)


def decode_boxes(loc: np.ndarray, anchors: np.ndarray,
                 variances: Tuple[float, float] = (0.1, 0.2)) -> np.ndarray:
    """SSD box decoding: loc deltas + anchors → corner-form [x1,y1,x2,y2]."""
    cxcy = anchors[:, :2] + loc[:, :2] * variances[0] * anchors[:, 2:]
    wh = anchors[:, 2:] * np.exp(loc[:, 2:] * variances[1])
    return np.concatenate([cxcy - wh / 2, cxcy + wh / 2], axis=1)


def nms(boxes: np.ndarray, scores: np.ndarray, iou_threshold: float = 0.45,
        top_k: int = 200) -> List[int]:
    """Greedy class-wise NMS (reference: postprocess Nms.scala)."""
    order = np.argsort(-scores)[:top_k]
    keep: List[int] = []
    while len(order):
        i = order[0]
        keep.append(int(i))
        if len(order) == 1:
            break
        rest = order[1:]
        xx1 = np.maximum(boxes[i, 0], boxes[rest, 0])
        yy1 = np.maximum(boxes[i, 1], boxes[rest, 1])
        xx2 = np.minimum(boxes[i, 2], boxes[rest, 2])
        yy2 = np.minimum(boxes[i, 3], boxes[rest, 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        a_i = ((boxes[i, 2] - boxes[i, 0]) * (boxes[i, 3] - boxes[i, 1]))
        a_r = ((boxes[rest, 2] - boxes[rest, 0]) *
               (boxes[rest, 3] - boxes[rest, 1]))
        iou = inter / np.clip(a_i + a_r - inter, 1e-9, None)
        order = rest[iou <= iou_threshold]
    return keep


class SSDLite(ZooModel):
    """SSD head over ResNet stages 2..4 + one extra stride-2 level."""

    N_RATIOS = 3

    def __init__(self, class_num: int = 21, backbone_depth: int = 18,
                 image_size: int = 128):
        super().__init__()
        self._config = dict(class_num=class_num,
                            backbone_depth=backbone_depth,
                            image_size=image_size)
        self.class_num = class_num
        self.image_size = image_size
        self.backbone = ResNet(depth=backbone_depth, include_top=False,
                               return_stages=True)
        # feature strides 8/16/32/64 on image_size → map sizes.  SAME-padded
        # stride-2 convs produce ceil(s/stride) maps, so fm sizes must be
        # computed by REPEATED ceil-division (floor disagrees for sizes not
        # divisible by 64 and desyncs anchors from head outputs)
        def halve(v: int, times: int) -> int:
            for _ in range(times):
                v = -(-v // 2)
            return v

        s = image_size
        self.fm_sizes = [(halve(s, k), halve(s, k)) for k in (3, 4, 5, 6)]
        self.scales = [0.1, 0.25, 0.45, 0.7]
        self.anchors = _make_anchors(self.fm_sizes, self.scales)

    def _features(self, scope: Scope, x: jax.Array) -> List[jax.Array]:
        """ResNet trunk taps (stages 1..3) + one extra stride-2 level."""
        taps = scope.child(self.backbone, x, name="backbone")
        extra = scope.child(nn.Conv2D(256, 3, strides=2, activation="relu"),
                            taps[-1], name="extra")
        return list(taps) + [extra]

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        """Returns [B, n_anchors, 4 + class_num] (loc ++ class logits)."""
        feats = self._features(scope, x)
        locs, clss = [], []
        k = self.N_RATIOS
        for i, f in enumerate(feats):
            loc = scope.child(nn.Conv2D(k * 4, 3), f, name=f"loc_{i}")
            cls = scope.child(nn.Conv2D(k * self.class_num, 3), f,
                              name=f"cls_{i}")
            b, fh, fw, _ = loc.shape
            locs.append(loc.reshape(b, fh * fw * k, 4))
            clss.append(cls.reshape(b, fh * fw * k, self.class_num))
        return jnp.concatenate(
            [jnp.concatenate(locs, axis=1), jnp.concatenate(clss, axis=1)],
            axis=-1)


class ObjectDetector(ZooModel):
    """Reference-API wrapper: predict_image_set → per-image detections
    [(class, score, [x1,y1,x2,y2]), ...] after decode + NMS."""

    def __init__(self, class_num: int = 21, backbone_depth: int = 18,
                 image_size: int = 128,
                 labels: Optional[Sequence[str]] = None):
        super().__init__()
        self._config = dict(class_num=class_num,
                            backbone_depth=backbone_depth,
                            image_size=image_size,
                            labels=list(labels) if labels else None)
        self.ssd = SSDLite(class_num, backbone_depth, image_size)
        self.class_num = class_num
        self.labels = list(labels) if labels else None

    def forward(self, scope: Scope, x: jax.Array) -> jax.Array:
        return scope.child(self.ssd, x, name="ssd")

    def predict_image_set(self, images: np.ndarray,
                          score_threshold: float = 0.5,
                          iou_threshold: float = 0.45
                          ) -> List[List[Tuple[Any, float, np.ndarray]]]:
        raw = self.predict(np.asarray(images))
        anchors = self.ssd.anchors
        results = []
        for row in raw:
            loc, logits = row[:, :4], row[:, 4:]
            probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
            boxes = decode_boxes(loc, anchors)
            dets = []
            for c in range(1, self.class_num):  # 0 = background
                sc = probs[:, c]
                sel = np.where(sc >= score_threshold)[0]
                if not len(sel):
                    continue
                for i in nms(boxes[sel], sc[sel], iou_threshold):
                    label = self.labels[c] if self.labels else c
                    dets.append((label, float(sc[sel][i]), boxes[sel][i]))
            dets.sort(key=lambda d: -d[1])
            results.append(dets)
        return results


class Visualizer:
    """Draw detections onto images (reference: the objectdetection
    Visualizer utility — models/image/objectdetection/, which rendered
    boxes + labels via OpenCV; here PIL on the host).

    ``visualize(image, detections)`` takes one HWC image (uint8 or float
    in [0,1]/[0,255]) and the per-image output of
    ``ObjectDetector.predict_image_set`` and returns a uint8 HWC array
    with boxes and ``label: score`` captions drawn."""

    # a small fixed palette cycled per class label
    _COLORS = [(230, 25, 75), (60, 180, 75), (255, 225, 25), (0, 130, 200),
               (245, 130, 48), (145, 30, 180), (70, 240, 240),
               (240, 50, 230), (210, 245, 60), (250, 190, 190)]

    def __init__(self, score_format: str = "{label}: {score:.2f}"):
        self.score_format = score_format

    def visualize(self, image: np.ndarray, detections: List[Tuple[Any,
                  float, np.ndarray]]) -> np.ndarray:
        from PIL import Image, ImageDraw
        img = np.asarray(image)
        if img.dtype != np.uint8:
            scale = 255.0 if img.max() <= 1.0 + 1e-6 else 1.0
            img = np.clip(img * scale, 0, 255).astype(np.uint8)
        pil = Image.fromarray(img)
        draw = ImageDraw.Draw(pil)
        color_of: dict = {}
        for label, score, box in detections:
            if label not in color_of:
                color_of[label] = self._COLORS[len(color_of)
                                               % len(self._COLORS)]
            color = color_of[label]
            x1, y1, x2, y2 = [float(v) for v in box]
            draw.rectangle([x1, y1, x2, y2], outline=color, width=2)
            draw.text((x1 + 2, max(0.0, y1 - 10)),
                      self.score_format.format(label=label, score=score),
                      fill=color)
        return np.asarray(pil)

    def save(self, path: str, image: np.ndarray,
             detections: List[Tuple[Any, float, np.ndarray]]) -> str:
        from PIL import Image
        Image.fromarray(self.visualize(image, detections)).save(path)
        return path
