"""KNRM kernel-pooling text matching (reference: zoo.models.textmatching —
models/textmatching/KNRM.scala; Xiong et al., K-NRM).

Query/doc token ids → shared embedding → cosine translation matrix →
RBF kernel pooling → linear ranking score.  The whole model is three einsums
plus exp — ideal MXU/VPU fusion material; the reference ran it per-record
on BigDL CPU tensors.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import analytics_zoo_tpu.nn as nn
from .common import ZooModel


class KNRM(ZooModel):
    def __init__(self, text1_length: int, text2_length: int,
                 vocab_size: int = 20000, embed_size: int = 300,
                 kernel_num: int = 21, sigma: float = 0.1,
                 exact_sigma: float = 0.001, target_mode: str = "ranking"):
        super().__init__()
        self._config = dict(text1_length=text1_length,
                            text2_length=text2_length, vocab_size=vocab_size,
                            embed_size=embed_size, kernel_num=kernel_num,
                            sigma=sigma, exact_sigma=exact_sigma,
                            target_mode=target_mode)
        for k, v in self._config.items():
            setattr(self, k, v)

    def forward(self, scope, ids):
        """ids: int [B, text1_length + text2_length] (query ++ doc)."""
        # one shared embedding over the concatenated ids (the reference ties
        # query/doc embeddings); split after the gather
        qd = scope.child(nn.Embedding(self.vocab_size, self.embed_size),
                         ids, name="embed")
        q = qd[:, :self.text1_length]
        d = qd[:, self.text1_length:]
        qn = q / (jnp.linalg.norm(q, axis=-1, keepdims=True) + 1e-8)
        dn = d / (jnp.linalg.norm(d, axis=-1, keepdims=True) + 1e-8)
        trans = jnp.einsum("bqe,bde->bqd", qn, dn)   # cosine match matrix
        mus = np.linspace(-1.0, 1.0, self.kernel_num)
        sigmas = np.full(self.kernel_num, self.sigma)
        sigmas[-1] = self.exact_sigma  # the exact-match kernel at mu=1
        mus_a = jnp.asarray(mus, jnp.float32)
        sig_a = jnp.asarray(sigmas, jnp.float32)
        # RBF kernels: [B, Q, D, K] → sum over D, log, sum over Q
        k = jnp.exp(-jnp.square(trans[..., None] - mus_a) /
                    (2.0 * jnp.square(sig_a)))
        pooled = jnp.log(jnp.clip(k.sum(axis=2), 1e-10)) * 0.01
        feats = pooled.sum(axis=1)                   # [B, K]
        out = scope.child(nn.Dense(1), feats, name="score")
        if self.target_mode == "classification":
            out = jnp.concatenate([jnp.zeros_like(out), out], axis=-1)
        return out
