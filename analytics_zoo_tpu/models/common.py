"""ZooModel: the model-zoo base class.

Reference (SURVEY.md §2.7 'common'): ``ZooModel`` (zoo/.../models/common/
ZooModel.scala) gave every built-in model BigDL-protobuf save/load,
``predictClasses`` and fit/predict plumbing through KerasNet.

TPU-native: a ZooModel IS an nn.Module; ``compile`` attaches the unified
Estimator (orca.learn) so ``fit/evaluate/predict`` run the jit-compiled,
mesh-sharded path; ``save_model/load_model`` round-trip weights (checkpoint
IO) + the constructor config (JSON) in one directory.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from analytics_zoo_tpu.core import checkpoint as ckpt_io
from analytics_zoo_tpu.nn.module import Module

_REGISTRY: Dict[str, type] = {}


class ZooModel(Module):
    """Base: subclasses set ``self._config = {...}`` (constructor kwargs)
    before/inside __init__ and implement ``forward``."""

    _config: Dict[str, Any]

    def __init_subclass__(cls, **kw: Any):
        super().__init_subclass__(**kw)
        _REGISTRY[cls.__name__] = cls

    # -- training plumbing ----------------------------------------------------

    def compile(self, loss: Any, optimizer: Any = "adam",
                learning_rate: Optional[float] = None,
                metrics: Optional[Sequence[Any]] = None,
                **kwargs: Any) -> "ZooModel":
        from analytics_zoo_tpu.orca.learn import Estimator
        self._estimator = Estimator.from_keras(
            self, loss=loss, optimizer=optimizer,
            learning_rate=learning_rate, metrics=metrics, **kwargs)
        self._inject_loaded_weights()
        return self

    def _inject_loaded_weights(self) -> None:
        """After load_model(), any compile() starts from the loaded weights
        rather than a fresh random init."""
        lv = getattr(self, "_loaded_variables", None)
        if lv is None:
            return
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from analytics_zoo_tpu.core import get_mesh
        est = self._estimator
        mesh = get_mesh()
        repl = NamedSharding(mesh, P())
        opt_state = est.tx.init(lv["params"])
        est._ts = jax.device_put(
            {"params": lv["params"], "state": lv.get("state", {}),
             "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32),
             "rng": jax.random.PRNGKey(est.seed)}, repl)
        est._build_steps(mesh)

    def set_estimator(self, estimator: Any) -> "ZooModel":
        """Attach an externally built estimator (e.g. one configured with
        custom sharding/frozen settings) instead of compile()'s default."""
        self._estimator = estimator
        self._inject_loaded_weights()
        return self

    @property
    def estimator(self):
        if getattr(self, "_estimator", None) is None:
            raise ValueError(f"{type(self).__name__}: call compile() (or "
                             "set_estimator) before fit/evaluate/predict")
        return self._estimator

    def fit(self, data: Any, epochs: int = 1, batch_size: int = 32,
            **kwargs: Any) -> Dict[str, Any]:
        return self.estimator.fit(data, epochs=epochs, batch_size=batch_size,
                                  **kwargs)

    def evaluate(self, data: Any, batch_size: int = 32,
                 **kwargs: Any) -> Dict[str, float]:
        return self.estimator.evaluate(data, batch_size=batch_size, **kwargs)

    def predict(self, data: Any, batch_size: int = 32,
                **kwargs: Any) -> np.ndarray:
        return self.estimator.predict(data, batch_size=batch_size, **kwargs)

    def predict_classes(self, data: Any, batch_size: int = 32) -> np.ndarray:
        """Reference: ZooModel.predictClasses — argmax over output dist."""
        out = self.predict(data, batch_size=batch_size)
        if out.ndim > 1 and out.shape[-1] > 1:
            return np.argmax(out, axis=-1)
        return (out.reshape(len(out), -1)[:, 0] > 0).astype(np.int64)

    # -- persistence ----------------------------------------------------------

    def save_model(self, path: str) -> str:
        """Weights + config in one directory (reference: saveModule)."""
        est = getattr(self, "_estimator", None)
        if est is None or est._ts is None:
            raise ValueError("model has no trained/initialized weights; "
                             "compile() and run fit/predict first")
        os.makedirs(path, exist_ok=True)
        ckpt_io.save(os.path.join(path, "weights"), est.get_model())
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump({"class": type(self).__name__,
                       "config": self._config}, f)
        return path

    @staticmethod
    def load_model(path: str) -> "ZooModel":
        """Rebuild from a save_model directory (class + config + weights)."""
        with open(os.path.join(path, "config.json")) as f:
            meta = json.load(f)
        cls = _REGISTRY[meta["class"]]
        model = cls(**meta["config"])
        model._loaded_variables = ckpt_io.restore(
            os.path.join(path, "weights"))
        return model

    # back-compat alias: compile() now injects loaded weights itself
    def compile_with_loaded(self, loss: Any, **kw: Any) -> "ZooModel":
        return self.compile(loss, **kw)
