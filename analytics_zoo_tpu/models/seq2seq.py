"""Seq2seq (reference: zoo.models.seq2seq — models/seq2seq/*.scala:
Seq2seq, RNNEncoder, RNNDecoder, Bridge).

Encoder-decoder over LSTM/GRU stacks with an optional dense Bridge mapping
encoder final states to decoder initial states, and optional Luong dot
attention over encoder outputs.  Teacher-forced training (decoder input =
shifted target), greedy ``infer`` loop via lax.scan — compiled, no Python
step loop (the reference single-stepped on the JVM).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.nn.module import Module
from .common import ZooModel


class RNNEncoder(Module):
    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 64, embedding: Optional[Module] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.rnn_type = rnn_type
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.embedding = embedding

    def forward(self, scope, x):
        if self.embedding is not None:
            x = scope.child(self.embedding, x, name="embed")
        for i in range(self.num_layers):
            cls = nn.LSTM if self.rnn_type == "lstm" else nn.GRU
            x = scope.child(cls(self.hidden_size, return_sequences=True), x,
                            name=f"rnn_{i}")
        return x


class RNNDecoder(Module):
    """Stacked decoder RNN.  Context injection: the bridge's summary vector
    arrives as the FIRST timestep of ``x`` (prepended by Seq2seq) — our RNN
    layers are carry-free, so state is injected through the input sequence,
    and the caller drops the first output step."""

    def __init__(self, rnn_type: str = "lstm", num_layers: int = 1,
                 hidden_size: int = 64, embedding: Optional[Module] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.rnn_type = rnn_type
        self.num_layers = num_layers
        self.hidden_size = hidden_size
        self.embedding = embedding

    def forward(self, scope, x):
        if self.embedding is not None:
            x = scope.child(self.embedding, x, name="embed")
        for i in range(self.num_layers):
            cls = nn.LSTM if self.rnn_type == "lstm" else nn.GRU
            x = scope.child(cls(self.hidden_size, return_sequences=True), x,
                            name=f"rnn_{i}")
        return x


class Seq2seq(ZooModel):
    """x: dict-free interface — forward takes int ids [B, T_enc + T_dec]
    (encoder input ++ shifted decoder input), split by ``encoder_length``."""

    def __init__(self, vocab_size: int, embed_dim: int = 64,
                 hidden_size: int = 64, encoder_length: int = 10,
                 decoder_length: int = 10, rnn_type: str = "lstm",
                 num_layers: int = 1, use_attention: bool = False,
                 bridge: str = "dense", output_dim: Optional[int] = None):
        super().__init__()
        self._config = dict(vocab_size=vocab_size, embed_dim=embed_dim,
                            hidden_size=hidden_size,
                            encoder_length=encoder_length,
                            decoder_length=decoder_length, rnn_type=rnn_type,
                            num_layers=num_layers,
                            use_attention=use_attention, bridge=bridge,
                            output_dim=output_dim)
        for k, v in self._config.items():
            setattr(self, k, v)
        self.output_dim = output_dim or vocab_size

    def forward(self, scope, ids):
        enc_ids = ids[:, :self.encoder_length]
        dec_ids = ids[:, self.encoder_length:]
        embed = nn.Embedding(self.vocab_size, self.embed_dim)
        enc = RNNEncoder(self.rnn_type, self.num_layers, self.hidden_size,
                         embedding=embed)
        enc_out = scope.child(enc, enc_ids, name="encoder")

        # Bridge: map encoder summary → a context vector prepended to the
        # decoder input sequence (state injection without stateful cells)
        summary = enc_out[:, -1]
        if self.bridge == "dense":
            summary = scope.child(nn.Dense(self.hidden_size), summary,
                                  name="bridge")
        dec_in = scope.child(nn.Embedding(self.vocab_size, self.embed_dim),
                             dec_ids, name="dec_embed")
        ctx = summary[:, None, :]
        if ctx.shape[-1] != dec_in.shape[-1]:
            ctx = scope.child(nn.Dense(self.embed_dim), summary,
                              name="ctx_proj")[:, None, :]
        h = jnp.concatenate([ctx, dec_in], axis=1)  # [B, 1+T_dec, E]
        dec = RNNDecoder(self.rnn_type, self.num_layers, self.hidden_size)
        h = scope.child(dec, h, name="decoder")
        h = h[:, 1:]                                # drop the context step
        if self.use_attention:
            # Luong dot attention over encoder outputs
            att = jax.nn.softmax(
                jnp.einsum("btd,bsd->bts", h, enc_out), axis=-1)
            c = jnp.einsum("bts,bsd->btd", att, enc_out)
            h = scope.child(nn.Dense(self.hidden_size, activation="tanh"),
                            jnp.concatenate([h, c], axis=-1), name="att_comb")
        return scope.child(nn.Dense(self.output_dim), h, name="head")

    def infer(self, enc_ids, start_id: int = 0, max_length: Optional[int] = None
              ):
        """Greedy decode: returns int ids [B, max_length] (compiled scan)."""
        import numpy as np
        max_length = max_length or self.decoder_length
        est = self.estimator
        if est._ts is None:
            raise ValueError("fit/compile the model first")
        variables = {"params": est._ts["params"], "state": est._ts["state"]}
        enc_ids = jnp.asarray(np.asarray(enc_ids))
        b = enc_ids.shape[0]

        def dec_step(tokens, _):
            full = jnp.concatenate([enc_ids, tokens], axis=1)
            logits, _ = self.apply(variables, full)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(tokens.dtype)
            return jnp.concatenate([tokens[:, 1:], nxt[:, None]], axis=1), nxt

        tokens0 = jnp.full((b, self.decoder_length), start_id,
                           enc_ids.dtype)
        _, outs = jax.lax.scan(dec_step, tokens0, None, length=max_length)
        return np.asarray(outs.T)
