"""DataFeed: host-side batching + prefetch feeding the device mesh.

Reference (SURVEY.md §2.2, §3.2): data reached compute through per-framework
feeders — BigDL MiniBatch from FeatureSet, ``tf.data`` per TFRunner actor,
torch DataLoader per TorchRunner — all downstream of a Spark→Ray object-store
hop.  TPU-native: each host process batches its local numpy data and places
it directly onto its devices, sharded along the mesh's batch axes
(``data``/``fsdp``).  XLA overlaps the host→HBM copy of batch N+1 with the
compute of batch N because ``jax.device_put`` dispatches asynchronously; we
additionally keep a one-batch lookahead so the host-side slicing/stacking is
off the critical path.

Static shapes: batches are fixed-size (remainder dropped or padded) so the
``jit``-compiled train step compiles exactly once.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any, Dict, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analytics_zoo_tpu.core import metrics as _metrics_lib
from analytics_zoo_tpu.core.faults import get_registry as _fault_registry
from .shards import XShards

BATCH_AXES = ("data", "fsdp")  # mesh axes a batch dim is sharded over


def batch_sharding(mesh: Mesh, leaf_rank: int = 1,
                   seq_dim_size: Optional[int] = None,
                   dim0_size: Optional[int] = None) -> NamedSharding:
    """NamedSharding that shards dim 0 over the mesh's batch axes.

    ``seq_dim_size``: pass the leaf's dim-1 size to ALSO shard dim 1 over the
    mesh's ``seq`` axis (sequence/context parallelism) — applied only to
    feature ('x') leaves whose dim 1 divides the axis; labels and
    non-divisible shapes stay batch-sharded only.

    ``dim0_size``: pass the leaf's GLOBAL dim-0 size so a batch that does
    not divide the batch axes falls back to replicated placement (small
    inference batches must work on any mesh) instead of erroring.  The
    fallback is only legal single-process: with multiple processes each
    host holds different rows, and a "replicated" assembly would silently
    disagree across hosts — there we raise instead."""
    present = tuple(a for a in BATCH_AXES if a in mesh.axis_names)
    dim0 = present if present else None
    if dim0 is not None and dim0_size is not None:
        axis_size = int(np.prod([mesh.shape[a] for a in present]))
        if dim0_size % axis_size != 0:
            if jax.process_count() > 1:
                raise ValueError(
                    f"global batch dim {dim0_size} does not divide the "
                    f"mesh batch axes (size {axis_size}); pick a batch "
                    "size divisible by the data/fsdp axes in multihost "
                    "runs (no replicated fallback across processes)")
            dim0 = None
    seq_ok = (seq_dim_size is not None and leaf_rank >= 2
              and "seq" in mesh.axis_names and mesh.shape["seq"] > 1
              and seq_dim_size % mesh.shape["seq"] == 0)
    if seq_ok:
        spec = P(dim0, "seq", *([None] * (leaf_rank - 2)))
    else:
        spec = P(dim0, *([None] * (leaf_rank - 1)))
    return NamedSharding(mesh, spec)


def batch_axis_size(mesh: Mesh) -> int:
    size = 1
    for a in BATCH_AXES:
        if a in mesh.axis_names:
            size *= mesh.shape[a]
    return size


def shard_batch(batch: Any, mesh: Mesh) -> Any:
    """Place a host-local pytree of numpy arrays onto the mesh.

    Single-process: ``device_put`` splits the global batch across devices.
    Multi-process: each process passes its *local* slice and
    ``make_array_from_process_local_data`` assembles the global logical array
    (the SPMD contract: global batch = concat of per-host batches).
    """
    multi = jax.process_count() > 1

    def place(leaf: np.ndarray, is_feature: bool) -> jax.Array:
        leaf = np.asarray(leaf)
        seq_size = leaf.shape[1] if (is_feature and leaf.ndim >= 2) else None
        # dim0_size must be the GLOBAL batch: each process contributes an
        # equal local slice, so global = local * process_count
        dim0 = (leaf.shape[0] * jax.process_count() if multi
                else leaf.shape[0]) if leaf.ndim else None
        sharding = batch_sharding(mesh, max(leaf.ndim, 1),
                                  seq_dim_size=seq_size,
                                  dim0_size=dim0)
        if multi:
            return jax.make_array_from_process_local_data(sharding, leaf)
        return jax.device_put(leaf, sharding)

    if isinstance(batch, dict):
        # seq-axis sharding applies to features only, never labels
        return {k: jax.tree_util.tree_map(
                    lambda l: place(l, is_feature=(k == "x")), v)
                for k, v in batch.items()}
    return jax.tree_util.tree_map(lambda l: place(l, True), batch)


class FeedBase:
    """Shared feed contract: global-vs-local batch math, epoch step count,
    and the per-epoch shuffle index.  ``batch_size`` is the **global** batch
    (reference Estimator semantics: pyzoo/zoo/orca/learn/pytorch/
    pytorch_ray_estimator.py divided it across workers); each host
    contributes batch_size / process_count rows."""

    def __init__(self, num_samples: int, batch_size: int, shuffle: bool,
                 seed: int, drop_remainder: bool):
        self._n = num_samples
        self.global_batch = batch_size
        self._local_batch = max(1, batch_size // max(1, jax.process_count()))
        self.shuffle = shuffle
        self.seed = seed
        self.drop_remainder = drop_remainder

    @property
    def num_rows(self) -> int:
        return self._n

    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return self._n // self._local_batch
        return -(-self._n // self._local_batch)

    def _epoch_index(self, epoch_idx: int) -> np.ndarray:
        """Row order for one epoch; also validates it yields >= 1 batch."""
        if self.steps_per_epoch() == 0:
            raise ValueError(
                f"dataset of {self._n} rows yields no batches of local "
                f"size {self._local_batch}")
        idx = np.arange(self._n)
        if self.shuffle:
            np.random.default_rng(self.seed + epoch_idx).shuffle(idx)
        return idx

    def _batch_index(self, idx: np.ndarray, step: int) -> np.ndarray:
        sel = idx[step * self._local_batch:(step + 1) * self._local_batch]
        if len(sel) < self._local_batch:  # pad the last partial batch
            sel = np.resize(sel, self._local_batch)
        return sel

    def step_mask(self, step: int) -> np.ndarray:
        """Real-row weights for this process's ``step`` batch: 1.0 for rows
        that exist, 0.0 for padding (only the last non-drop_remainder batch
        is ever padded).  Lets a jit-compiled eval step cover the tail rows
        exactly under static shapes."""
        real = min(self._local_batch,
                   max(0, self._n - step * self._local_batch))
        m = np.zeros((self._local_batch,), np.float32)
        m[:real] = 1.0
        return m

    def dropped_rows(self, epoch_idx: int = 0):
        """The rows a drop_remainder epoch skips, respecting THAT epoch's
        shuffle order (shuffled feeds drop a permutation-dependent tail).
        None if nothing is dropped or the subclass cannot reconstruct them
        (callers fall back to a warning)."""
        if not self.shuffle:
            return self.remainder()
        return None


class DataFeed(FeedBase):
    """An epoch-iterable source of device-resident, mesh-sharded batches,
    holding the whole (host-local) dataset in RAM.  For datasets that don't
    fit, use stream.StreamingDataFeed."""

    def __init__(self, data: Dict[str, Any], batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_remainder: bool = True):
        if "x" not in data:
            raise ValueError("DataFeed requires at least an 'x' entry")
        self._data = {k: v for k, v in data.items()}
        n = _nrows(self._data["x"])
        for k, v in self._data.items():
            if _nrows(v) != n:
                raise ValueError(
                    f"feature/label row mismatch: {k} has {_nrows(v)} rows, "
                    f"x has {n}")
        super().__init__(n, batch_size, shuffle, seed, drop_remainder)

    # -- constructors ---------------------------------------------------------

    @staticmethod
    def from_arrays(x: Any, y: Any = None, batch_size: int = 32,
                    **kw: Any) -> "DataFeed":
        data = {"x": x}
        if y is not None:
            data["y"] = y
        return DataFeed(data, batch_size, **kw)

    @staticmethod
    def from_shards(shards: XShards, batch_size: int = 32,
                    **kw: Any) -> "DataFeed":
        """Numpy-dict XShards ({"x": ..., "y": ...}) → DataFeed."""
        data = shards.concatenated()
        if not isinstance(data, dict):
            data = {"x": data}
        return DataFeed(data, batch_size, **kw)

    # -- iteration ------------------------------------------------------------

    def remainder(self) -> Optional[Dict[str, np.ndarray]]:
        """The tail rows a drop_remainder epoch skips (unshuffled order), or
        None.  Used by Estimator.evaluate so metrics cover every row."""
        r = self._n % self._local_batch
        if r == 0:
            return None
        sel = np.arange(self._n - r, self._n)
        return jax.tree_util.tree_map(lambda a: _take(a, sel), self._data)

    def dropped_rows(self, epoch_idx: int = 0):
        """Exact drop_remainder coverage even when shuffled: the dropped
        rows are the tail of THIS epoch's permutation."""
        r = self._n % self._local_batch
        if r == 0:
            return None
        sel = self._epoch_index(epoch_idx)[self._n - r:]
        return jax.tree_util.tree_map(lambda a: _take(a, sel), self._data)

    def epoch(self, mesh: Mesh, epoch_idx: int = 0
              ) -> Iterator[Dict[str, jax.Array]]:
        """Yield mesh-sharded batches for one epoch (one-batch lookahead)."""
        idx = self._epoch_index(epoch_idx)
        steps = self.steps_per_epoch()
        # batch-assembly latency (slice + stack + device_put dispatch):
        # the host-side cost the one-batch lookahead hides from training
        m_assemble = _metrics_lib.get_registry().histogram(
            "feed.batch_assembly_ms")

        def host_batch(step: int) -> Dict[str, np.ndarray]:
            t0 = time.monotonic()
            sel = self._batch_index(idx, step)
            out = jax.tree_util.tree_map(
                lambda a: _take(a, sel), self._data)
            m_assemble.observe((time.monotonic() - t0) * 1000.0)
            return out

        pending = shard_batch(host_batch(0), mesh)
        for step in range(steps):
            # ``feed.stall`` injection point (core/faults.py): an armed
            # delay models a slow storage read / augmentation hiccup, so
            # resilience tests can prove training-side timing behavior
            _fault_registry().fire("feed.stall")
            nxt = (shard_batch(host_batch(step + 1), mesh)
                   if step + 1 < steps else None)
            yield pending
            pending = nxt


class PrefetchIterator:
    """Depth-bounded background prefetch over a batch iterator.

    A producer thread drives the wrapped iterator — for DataFeed /
    StreamingDataFeed epochs that means the host-side batch indexing,
    ``shard_batch`` and the ``device_put`` dispatch all happen OFF the
    training thread — and parks up to ``depth`` ready batches in a
    bounded queue (``depth=2`` is classic double buffering: batch k+1
    stages while the device computes batch k, and one more is in
    flight).  The consumer's ``next()`` then only blocks when the feed
    is genuinely slower than the step, which is exactly what the
    ``train.data_wait_ms`` histogram should measure.

    ``place``: optional callable applied to every item INSIDE the
    producer thread (e.g. ``stream.make_placer(mesh)`` = ``shard_batch``
    over host batches).  With ``depth >= 2`` this is double-buffered
    ``device_put``: the host→HBM copy of batch N+1 is dispatched — and
    completes — while the device computes batch N.  Items carrying a
    ``release()`` handle (shared-memory pool slots from the streaming
    feed's process backend) are retired one item behind the placement:
    once the NEXT item is dispatched, the previous transfer is synced
    (its unhidden tail observed as ``feed.h2d_ms``) and the slot
    recycled.

    Exceptions from the producer (loader failures, injected
    ``feed.stall``-adjacent faults) re-raise in the consumer at the
    position they occurred.  ``close()`` is safe mid-epoch (rollback,
    preemption, crash injection): it unblocks and joins the producer
    without draining the rest of the epoch.
    """

    _END = object()

    def __init__(self, it: Iterator, depth: int = 2,
                 gauge: Optional[Any] = None,
                 place: Optional[Any] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._it = iter(it)
        self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
        self._gauge = gauge  # e.g. the train.prefetch_depth gauge
        self._place = place
        self._staged = None  # (placed, releasable_raw, dispatch_ms)
        self._m_h2d = (_metrics_lib.get_registry().histogram("feed.h2d_ms")
                       if place is not None else None)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._produce, daemon=True,
                                        name="zoo-prefetch")
        self._thread.start()

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                if self._gauge is not None:
                    self._gauge.set(self._q.qsize())
                return True
            except queue_mod.Full:
                continue
        return False

    def _stage(self, raw: Any) -> Any:
        """Dispatch the device copy of THIS item, then retire the
        previous one (sync its transfer tail, recycle its pool slot) —
        the one-item lag is what guarantees a slot is never reused
        while its bytes are still in flight to the device."""
        t0 = time.monotonic()
        placed = self._place(raw)
        disp_ms = (time.monotonic() - t0) * 1000.0
        self._retire()
        self._staged = (placed, raw if hasattr(raw, "release") else None,
                        disp_ms)
        return placed

    def _retire(self) -> None:
        # producer-thread only (close() leaves the last slot to the
        # SlotBatch GC safety net rather than racing the producer)
        staged, self._staged = self._staged, None
        if staged is None:
            return
        placed, raw, disp_ms = staged
        if raw is not None:
            t0 = time.monotonic()
            jax.block_until_ready(placed)
            if self._m_h2d is not None:
                self._m_h2d.observe(
                    disp_ms + (time.monotonic() - t0) * 1000.0)
            raw.release()
        elif self._m_h2d is not None:
            # no slot to recycle (thread backend): no forced sync, but
            # the dispatch half keeps per-backend h2d comparable
            self._m_h2d.observe(disp_ms)

    def _produce(self) -> None:
        try:
            for batch in self._it:
                if self._place is not None:
                    batch = self._stage(batch)
                if not self._put(("item", batch)):
                    return  # closed mid-epoch
                if self._stop.is_set():
                    return
            self._retire()
        except BaseException as e:  # noqa: BLE001 — re-raised in consumer
            self._put(("error", e))
            return
        self._put((self._END, None))

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        kind, payload = self._q.get()
        if self._gauge is not None:
            self._gauge.set(self._q.qsize())
        if kind == "item":
            return payload
        self._stop.set()
        if kind == "error":
            raise payload
        raise StopIteration

    def close(self, timeout: float = 5.0) -> None:
        """Stop the producer and reclaim its thread (idempotent).  The
        wait is BOUNDED: a producer wedged inside the wrapped iterator
        itself (a hung loader) cannot be interrupted from here — after
        ``timeout`` the daemon thread is abandoned (it exits at its next
        queue handoff) rather than turning the caller's own exit (e.g. a
        clean preemption) into a hang."""
        self._stop.set()
        deadline = time.monotonic() + timeout
        while self._thread.is_alive():
            try:  # unblock a producer stuck on a full queue
                self._q.get_nowait()
            except queue_mod.Empty:
                pass
            self._thread.join(timeout=0.05)
            if time.monotonic() > deadline:
                break
        if not self._thread.is_alive():
            close_it = getattr(self._it, "close", None)
            if close_it is not None:
                try:  # prompt generator cleanup (stream feeds join
                    close_it()  # their decode workers)
                except (RuntimeError, ValueError):
                    pass
        if self._gauge is not None:
            self._gauge.set(0.0)


def as_feed(data: Any, batch_size: int, **kw: Any) -> DataFeed:
    """Coerce the estimator's accepted data forms into a DataFeed.

    Accepts: DataFeed (passthrough), XShards of numpy dicts, a (x, y) tuple,
    a dict {"x": ..., "y": ...}, or a bare array (unsupervised).
    """
    if isinstance(data, FeedBase):
        return data  # DataFeed / StreamingDataFeed / any FeedBase subclass
    if isinstance(data, XShards):
        return DataFeed.from_shards(data, batch_size, **kw)
    if isinstance(data, dict):
        return DataFeed(data, batch_size, **kw)
    if isinstance(data, tuple) and len(data) == 2:
        return DataFeed.from_arrays(data[0], data[1], batch_size, **kw)
    return DataFeed.from_arrays(data, None, batch_size, **kw)


def _nrows(v: Any) -> int:
    if isinstance(v, (tuple, list)):
        return _nrows(v[0])
    if isinstance(v, dict):
        return _nrows(next(iter(v.values())))
    return len(v)


def _take(a: Any, sel: np.ndarray) -> np.ndarray:
    return np.asarray(a)[sel]
