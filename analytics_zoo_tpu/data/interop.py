"""Foreign input-pipeline interop: tf.data, torch Dataset/DataLoader, and
plain Python iterables → mesh-sharded device feeds.

Reference (SURVEY.md §2.2): "orca TF Dataset" wrapped ``tf.data.Dataset``
for the TF estimators (pyzoo/zoo/orca/data/tf/data.py), TFPark's
``TFDataset`` fed per-worker queues, and the torch estimators took
``data_creator`` functions returning DataLoaders
(pyzoo/zoo/orca/learn/pytorch/).  Each framework owned its own feeding
stack.

TPU-native collapse: every foreign source becomes one of two feeds —

- map-style sources (torch ``Dataset.__getitem__``) ride
  ``StreamingDataFeed``: native-queue prefetch, worker threads, step-order
  delivery — the full input pipeline, with the foreign object only
  supplying ``load_sample``;
- stream-style sources (``tf.data.Dataset``, generators, torch
  ``IterableDataset``) ride ``IterableDataFeed``: re-batched to the global
  batch, final partial batch padded + masked so evaluate stays exact.

TensorFlow is NOT a dependency: ``from_tf_dataset`` imports it lazily and
raises a clear error when absent.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np
from jax.sharding import Mesh

from .feed import FeedBase, shard_batch


def _as_sample_dict(elem: Any) -> Dict[str, Any]:
    if isinstance(elem, dict):
        return elem
    if isinstance(elem, (tuple, list)):
        if len(elem) == 2:
            return {"x": elem[0], "y": elem[1]}
        if len(elem) == 1:
            return {"x": elem[0]}
        raise ValueError(
            f"sample tuples must be (x,) or (x, y); got {len(elem)} items")
    return {"x": elem}


class IterableDataFeed(FeedBase):
    """Unknown-length sample stream → fixed-shape device batches.

    ``make_iter(epoch_idx)`` returns a fresh iterator of samples (dicts,
    (x, y) tuples, or bare arrays).  The final partial batch is padded to
    the static shape and carries a ``mask`` entry weighting padding rows 0
    (Estimator.evaluate consumes it for exact metrics); with
    ``drop_remainder`` the tail is dropped instead.  After one pass the
    true row count is known (``num_rows``), which Estimator.predict reads
    after iterating."""

    def __init__(self, make_iter: Callable[[int], Iterator[Any]],
                 batch_size: int, drop_remainder: bool = False,
                 seed: int = 0, pre_sharded: bool = False):
        """``pre_sharded``: the iterator already yields only THIS process's
        samples (e.g. a tf.data pipeline with ``.shard(...)``).  Default
        False: in multihost runs each process strides the shared stream
        (keeps sample ``i`` iff ``i %% process_count == process_index``) so
        the assembled global batch holds each sample exactly once."""
        super().__init__(num_samples=0, batch_size=batch_size,
                         shuffle=False, seed=seed,
                         drop_remainder=drop_remainder)
        self._make_iter = make_iter
        self.pre_sharded = pre_sharded

    def steps_per_epoch(self) -> int:
        if self._n:
            return super().steps_per_epoch()
        return -1  # unknown until one pass completes

    def remainder(self) -> Optional[Dict[str, np.ndarray]]:
        return None  # the padded+masked final batch covers the tail

    def step_mask(self, step: int) -> np.ndarray:
        # masks are attached by epoch() itself (length unknown up front)
        return np.ones((self._local_batch,), np.float32)

    def epoch(self, mesh: Mesh, epoch_idx: int = 0
              ) -> Iterator[Dict[str, Any]]:
        import jax as _jax
        multi = _jax.process_count() > 1
        it = self._make_iter(epoch_idx)
        if not self.pre_sharded and multi:
            pidx, pcount = _jax.process_index(), _jax.process_count()
            it = (e for i, e in enumerate(it) if i % pcount == pidx)
        lb = self._local_batch
        count = 0
        pending = None
        last_row: Any = None
        exhausted = False

        def flush(batch_rows, n_real, include_mask):
            batch = {k: np.stack([np.asarray(r[k]) for r in batch_rows])
                     for k in batch_rows[0]}
            if include_mask:
                m = np.zeros((len(batch_rows),), np.float32)
                m[:n_real] = 1.0
                batch["mask"] = m
            return shard_batch(batch, mesh)

        while True:
            rows: list = []
            while len(rows) < lb and not exhausted:
                try:
                    rows.append(_as_sample_dict(next(it)))
                    count += 1
                except StopIteration:
                    exhausted = True
            n_real = len(rows)
            if multi:
                # SPMD consensus: every process must emit the same number
                # of (global) batches — and agree on the batch STRUCTURE
                # (mask present or not) — even when stream lengths differ;
                # a process that ran dry emits all-masked filler batches
                # until the slowest stream finishes
                from jax.experimental import multihost_utils
                starved = n_real == 0 and last_row is None
                stats = multihost_utils.process_allgather(
                    np.asarray([n_real, int(starved)], np.int32))
                reals = stats[..., 0]
                if int(np.max(reals)) == 0:
                    break
                if int(np.max(stats[..., 1])):
                    # raise on EVERY process (a local-only raise would
                    # leave the peers hanging in the next collective)
                    raise ValueError(
                        "a process received zero samples while others have "
                        "data; give every host samples (or use "
                        "pre_sharded=False striding)")
                include_mask = int(np.min(reals)) < lb
            elif n_real == 0:
                break
            else:
                include_mask = n_real < lb
            if include_mask and self.drop_remainder and not multi:
                break
            if n_real < lb:
                filler = rows[-1] if rows else last_row
                rows = rows + [filler] * (lb - n_real)
            last_row = rows[-1]
            if pending is not None:
                yield pending  # one-batch lookahead, like DataFeed
            pending = flush(rows, n_real, include_mask)
            if exhausted and not multi:
                break
        self._n = count
        if pending is not None:
            yield pending


def from_iterator(make_iter: Callable[[int], Iterator[Any]],
                  batch_size: int, **kw: Any) -> IterableDataFeed:
    """Generic stream → feed.  ``make_iter(epoch_idx)`` yields samples."""
    return IterableDataFeed(make_iter, batch_size, **kw)


def from_tf_dataset(dataset: Any, batch_size: int, batched: bool = False,
                    **kw: Any) -> IterableDataFeed:
    """``tf.data.Dataset`` → feed.

    Elements map like any sample: dict passthrough, (x, y) tuple, or a
    single tensor.  Pass ``batched=True`` for a dataset that already went
    through ``.batch(...)`` — it is unbatched and re-batched to the GLOBAL
    batch (multihost semantics tf can't know about).  No shape-based
    guessing: a leading None dim also legitimately means ragged sequences.
    Re-iterated per epoch, so shuffling/augmentation inside the tf pipeline
    re-applies each epoch."""
    try:
        import tensorflow as tf  # noqa: F401  (optional dependency)
    except ImportError as e:
        raise ImportError(
            "from_tf_dataset needs tensorflow installed "
            "(pip install analytics-zoo-tpu[tf])") from e
    if batched:
        dataset = dataset.unbatch()

    def make_iter(epoch_idx: int):
        return iter(dataset.as_numpy_iterator())

    return IterableDataFeed(make_iter, batch_size, **kw)


def from_torch_dataset(dataset: Any, batch_size: int, shuffle: bool = True,
                       num_workers: int = 4, seed: int = 0,
                       **kw: Any):
    """Map-style ``torch.utils.data.Dataset`` → StreamingDataFeed (native-
    queue prefetch + worker threads run ``dataset[i]`` off the critical
    path).  Iterable-style datasets go through ``from_iterator``."""
    if hasattr(dataset, "__getitem__") and hasattr(dataset, "__len__"):
        from .stream import StreamingDataFeed

        def load_sample(i: int, rng=None) -> Dict[str, np.ndarray]:
            return _to_numpy_sample(dataset[i])

        return StreamingDataFeed(len(dataset), load_sample, batch_size,
                                 shuffle=shuffle, num_workers=num_workers,
                                 seed=seed, **kw)
    return IterableDataFeed(lambda e: iter(dataset), batch_size,
                            seed=seed, **kw)


def from_torch_dataloader(loader: Any, batch_size: Optional[int] = None,
                          **kw: Any) -> IterableDataFeed:
    """``torch.utils.data.DataLoader`` → feed.  The loader's own batching
    is flattened back to samples, then re-batched to the GLOBAL batch
    (multihost semantics the loader can't know about)."""
    bs = batch_size or getattr(loader, "batch_size", None) or 32

    def make_iter(epoch_idx: int):
        for batch in loader:
            sample = _to_numpy_sample(batch)
            n = len(next(iter(sample.values())))
            for i in range(n):
                yield {k: v[i] for k, v in sample.items()}

    return IterableDataFeed(make_iter, bs, **kw)


def _to_numpy_sample(elem: Any) -> Dict[str, np.ndarray]:
    def to_np(v):
        if hasattr(v, "detach"):  # torch tensor
            return v.detach().cpu().numpy()
        return np.asarray(v)

    sample = _as_sample_dict(elem)
    return {k: to_np(v) for k, v in sample.items()}
