"""Preallocated shared-memory batch-buffer pool for multi-process decode.

The streaming feed's process backend (data/stream.py) forks decode
workers; the expensive part of multi-process input pipelines is normally
the transport — pickling every decoded row through a ``multiprocessing``
queue costs a serialize + copy + deserialize per row (tens of MB/s of
pure overhead at ImageNet scale).  This pool removes the transport
entirely: the parent preallocates ``slots`` batch-sized buffers in
``multiprocessing.shared_memory`` **before forking**, so parent and
children share the same physical pages, and a worker decodes each row
*directly into its batch's final position* (no per-row pickle, no
per-batch ``np.stack``).  The only thing that crosses the process
boundary per batch is a few-int control message.

Lifecycle contract:

- slots circulate through a fork-safe free queue: ``acquire()`` blocks
  when every slot is in flight — that bound IS the feed's memory bound
  (the process analog of workers blocking on the full native queue);
- ``release(slot)`` is idempotent per cycle and callable from any
  parent thread (the feed releases a crashed worker's half-written slot
  on its behalf);
- ``close()`` unlinks every segment (idempotent; also attempted on GC),
  so an exhausted or abandoned epoch leaves nothing in ``/dev/shm`` —
  asserted by test.

``available()`` gates the whole backend: no ``shared_memory`` module or
no ``fork`` start method (the backend relies on fork inheritance so the
user's ``load_sample`` closure never needs to be picklable) means the
feed falls back to threads.
"""

from __future__ import annotations

import logging
import os
import queue as pyqueue
import threading
import uuid
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger("analytics_zoo_tpu")

#: /dev/shm name prefix for every segment this pool creates — leak checks
#: (tests, ops) can glob for it.
SHM_PREFIX = "zoofeed"

_ALIGN = 64  # per-key offset alignment inside a slot segment


def available() -> bool:
    """Can the process decode backend run here?  Needs
    ``multiprocessing.shared_memory`` (py3.8+) and the ``fork`` start
    method (Linux; fork inheritance is what makes arbitrary
    ``load_sample`` closures work without pickling)."""
    try:
        from multiprocessing import shared_memory  # noqa: F401
        import multiprocessing as mp
        return "fork" in mp.get_all_start_methods()
    except (ImportError, AttributeError):
        return False


class ShmBatchPool:
    """``slots`` preallocated batch buffers in POSIX shared memory.

    ``spec``: ``{key: (row_shape, dtype)}`` — one fixed-size segment per
    slot holds every key's ``[batch, *row_shape]`` array at an aligned
    offset.  ``views(slot)`` returns zero-copy numpy views over the
    slot; the views built here (pre-fork) are inherited by forked
    workers, so both sides address the same pages.
    """

    def __init__(self, slots: int, batch: int,
                 spec: Dict[str, Tuple[Tuple[int, ...], np.dtype]],
                 ctx=None):
        from multiprocessing import shared_memory
        if ctx is None:
            import multiprocessing as mp
            ctx = mp.get_context("fork")
        if slots < 2:
            raise ValueError(f"pool needs >= 2 slots (one filling, one "
                             f"consuming), got {slots}")
        self.slots = slots
        self.batch = batch
        self.spec = {k: (tuple(shape), np.dtype(dt))
                     for k, (shape, dt) in spec.items()}
        # segment layout: aligned per-key offsets
        offsets: Dict[str, int] = {}
        off = 0
        for k, (shape, dt) in self.spec.items():
            nbytes = int(batch * int(np.prod(shape, dtype=np.int64))
                         * dt.itemsize)
            offsets[k] = off
            off += -(-nbytes // _ALIGN) * _ALIGN
        self._nbytes = max(off, _ALIGN)
        self._offsets = offsets
        self._segs = []
        self._views = []
        self._closed = False
        self._close_lock = threading.Lock()
        run = uuid.uuid4().hex[:8]
        try:
            for s in range(slots):
                seg = shared_memory.SharedMemory(
                    create=True, size=self._nbytes,
                    name=f"{SHM_PREFIX}_{os.getpid()}_{run}_{s}")
                self._segs.append(seg)
                self._views.append({
                    k: np.ndarray((batch,) + shape, dtype=dt,
                                  buffer=seg.buf, offset=offsets[k])
                    for k, (shape, dt) in self.spec.items()})
        except BaseException:
            self.close()
            raise
        # fork-safe slot circulation; qsize() on Linux is exact enough
        # for the feed.shm_in_use gauge
        self._free = ctx.Queue()
        for s in range(slots):
            self._free.put(s)

    # -- slot circulation -----------------------------------------------------

    def acquire(self, timeout: Optional[float] = None) -> Optional[int]:
        """Next free slot id; blocks (the memory bound) until one is
        released.  None on timeout."""
        try:
            return self._free.get(timeout=timeout)
        except pyqueue.Empty:
            return None

    def release(self, slot: int) -> None:
        """Return a slot to the free queue (no-op after close)."""
        if self._closed:
            return
        try:
            self._free.put(slot)
        except (ValueError, OSError, AssertionError):
            pass  # pool closing under us: segments are being unlinked

    def views(self, slot: int) -> Dict[str, np.ndarray]:
        """Zero-copy ``{key: [batch, *row_shape]}`` numpy views over the
        slot's shared pages (same dict object every call)."""
        return self._views[slot]

    def in_use(self) -> int:
        """Approximate slots currently out of the free queue."""
        if self._closed:
            return 0
        try:
            return self.slots - self._free.qsize()
        except (NotImplementedError, OSError):
            return 0

    # -- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        """Unlink every segment (idempotent).  Parent-only: children
        never unlink — the parent owns segment lifetime, which is what
        keeps a crashed worker from taking the pool down with it."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        free = getattr(self, "_free", None)
        if free is not None:
            try:
                free.close()
                free.cancel_join_thread()
            except (OSError, AttributeError):
                pass
        self._views = []
        for seg in self._segs:
            try:
                seg.close()
            except (OSError, BufferError):
                pass
            try:
                seg.unlink()
            except FileNotFoundError:
                pass
            except OSError:
                pass
        self._segs = []

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class SlotBatch(dict):
    """One decoded batch living in a pool slot: a plain dict of
    zero-copy numpy views plus the slot's release handle.

    The consumer (or the feed's placed path, after the device copy of
    the batch completes) calls ``release()`` to return the slot;
    holding a view past release means the pool may overwrite it — the
    standard buffer-pool contract.  GC releases as a safety net, so a
    consumer that copies (``np.stack``/``np.asarray``) and drops the
    batch keeps the pipeline flowing without ever naming the slot."""

    def __init__(self, views: Dict[str, np.ndarray], slot: int,
                 pool: ShmBatchPool):
        super().__init__(views)
        self._slot = slot
        self._pool = pool

    def release(self) -> None:
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.release(self._slot)

    def __del__(self):
        try:
            self.release()
        except Exception:  # noqa: BLE001 — GC during teardown
            pass
