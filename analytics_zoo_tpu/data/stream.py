"""StreamingDataFeed: bounded-memory input pipeline over the native queue.

Reference (SURVEY.md §2.2): FeatureSet cached the training set in DRAM/PMEM
native arrays and fed per-worker mini-batches; the PMEM path existed
precisely because datasets outgrow RAM.  DataFeed (feed.py) is the
whole-dataset-in-RAM analog — fine for MNIST, disqualifying for ImageNet.

This feed never materializes the dataset: worker threads pull sample
indices, run the user loader (decode + augment for images), and stack
batches.  The bounded C++ MPMC queue (native/zoo_native.cpp) is the
synchronization/backpressure primitive between decoders and the consumer:
workers push an 8-byte batch token (blocking when the bound is hit — that
bound IS the memory bound), while the batch arrays themselves stay
in-process in a token-keyed dict, so no payload bytes are copied.  The
consumer reorders tokens so batches always arrive in STEP ORDER regardless
of worker timing (predict depends on row order; training gets reproducible
batch sequences), and double-buffers device placement so the host→HBM copy
of batch N+1 overlaps compute of batch N.

Loader resilience: at ImageNet scale a corrupt JPEG or a flaky filesystem
read is routine, and a single exception must not cost an epoch.  Each
sample read gets ``retries`` bounded retries; after that,
``on_error="skip"`` substitutes a neighboring sample and counts the loss
(``skipped_rows``/``load_failures`` make the degradation visible, and
``max_skipped`` bounds it), while the default ``on_error="raise"``
propagates the failure to the consumer.  The ``feed.read_fail`` injection
point (core/faults.py) makes both paths deterministically testable.

Same interface as DataFeed (both subclass feed.FeedBase), so Estimator.fit
takes either interchangeably.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np
from jax.sharding import Mesh

from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.native import NativeQueue
from .feed import FeedBase, shard_batch

_ERROR_TOKEN = (1 << 63) - 1

#: How many alternative indices a skipped sample may be substituted with
#: before the failure is treated as systemic and re-raised.
_MAX_FALLBACK_TRIES = 8


class StreamingDataFeed(FeedBase):
    """Index-based streaming loader: ``load_sample(i, rng)`` → sample dict.

    ``retries``: per-sample reload attempts after a loader exception
    (0 = fail on first exception).  ``on_error``: what to do once retries
    are exhausted — ``"raise"`` (default) aborts the epoch with the
    loader's exception; ``"skip"`` substitutes the next loadable sample
    index and increments ``skipped_rows``.  ``max_skipped`` (with
    ``"skip"``) bounds silent degradation: exceeding it raises."""

    def __init__(self, num_samples: int,
                 load_sample: Callable[..., Dict[str, np.ndarray]],
                 batch_size: int, shuffle: bool = True, seed: int = 0,
                 num_workers: int = 4, prefetch_batches: int = 4,
                 drop_remainder: bool = True,
                 retries: int = 0, on_error: str = "raise",
                 max_skipped: Optional[int] = None):
        super().__init__(num_samples, batch_size, shuffle, seed,
                         drop_remainder)
        if on_error not in ("raise", "skip"):
            raise ValueError(f"on_error must be 'raise' or 'skip', "
                             f"got {on_error!r}")
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        self._load = load_sample
        self.num_workers = max(1, num_workers)
        self.prefetch_batches = max(1, prefetch_batches)
        self.retries = retries
        self.on_error = on_error
        self.max_skipped = max_skipped
        self._counter_lock = threading.Lock()
        self.skipped_rows = 0    # rows substituted because their sample
        #                          never loaded (on_error="skip")
        self.load_failures = 0   # loader exceptions seen (incl. retried)
        # telemetry (core/metrics.py): per-sample load latency + the
        # resilience counters mirrored process-wide, so "is the input
        # pipeline degrading?" is answerable without holding the feed
        reg = metrics_lib.get_registry()
        self._m_load = reg.histogram("feed.load_ms")
        self._m_failures = reg.counter("feed.load_failures")
        self._m_retries = reg.counter("feed.retries")
        self._m_skipped = reg.counter("feed.skipped_rows")
        # decoded-batch lookahead occupancy (high-water mark = realized
        # prefetch depth): a gauge pinned at 0 means the consumer eats
        # batches as fast as the workers decode them — the feed, not the
        # device, is the bottleneck
        self._m_ready = reg.gauge("feed.ready_depth")

    # -- resilient sample loading --------------------------------------------

    def _fault_registry(self):
        from analytics_zoo_tpu.core import faults
        return faults.get_registry()

    def _load_with_retry(self, i: int, rng,
                         inject: bool = True) -> Dict[str, np.ndarray]:
        """One sample through the loader with ``retries`` bounded retries.
        The ``feed.read_fail`` injection point sits INSIDE the attempt so
        an armed fault exercises the same except-clause a real corrupt
        read would — and is retried the same way.  ``inject=False`` for
        fallback substitution loads, so a fault armed against the primary
        sample cannot cascade into every substitute."""
        last: Optional[BaseException] = None
        for _attempt in range(self.retries + 1):
            try:
                if _attempt:
                    self._m_retries.inc()
                if inject:
                    self._fault_registry().raise_if("feed.read_fail",
                                                    OSError)
                t0 = time.monotonic()
                out = self._load(i, rng=rng)
                self._m_load.observe((time.monotonic() - t0) * 1000.0)
                return out
            except Exception as e:  # noqa: BLE001 — loader bugs vary freely
                last = e
                with self._counter_lock:
                    self.load_failures += 1
                self._m_failures.inc()
        assert last is not None
        raise last

    def _load_row(self, i: int, rng) -> Dict[str, np.ndarray]:
        """Sample ``i`` with retry + optional skip-and-substitute."""
        try:
            return self._load_with_retry(i, rng)
        except Exception:
            if self.on_error != "skip":
                raise
            with self._counter_lock:
                self.skipped_rows += 1
                skipped = self.skipped_rows
            self._m_skipped.inc()
            if self.max_skipped is not None and skipped > self.max_skipped:
                raise RuntimeError(
                    f"streaming feed skipped {skipped} rows "
                    f"(max_skipped={self.max_skipped}): loader failures "
                    "are no longer a tolerable minority") from None
            # substitute neighboring samples (no injection hits, plain
            # retries only) so the batch keeps its static shape
            for k in range(1, _MAX_FALLBACK_TRIES + 1):
                alt = (i + k) % self._n
                try:
                    return self._load_with_retry(alt, rng, inject=False)
                except Exception:
                    continue
            raise RuntimeError(
                f"sample {i} and {_MAX_FALLBACK_TRIES} fallback samples all "
                "failed to load: the failure is systemic, not per-sample")

    def remainder(self) -> Optional[Dict[str, np.ndarray]]:
        r = self._n % self._local_batch
        if r == 0:
            return None
        rng = np.random.default_rng(self.seed)
        rows = [self._load_row(i, rng) for i in range(self._n - r, self._n)]
        return {k: np.stack([row[k] for row in rows]) for k in rows[0]}

    def dropped_rows(self, epoch_idx: int = 0):
        """Exact drop_remainder coverage even when shuffled: reload the
        tail of this epoch's permutation through the sample loader."""
        r = self._n % self._local_batch
        if r == 0:
            return None
        sel = self._epoch_index(epoch_idx)[self._n - r:]
        rng = np.random.default_rng(self.seed)
        rows = [self._load_row(int(i), rng) for i in sel]
        return {k: np.stack([row[k] for row in rows]) for k in rows[0]}

    def epoch(self, mesh: Mesh, epoch_idx: int = 0, place: bool = True
              ) -> Iterator[Dict[str, "np.ndarray"]]:
        """``place=False`` yields host numpy batches (no device placement):
        the consumer owns staging, e.g. to stack K batches into one
        infeed-chunk transfer for ``Estimator._multi_step_data``."""
        idx = self._epoch_index(epoch_idx)
        steps = self.steps_per_epoch()

        # the bounded native queue carries batch tokens; ready holds the
        # actual arrays (at most prefetch_batches + num_workers entries,
        # because push blocks when the queue is full)
        queue = NativeQueue(max_items=self.prefetch_batches)
        ready: Dict[int, Dict[str, np.ndarray]] = {}
        ready_lock = threading.Lock()
        # one condition guards BOTH ready and errors: workers notify when
        # either changes, so the consumer never busy-waits
        ready_cond = threading.Condition(ready_lock)
        step_iter = iter(range(steps))
        step_lock = threading.Lock()
        errors: List[BaseException] = []

        def worker(wid: int) -> None:
            rng = np.random.default_rng(
                (self.seed + epoch_idx) * 10007 + wid)
            while True:
                with step_lock:
                    step = next(step_iter, None)
                if step is None:
                    return
                sel = self._batch_index(idx, step)
                try:
                    rows = [self._load_row(int(i), rng) for i in sel]
                    batch = {k: np.stack([r[k] for r in rows])
                             for k in rows[0]}
                except BaseException as e:          # noqa: BLE001 loader bug
                    with ready_cond:
                        errors.append(e)
                        ready_cond.notify_all()
                    try:
                        queue.push(_ERROR_TOKEN.to_bytes(8, "big"))
                    except RuntimeError:
                        pass                        # consumer already gone
                    return
                with ready_cond:
                    ready[step] = batch
                    self._m_ready.set(len(ready))
                    ready_cond.notify_all()
                try:
                    queue.push(step.to_bytes(8, "big"))  # blocks when full
                except RuntimeError:                # queue closed: abandon
                    return

        workers = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in workers:
            t.start()

        bound = self.prefetch_batches + self.num_workers

        def take(expected_step: int) -> Dict[str, np.ndarray]:
            """Next batch in step order; holds out-of-order arrivals.  Live
            because steps are claimed in order: the token for
            ``expected_step`` is pushed or being produced.  Bounded because
            once ``ready`` holds ``bound`` batches the consumer stops
            draining tokens — workers then block on the full queue, halting
            production while the straggler decode finishes (workers insert
            into ``ready`` BEFORE their token push, so the straggler's
            batch still lands).  While over the bound the consumer parks on
            the condition (woken by the next insert/error) instead of
            spinning a sleep loop."""
            while True:
                with ready_cond:
                    if expected_step in ready:
                        batch = ready.pop(expected_step)
                        self._m_ready.set(len(ready))
                        return batch
                    if errors:
                        raise errors[0]
                    if len(ready) >= bound:
                        ready_cond.wait(timeout=0.2)
                        continue
                item = queue.pop(timeout=0.2)
                if item is None:
                    continue                        # wait out slow decodes
                if int.from_bytes(item[0], "big") == _ERROR_TOKEN:
                    with ready_cond:
                        err = errors[0] if errors else None
                    raise err if err is not None else \
                        RuntimeError("worker aborted")

        try:
            pending = None
            for step in range(steps):
                batch = take(step)
                if place:
                    batch = shard_batch(batch, mesh)
                if pending is not None:
                    yield pending                   # batch N computes while
                pending = batch                     # N+1 already on device
            if pending is not None:
                yield pending
        finally:
            queue.close()
            for t in workers:
                try:
                    t.join(timeout=5)
                except TypeError:
                    # generator finalized during interpreter teardown:
                    # threading internals are already torn down
                    pass
