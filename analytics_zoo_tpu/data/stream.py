"""StreamingDataFeed: bounded-memory input pipeline over the native queue.

Reference (SURVEY.md §2.2): FeatureSet cached the training set in DRAM/PMEM
native arrays and fed per-worker mini-batches; the PMEM path existed
precisely because datasets outgrow RAM.  DataFeed (feed.py) is the
whole-dataset-in-RAM analog — fine for MNIST, disqualifying for ImageNet.

This feed never materializes the dataset: decode workers pull sample
indices, run the user loader (decode + augment for images), and assemble
batches.  The bounded C++ MPMC queue (native/zoo_native.cpp) is the
synchronization/backpressure primitive between decoders and the consumer:
producers push an 8-byte batch token (blocking when the bound is hit — that
bound IS the memory bound), while the batch arrays themselves stay
in-process in a token-keyed dict, so no payload bytes are copied.  The
consumer reorders tokens so batches always arrive in STEP ORDER regardless
of worker timing (predict depends on row order; training gets reproducible
batch sequences), and double-buffers device placement so the host→HBM copy
of batch N+1 overlaps compute of batch N.

Two decode backends (``workers=``):

- ``"thread"`` (default): worker THREADS — zero setup cost, fine when the
  loader releases the GIL (PIL decode, file I/O), and the
  bisection-safe path: its batch sequences are byte-identical to the
  pre-backend code.
- ``"process"``: worker PROCESSES writing rows **directly into a pool of
  preallocated ``multiprocessing.shared_memory`` batch buffers**
  (data/shm_pool.py).  A GIL-bound decode (numpy augment chains, JPEG
  headers, tensor packing) serializes threads at ~1 core; processes scale
  it across the host.  Zero-copy assembly: no per-row pickle, no
  per-batch ``np.stack`` — each row is decoded into its batch's final
  position in shared pages, and only a few-int control message crosses
  the process boundary per batch.  Workers are FORKED so the loader
  closure never needs to be picklable; slot acquisition happens in step
  order (under the step-claim lock), which makes the pool bound
  deadlock-free by construction.  Falls back to ``"thread"`` (with a
  warning) where ``shared_memory``/fork are unavailable.

The backpressure/step-ordering contracts are shared: the native queue
still carries 8-byte step tokens — under the process backend each token
names a batch that lives in a shm slot — and the consumer logic is
literally the same function.

Loader resilience: at ImageNet scale a corrupt JPEG or a flaky filesystem
read is routine, and a single exception must not cost an epoch.  Each
sample read gets ``retries`` bounded retries; after that,
``on_error="skip"`` substitutes a neighboring sample and counts the loss
(``skipped_rows``/``load_failures`` make the degradation visible, and
``max_skipped`` bounds it), while the default ``on_error="raise"``
propagates the failure to the consumer.  The ``feed.read_fail`` injection
point (core/faults.py) makes both paths deterministically testable; forked
workers inherit the armed registry and their hit/fire counts are absorbed
back into the parent registry at epoch end.

Same interface as DataFeed (both subclass feed.FeedBase), so Estimator.fit
takes either interchangeably.
"""

from __future__ import annotations

import logging
import queue as pyqueue
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.core import trace as trace_lib
from analytics_zoo_tpu.core.context import config_default
from analytics_zoo_tpu.native import NativeQueue
from . import shm_pool
from .feed import FeedBase, shard_batch
from .shm_pool import ShmBatchPool, SlotBatch

logger = logging.getLogger("analytics_zoo_tpu")

_ERROR_TOKEN = (1 << 63) - 1

#: How many alternative indices a skipped sample may be substituted with
#: before the failure is treated as systemic and re-raised.
_MAX_FALLBACK_TRIES = 8

#: Valid ``workers=`` backends.
FEED_BACKENDS = ("thread", "process")


def detach_for_placement(batch: Dict[str, np.ndarray]
                         ) -> Dict[str, np.ndarray]:
    """Make a pool-slot batch safe to hand to ``device_put``.

    On real accelerators the host→HBM transfer copies, so once
    ``block_until_ready`` returns the slot can be recycled.  XLA:CPU,
    however, ZERO-COPIES aligned host buffers — the "device" array
    aliases the shm slot, and recycling (or unlinking) the slot would
    corrupt or segfault every batch already "placed".  On the CPU
    backend we therefore detach with one host memcpy first; elsewhere
    this is a passthrough."""
    if jax.default_backend() == "cpu":
        return {k: np.array(v) for k, v in batch.items()}
    return batch


def make_placer(mesh: "Mesh"):
    """``shard_batch`` wrapped with the pool-slot detach rule — the
    ``place=`` callable for ``PrefetchIterator`` when iterating a feed's
    host-batch epoch (``epoch(place=False)``)."""
    def place(batch):
        if isinstance(batch, SlotBatch):
            batch = detach_for_placement(batch)
        return shard_batch(batch, mesh)
    return place


class StreamingDataFeed(FeedBase):
    """Index-based streaming loader: ``load_sample(i, rng)`` → sample dict.

    ``retries``: per-sample reload attempts after a loader exception
    (0 = fail on first exception).  ``on_error``: what to do once retries
    are exhausted — ``"raise"`` (default) aborts the epoch with the
    loader's exception; ``"skip"`` substitutes the next loadable sample
    index and increments ``skipped_rows``.  ``max_skipped`` (with
    ``"skip"``) bounds silent degradation: exceeding it raises.

    ``workers``: decode backend — ``"thread"`` (default; also the
    ``ZooConfig.feed_backend`` default) or ``"process"`` (shared-memory
    slot pool, see module docstring).  ``num_workers`` defaults to
    ``ZooConfig.feed_workers`` (else 4)."""

    def __init__(self, num_samples: int,
                 load_sample: Callable[..., Dict[str, np.ndarray]],
                 batch_size: int, shuffle: bool = True, seed: int = 0,
                 num_workers: Optional[int] = None,
                 prefetch_batches: int = 4,
                 drop_remainder: bool = True,
                 retries: int = 0, on_error: str = "raise",
                 max_skipped: Optional[int] = None,
                 workers: Optional[str] = None):
        super().__init__(num_samples, batch_size, shuffle, seed,
                         drop_remainder)
        if on_error not in ("raise", "skip"):
            raise ValueError(f"on_error must be 'raise' or 'skip', "
                             f"got {on_error!r}")
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if num_workers is None:
            cfg_workers = config_default("feed_workers", None)
            num_workers = 4 if cfg_workers is None else cfg_workers
        if workers is None:
            workers = config_default("feed_backend", "thread")
        if workers not in FEED_BACKENDS:
            raise ValueError(f"workers must be one of {FEED_BACKENDS}, "
                             f"got {workers!r}")
        if workers == "process" and not shm_pool.available():
            logger.warning(
                "workers='process' needs multiprocessing.shared_memory and "
                "the fork start method; falling back to workers='thread'")
            workers = "thread"
        self.workers = workers
        self._load = load_sample
        self.num_workers = max(1, num_workers)
        self.prefetch_batches = max(1, prefetch_batches)
        self.retries = retries
        self.on_error = on_error
        self.max_skipped = max_skipped
        self._counter_lock = threading.Lock()
        self.skipped_rows = 0    # rows substituted because their sample
        #                          never loaded (on_error="skip")
        self.load_failures = 0   # loader exceptions seen (incl. retried)
        self._spec = None        # probed {key: (row_shape, dtype)}
        # optional loader protocols (duck-typed off the bound method's
        # owner, e.g. ImageSet): ``hint_indices(list)`` lets a readahead
        # reader start fetching a batch's files before decode asks for
        # them; ``feed_stats() -> {"io_wait_ms": ...}`` exposes the
        # calling worker's cumulative blocked-on-storage time
        owner = getattr(load_sample, "__self__", None)
        self._hint_fn = getattr(owner, "hint_indices", None)
        self._stats_fn = getattr(owner, "feed_stats", None)
        # telemetry (core/metrics.py): per-sample load latency + the
        # resilience counters mirrored process-wide, so "is the input
        # pipeline degrading?" is answerable without holding the feed
        reg = metrics_lib.get_registry()
        self._m_load = reg.histogram("feed.load_ms")
        self._m_failures = reg.counter("feed.load_failures")
        self._m_retries = reg.counter("feed.retries")
        self._m_skipped = reg.counter("feed.skipped_rows")
        # decoded-batch lookahead occupancy (high-water mark = realized
        # prefetch depth): a gauge pinned at 0 means the consumer eats
        # batches as fast as the workers decode them — the feed, not the
        # device, is the bottleneck
        self._m_ready = reg.gauge("feed.ready_depth")
        # per-stage breakdown of the input pipeline (bench.py
        # input_pipeline reads these): whole-batch decode wall, the part
        # of it spent blocked on storage, shm-slot occupancy, and the
        # host→device copy time not hidden by the pipeline
        self._m_decode = reg.histogram("feed.decode_ms")
        self._m_io = reg.histogram("feed.io_wait_ms")
        self._m_shm = reg.gauge("feed.shm_in_use")
        self._m_h2d = reg.histogram("feed.h2d_ms")
        # span tree (core/trace.py): one trace id per epoch; per-batch
        # decode spans hang under the epoch root — the thread backend
        # records them in the worker, the process backend forwards the
        # timings over the existing control-message channel and the
        # parent records them (children can't reach the parent's ring)
        self.trace_id: Optional[str] = None
        self._epoch_sid: Optional[str] = None

    def _begin_epoch_trace(self, epoch_idx: int) -> None:
        if trace_lib.enabled:
            self.trace_id = trace_lib.new_trace_id()
            self._epoch_sid = trace_lib.new_span_id()
        else:
            self.trace_id = self._epoch_sid = None

    def _record_decode_span(self, step: int, decode_ms: float,
                            io_ms: float) -> None:
        if self.trace_id is not None:
            trace_lib.record(
                self.trace_id, "feed.decode",
                {"step": step, "decode_ms": round(decode_ms, 3),
                 "io_wait_ms": round(io_ms, 3)},
                parent=self._epoch_sid, dur_ms=decode_ms)

    def _end_epoch_trace(self, epoch_idx: int, steps: int,
                         t0: float) -> None:
        if self.trace_id is not None:
            trace_lib.record(
                self.trace_id, "feed.epoch",
                {"epoch": epoch_idx, "steps": steps,
                 "backend": self.workers},
                span_id=self._epoch_sid,
                dur_ms=(time.monotonic() - t0) * 1000.0)

    # -- resilient sample loading --------------------------------------------

    def _fault_registry(self):
        from analytics_zoo_tpu.core import faults
        return faults.get_registry()

    # Counter updates are routed through these three so the process
    # backend's forked workers can re-bind them to fork-shared values
    # (plain ints on a forked copy of ``self`` would be invisible to the
    # parent and to sibling workers — max_skipped must bound the GLOBAL
    # skip count, exactly like the thread backend's shared lock does).

    def _note_failure(self) -> None:
        with self._counter_lock:
            self.load_failures += 1
        self._m_failures.inc()

    def _note_retry(self) -> None:
        self._m_retries.inc()

    def _note_skip(self) -> int:
        with self._counter_lock:
            self.skipped_rows += 1
            skipped = self.skipped_rows
        self._m_skipped.inc()
        return skipped

    def _hint_rows(self, sel: Sequence[int]) -> None:
        """Advisory: tell a readahead-capable loader which rows decode
        next, so file reads overlap the current batch's decode."""
        if self._hint_fn is None:
            return
        try:
            self._hint_fn([int(i) for i in sel])
        except Exception:  # noqa: BLE001 — readahead is best-effort
            logger.debug("readahead hint failed", exc_info=True)

    def _io_wait_ms(self) -> float:
        """The calling worker's cumulative blocked-on-storage ms, 0.0 for
        loaders without the ``feed_stats`` protocol."""
        if self._stats_fn is None:
            return 0.0
        try:
            return float(self._stats_fn().get("io_wait_ms", 0.0))
        except Exception:  # noqa: BLE001 — stats are best-effort
            return 0.0

    def _load_with_retry(self, i: int, rng,
                         inject: bool = True) -> Dict[str, np.ndarray]:
        """One sample through the loader with ``retries`` bounded retries.
        The ``feed.read_fail`` injection point sits INSIDE the attempt so
        an armed fault exercises the same except-clause a real corrupt
        read would — and is retried the same way.  ``inject=False`` for
        fallback substitution loads, so a fault armed against the primary
        sample cannot cascade into every substitute."""
        last: Optional[BaseException] = None
        for _attempt in range(self.retries + 1):
            try:
                if _attempt:
                    self._note_retry()
                if inject:
                    self._fault_registry().raise_if("feed.read_fail",
                                                    OSError)
                t0 = time.monotonic()
                out = self._load(i, rng=rng)
                self._m_load.observe((time.monotonic() - t0) * 1000.0)
                return out
            except Exception as e:  # noqa: BLE001 — loader bugs vary freely
                last = e
                self._note_failure()
        assert last is not None
        raise last

    def _load_row(self, i: int, rng) -> Dict[str, np.ndarray]:
        """Sample ``i`` with retry + optional skip-and-substitute."""
        try:
            return self._load_with_retry(i, rng)
        except Exception:
            if self.on_error != "skip":
                raise
            skipped = self._note_skip()
            if self.max_skipped is not None and skipped > self.max_skipped:
                raise RuntimeError(
                    f"streaming feed skipped {skipped} rows "
                    f"(max_skipped={self.max_skipped}): loader failures "
                    "are no longer a tolerable minority") from None
            # substitute neighboring samples (no injection hits, plain
            # retries only) so the batch keeps its static shape
            for k in range(1, _MAX_FALLBACK_TRIES + 1):
                alt = (i + k) % self._n
                try:
                    return self._load_with_retry(alt, rng, inject=False)
                except Exception:
                    continue
            raise RuntimeError(
                f"sample {i} and {_MAX_FALLBACK_TRIES} fallback samples all "
                "failed to load: the failure is systemic, not per-sample")

    # -- tail coverage --------------------------------------------------------

    def _load_tail(self, sel: List[int]) -> Dict[str, np.ndarray]:
        """Tail rows (remainder / dropped_rows) through the worker pool.
        A serial reload of an ImageNet-sized tail used to stall the epoch
        boundary on the caller thread; now up to ``num_workers`` threads
        load concurrently.  Determinism: single-worker feeds keep the
        historical sequential rng stream; parallel loads give each row
        its own ``(seed, i)``-derived rng so the result is independent of
        completion order."""
        self._hint_rows(sel)
        if self.num_workers <= 1 or len(sel) <= 1:
            rng = np.random.default_rng(self.seed)
            rows = [self._load_row(int(i), rng) for i in sel]
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(
                    max_workers=min(self.num_workers, len(sel)),
                    thread_name_prefix="zoo-feed-tail") as ex:
                rows = list(ex.map(
                    lambda i: self._load_row(
                        int(i), np.random.default_rng((self.seed, int(i)))),
                    sel))
        return {k: np.stack([row[k] for row in rows]) for k in rows[0]}

    def remainder(self) -> Optional[Dict[str, np.ndarray]]:
        r = self._n % self._local_batch
        if r == 0:
            return None
        return self._load_tail(list(range(self._n - r, self._n)))

    def dropped_rows(self, epoch_idx: int = 0):
        """Exact drop_remainder coverage even when shuffled: reload the
        tail of this epoch's permutation through the sample loader."""
        r = self._n % self._local_batch
        if r == 0:
            return None
        sel = self._epoch_index(epoch_idx)[self._n - r:]
        return self._load_tail([int(i) for i in sel])

    # -- epoch iteration ------------------------------------------------------

    def epoch(self, mesh: Mesh, epoch_idx: int = 0, place: bool = True
              ) -> Iterator[Dict[str, "np.ndarray"]]:
        """``place=False`` yields host numpy batches (no device placement):
        the consumer owns staging, e.g. to stack K batches into one
        infeed-chunk transfer for ``Estimator._multi_step_data``.  Under
        the process backend an unplaced batch is a ``SlotBatch`` of
        zero-copy views over its shm slot — copy (``np.stack`` /
        ``np.asarray``) or call ``.release()`` before asking for more
        batches than the pool holds (GC releases as a safety net)."""
        if self.workers == "process":
            return self._epoch_process(mesh, epoch_idx, place)
        return self._epoch_thread(mesh, epoch_idx, place)

    def _consume(self, queue: NativeQueue, ready: Dict, ready_cond,
                 errors: List[BaseException], bound: int, steps: int,
                 mesh: Mesh, place: bool):
        """The shared consumer half of both backends: in-step-order token
        draining, double-buffered placement, and (for shm batches) slot
        recycling one step behind the yield so the device copy of batch N
        completes — overlapped with the placement of N+1 — before its
        host buffer is reused."""
        m_ready = self._m_ready

        def take(expected_step: int) -> Dict[str, np.ndarray]:
            """Next batch in step order; holds out-of-order arrivals.  Live
            because steps are claimed in order: the token for
            ``expected_step`` is pushed or being produced.  Bounded because
            once ``ready`` holds ``bound`` batches the consumer stops
            draining tokens — producers then block on the full queue (or
            the empty slot pool), halting production while the straggler
            decode finishes (batches land in ``ready`` BEFORE their token
            push, so the straggler's batch still arrives).  All waits are
            EVENT-DRIVEN: the condition wakes on inserts/errors and the
            native queue's pop blocks until a token or close — an idle
            consumer costs zero wakeups, not 5/s of polling."""
            while True:
                with ready_cond:
                    if expected_step in ready:
                        batch = ready.pop(expected_step)
                        m_ready.set(len(ready))
                        return batch
                    if errors:
                        raise errors[0]
                    if len(ready) >= bound:
                        ready_cond.wait()
                        continue
                item = queue.pop(timeout=None)
                if item is None:
                    continue                    # spurious empty wakeup
                if int.from_bytes(item[0], "big") == _ERROR_TOKEN:
                    with ready_cond:
                        err = errors[0] if errors else None
                    raise err if err is not None else \
                        RuntimeError("worker aborted")

        def finish(item):
            out, slot, disp_ms = item
            if slot is not None:
                # the copy of this batch was dispatched one iteration ago
                # and overlapped the next batch's staging; the residual
                # wait here is the UNHIDDEN host→device time
                t0 = time.monotonic()
                jax.block_until_ready(out)
                self._m_h2d.observe(
                    disp_ms + (time.monotonic() - t0) * 1000.0)
                slot.release()
                self._m_shm.set(self._pool_in_use())
            elif disp_ms is not None:
                # thread backend: no slot to recycle, so no forced sync —
                # observe the dispatch half so per-backend h2d numbers
                # (bench input_pipeline) stay comparable
                self._m_h2d.observe(disp_ms)
            return out

        pending = None
        for step in range(steps):
            batch = take(step)
            if place:
                slot = batch if isinstance(batch, SlotBatch) else None
                t0 = time.monotonic()
                out = shard_batch(detach_for_placement(batch)
                                  if slot is not None else batch, mesh)
                item = (out, slot, (time.monotonic() - t0) * 1000.0)
            else:
                item = (batch, None, None)      # consumer owns the slot
            if pending is not None:
                yield finish(pending)           # batch N computes while
            pending = item                      # N+1 already on device
        if pending is not None:
            yield finish(pending)

    def _pool_in_use(self) -> int:
        pool = getattr(self, "_active_pool", None)
        return pool.in_use() if pool is not None else 0

    # -- thread backend -------------------------------------------------------

    def _epoch_thread(self, mesh: Mesh, epoch_idx: int, place: bool
                      ) -> Iterator[Dict[str, "np.ndarray"]]:
        idx = self._epoch_index(epoch_idx)
        steps = self.steps_per_epoch()
        self._begin_epoch_trace(epoch_idx)
        epoch_t0 = time.monotonic()

        # the bounded native queue carries batch tokens; ready holds the
        # actual arrays (at most prefetch_batches + num_workers entries,
        # because push blocks when the queue is full)
        queue = NativeQueue(max_items=self.prefetch_batches)
        ready: Dict[int, Dict[str, np.ndarray]] = {}
        ready_lock = threading.Lock()
        # one condition guards BOTH ready and errors: workers notify when
        # either changes, so the consumer never busy-waits
        ready_cond = threading.Condition(ready_lock)
        step_iter = iter(range(steps))
        step_lock = threading.Lock()
        errors: List[BaseException] = []

        def worker(wid: int) -> None:
            rng = np.random.default_rng(
                (self.seed + epoch_idx) * 10007 + wid)
            while True:
                with step_lock:
                    step = next(step_iter, None)
                if step is None:
                    return
                sel = self._batch_index(idx, step)
                try:
                    self._hint_rows(sel)
                    t0 = time.monotonic()
                    io0 = self._io_wait_ms()
                    rows = [self._load_row(int(i), rng) for i in sel]
                    batch = {k: np.stack([r[k] for r in rows])
                             for k in rows[0]}
                    decode_ms = (time.monotonic() - t0) * 1000.0
                    self._m_decode.observe(decode_ms)
                    io_ms = self._io_wait_ms() - io0
                    if io_ms > 0:
                        self._m_io.observe(io_ms)
                    self._record_decode_span(step, decode_ms,
                                             max(0.0, io_ms))
                except BaseException as e:          # noqa: BLE001 loader bug
                    with ready_cond:
                        errors.append(e)
                        ready_cond.notify_all()
                    try:
                        queue.push(_ERROR_TOKEN.to_bytes(8, "big"))
                    except RuntimeError:
                        pass                        # consumer already gone
                    return
                with ready_cond:
                    ready[step] = batch
                    self._m_ready.set(len(ready))
                    ready_cond.notify_all()
                try:
                    queue.push(step.to_bytes(8, "big"))  # blocks when full
                except RuntimeError:                # queue closed: abandon
                    return

        workers = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in workers:
            t.start()

        bound = self.prefetch_batches + self.num_workers

        try:
            yield from self._consume(queue, ready, ready_cond, errors,
                                     bound, steps, mesh, place)
        finally:
            queue.close()
            for t in workers:
                try:
                    t.join(timeout=5)
                except TypeError:
                    # generator finalized during interpreter teardown:
                    # threading internals are already torn down
                    pass
            self._end_epoch_trace(epoch_idx, steps, epoch_t0)

    # -- process backend ------------------------------------------------------

    def _batch_spec(self, idx: np.ndarray) -> Dict[str, tuple]:
        """``{key: (row_shape, dtype)}`` for shm slot sizing, probed from
        ONE sample loaded on the caller (plain load: no injection hits,
        no counter effects) and cached across epochs."""
        if self._spec is not None:
            return self._spec
        last: Optional[BaseException] = None
        row = None
        for k in range(min(len(idx), _MAX_FALLBACK_TRIES)):
            try:
                row = self._load(int(idx[k]),
                                 rng=np.random.default_rng(self.seed))
                break
            except Exception as e:  # noqa: BLE001 — probe the next sample
                last = e
        if row is None:
            raise RuntimeError(
                "could not load any sample to probe the batch spec for "
                "the shared-memory pool") from last
        self._spec = {key: (np.asarray(v).shape, np.asarray(v).dtype)
                      for key, v in row.items()}
        return self._spec

    def _epoch_process(self, mesh: Mesh, epoch_idx: int, place: bool
                       ) -> Iterator[Dict[str, "np.ndarray"]]:
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        idx = self._epoch_index(epoch_idx)
        steps = self.steps_per_epoch()
        self._begin_epoch_trace(epoch_idx)
        epoch_t0 = time.monotonic()
        spec = self._batch_spec(idx)
        nslots = max(2, self.prefetch_batches + self.num_workers)
        pool = ShmBatchPool(nslots, self._local_batch, spec, ctx=ctx)
        self._active_pool = pool
        queue = NativeQueue(max_items=self.prefetch_batches)
        ready: Dict[int, Dict[str, np.ndarray]] = {}
        ready_cond = threading.Condition(threading.Lock())
        errors: List[BaseException] = []
        sh = _ProcShared(ctx, self)
        fail0, skip0 = self.load_failures, self.skipped_rows
        stop = threading.Event()
        procs = [ctx.Process(target=_process_worker,
                             args=(self, idx, epoch_idx, steps, pool, wid,
                                   sh),
                             daemon=True, name=f"zoo-feed-w{wid}")
                 for wid in range(self.num_workers)]
        import warnings
        with warnings.catch_warnings():
            # jax warns on every os.fork(); the children never touch jax
            # (numpy decode only — the PyTorch-DataLoader contract), so
            # the warning is noise here
            warnings.filterwarnings("ignore", message=".*os.fork.*",
                                    category=RuntimeWarning)
            for p in procs:
                p.start()

        def forward() -> None:
            """Parent-side forwarder: turns worker control messages into
            ready-dict inserts + native-queue tokens (the consumer
            contract the thread backend already speaks), releases the
            slots of crashed workers, and converts a hard worker death
            into the same error path a loader exception takes."""
            done = [False] * self.num_workers
            n_done = 0
            while not stop.is_set() and n_done < self.num_workers:
                try:
                    msg = sh.result_q.get(timeout=0.5)
                except pyqueue.Empty:
                    for wid, p in enumerate(procs):
                        if done[wid] or p.exitcode is None:
                            continue
                        done[wid] = True
                        n_done += 1
                        if sh.finished[wid].value:
                            continue        # clean exit, message raced
                        slot = sh.held[wid].value
                        if slot >= 0:       # crash mid-write: reclaim
                            pool.release(slot)
                            sh.held[wid].value = -1
                        err = RuntimeError(
                            f"streaming decode worker {wid} died (exit "
                            f"code {p.exitcode}) mid-batch")
                        with ready_cond:
                            errors.append(err)
                            ready_cond.notify_all()
                        try:
                            queue.push(_ERROR_TOKEN.to_bytes(8, "big"))
                        except RuntimeError:
                            return
                    continue
                kind = msg[0]
                if kind == "batch":
                    _, step, slot, decode_ms, io_ms, load_ms = msg
                    self._m_decode.observe(decode_ms)
                    self._m_load.observe(load_ms)  # per-sample batch mean
                    if io_ms > 0:
                        self._m_io.observe(io_ms)
                    # forked workers can't reach this process's span
                    # ring — the decode timing rode the control message,
                    # so the span is recorded HERE, under the epoch root
                    self._record_decode_span(step, decode_ms,
                                             max(0.0, io_ms))
                    batch = SlotBatch(pool.views(slot), slot, pool)
                    with ready_cond:
                        ready[step] = batch
                        self._m_ready.set(len(ready))
                        ready_cond.notify_all()
                    self._m_shm.set(pool.in_use())
                    try:
                        queue.push(step.to_bytes(8, "big"))
                    except RuntimeError:
                        return              # consumer closed: abandon
                elif kind == "error":
                    _, wid, slot, exc = msg
                    if slot >= 0:
                        pool.release(slot)
                    with ready_cond:
                        errors.append(exc)
                        ready_cond.notify_all()
                    try:
                        queue.push(_ERROR_TOKEN.to_bytes(8, "big"))
                    except RuntimeError:
                        return
                elif kind == "done":
                    wid = msg[1]
                    if not done[wid]:
                        done[wid] = True
                        n_done += 1

        fwd = threading.Thread(target=forward, daemon=True,
                               name="zoo-feed-forwarder")
        fwd.start()

        try:
            yield from self._consume(queue, ready, ready_cond, errors,
                                     nslots, steps, mesh, place)
        finally:
            stop.set()
            queue.close()
            for p in procs:
                if p.is_alive():
                    p.terminate()           # may be blocked on the pool
            for p in procs:
                try:
                    p.join(timeout=5)
                except (AssertionError, ValueError):
                    pass
            try:
                fwd.join(timeout=5)
            except (RuntimeError, TypeError):
                pass
            # fold the workers' fork-shared counters back into the feed,
            # its metrics, and the fault registry (times charges consumed
            # in children must disarm the parent's spec too)
            self.load_failures = max(self.load_failures, sh.failures.value)
            self.skipped_rows = max(self.skipped_rows, sh.skipped.value)
            if self.load_failures > fail0:
                self._m_failures.inc(self.load_failures - fail0)
            if self.skipped_rows > skip0:
                self._m_skipped.inc(self.skipped_rows - skip0)
            if sh.retries_v.value:
                self._m_retries.inc(sh.retries_v.value)
            if sh.fault_hits.value or sh.fault_fired.value:
                self._fault_registry().absorb(
                    "feed.read_fail", hits=sh.fault_hits.value,
                    fired=sh.fault_fired.value)
            try:
                sh.result_q.close()
                sh.result_q.cancel_join_thread()
            except (OSError, AttributeError):
                pass
            self._active_pool = None
            pool.close()
            self._m_shm.set(0)
            self._end_epoch_trace(epoch_idx, steps, epoch_t0)


class _ProcShared:
    """Fork-shared control state for one process-backend epoch: the step
    claim counter, resilience counters, per-worker held-slot markers
    (crash recovery), clean-exit flags, and the control-message queue."""

    def __init__(self, ctx, feed: StreamingDataFeed):
        self.step = ctx.Value("l", 0)
        self.failures = ctx.Value("l", feed.load_failures)
        self.retries_v = ctx.Value("l", 0)
        self.skipped = ctx.Value("l", feed.skipped_rows)
        self.fault_hits = ctx.Value("l", 0)
        self.fault_fired = ctx.Value("l", 0)
        self.held = [ctx.Value("l", -1) for _ in range(feed.num_workers)]
        self.finished = [ctx.Value("b", 0) for _ in range(feed.num_workers)]
        self.result_q = ctx.Queue()


class _ChildFaultView:
    """A forked worker's view of the fault registry: decisions run
    against the inherited (copy-on-write) armed specs — deterministic per
    worker — while hit/fire counts mirror into fork-shared values so the
    PARENT registry can absorb them at epoch end (``fired()`` visible to
    tests, ``times`` charges consumed, armed-leak checks coherent)."""

    def __init__(self, real, hits, fired):
        self._real = real
        self._hits = hits
        self._fired = fired

    def raise_if(self, name: str,
                 default_exc=RuntimeError) -> None:
        h0, f0 = self._real.hits(name), self._real.fired(name)
        try:
            self._real.raise_if(name, default_exc)
        finally:
            dh = self._real.hits(name) - h0
            df = self._real.fired(name) - f0
            if dh:
                with self._hits.get_lock():
                    self._hits.value += dh
            if df:
                with self._fired.get_lock():
                    self._fired.value += df


def _vinc(v) -> int:
    with v.get_lock():
        v.value += 1
        return v.value


def _picklable_exc(e: BaseException) -> BaseException:
    import pickle
    try:
        pickle.dumps(e)
        return e
    except Exception:  # noqa: BLE001 — unpicklable user exception
        return RuntimeError(f"{type(e).__name__}: {e}")


def _process_worker(feed: StreamingDataFeed, idx: np.ndarray,
                    epoch_idx: int, steps: int, pool: ShmBatchPool,
                    wid: int, sh: _ProcShared) -> None:
    """Forked decode worker main loop.

    Runs in a CHILD process: ``feed`` is a copy-on-write copy, so its
    counter/fault plumbing is re-bound to the fork-shared values first.
    Step claim and slot acquisition happen under ONE lock so slots are
    acquired in step order — with claim order == step order this makes
    the pool bound deadlock-free (the lowest outstanding step always
    holds or gets the next free slot; later steps cannot starve it)."""
    try:
        real = feed._fault_registry()
        child_faults = _ChildFaultView(real, sh.fault_hits, sh.fault_fired)
        feed._fault_registry = lambda: child_faults
        feed._note_failure = lambda: _vinc(sh.failures) and None
        feed._note_retry = lambda: _vinc(sh.retries_v) and None
        feed._note_skip = lambda: _vinc(sh.skipped)
        # the child's metrics registry is invisible to the parent — the
        # parent observes decode/io from control messages instead
        metrics_lib.get_registry().enabled = False
        rng = np.random.default_rng((feed.seed + epoch_idx) * 10007 + wid)
        while True:
            with sh.step.get_lock():
                step = sh.step.value
                if step >= steps:
                    break
                slot = pool.acquire()       # in step order — see docstring
                sh.step.value = step + 1
                sh.held[wid].value = slot
            if slot is None:
                break                       # pool closing under us
            sel = feed._batch_index(idx, step)
            feed._hint_rows(sel)
            t0 = time.monotonic()
            io0 = feed._io_wait_ms()
            load_s = 0.0
            views = pool.views(slot)
            for k, i in enumerate(sel):
                t1 = time.monotonic()
                row = feed._load_row(int(i), rng)
                load_s += time.monotonic() - t1
                if set(row) != set(views):
                    raise ValueError(
                        f"load_sample keys {sorted(row)} do not match the "
                        f"probed batch spec {sorted(views)}")
                for key, v in row.items():
                    views[key][k] = v       # decoded straight into place
            decode_ms = (time.monotonic() - t0) * 1000.0
            io_ms = feed._io_wait_ms() - io0
            # the child's metrics registry is invisible to the parent —
            # per-sample loader latency rides the control message instead
            load_ms = load_s * 1000.0 / max(1, len(sel))
            # drop the held marker BEFORE reporting: once the message is
            # out, the batch owns the slot — a hard death in between must
            # not let the crash path reclaim a slot the consumer now holds
            sh.held[wid].value = -1
            sh.result_q.put(("batch", step, slot, decode_ms, io_ms,
                             load_ms))
    except BaseException as e:  # noqa: BLE001 — loader bugs vary freely
        try:
            sh.result_q.put(("error", wid, int(sh.held[wid].value),
                             _picklable_exc(e)))
            sh.held[wid].value = -1
        except Exception:       # parent already tearing down
            pass
    finally:
        try:
            sh.finished[wid].value = 1
            sh.result_q.put(("done", wid))
        except Exception:
            pass
