"""StreamingDataFeed: bounded-memory input pipeline over the native queue.

Reference (SURVEY.md §2.2): FeatureSet cached the training set in DRAM/PMEM
native arrays and fed per-worker mini-batches; the PMEM path existed
precisely because datasets outgrow RAM.  DataFeed (feed.py) is the
whole-dataset-in-RAM analog — fine for MNIST, disqualifying for ImageNet.

This feed never materializes the dataset: worker threads pull sample
indices, run the user loader (decode + augment for images), and stack
batches.  The bounded C++ MPMC queue (native/zoo_native.cpp) is the
synchronization/backpressure primitive between decoders and the consumer:
workers push an 8-byte batch token (blocking when the bound is hit — that
bound IS the memory bound), while the batch arrays themselves stay
in-process in a token-keyed dict, so no payload bytes are copied.  The
consumer pops tokens, claims batches, and double-buffers device placement
so the host→HBM copy of batch N+1 overlaps compute of batch N.

Same interface as DataFeed (global_batch / steps_per_epoch / remainder /
epoch), so Estimator.fit takes either interchangeably.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterator, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from analytics_zoo_tpu.native import NativeQueue
from .feed import shard_batch

_ERROR_TOKEN = (1 << 63) - 1


class StreamingDataFeed:
    """Index-based streaming loader: ``load_sample(i, rng)`` → sample dict."""

    def __init__(self, num_samples: int,
                 load_sample: Callable[..., Dict[str, np.ndarray]],
                 batch_size: int, shuffle: bool = True, seed: int = 0,
                 num_workers: int = 4, prefetch_batches: int = 4,
                 drop_remainder: bool = True):
        self._n = num_samples
        self._load = load_sample
        self.global_batch = batch_size
        self._local_batch = max(1, batch_size // max(1, jax.process_count()))
        self.shuffle = shuffle
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.prefetch_batches = max(1, prefetch_batches)
        self.drop_remainder = drop_remainder

    # -- DataFeed interface ----------------------------------------------------

    @property
    def num_rows(self) -> int:
        return self._n

    def steps_per_epoch(self) -> int:
        if self.drop_remainder:
            return self._n // self._local_batch
        return -(-self._n // self._local_batch)

    def remainder(self) -> Optional[Dict[str, np.ndarray]]:
        r = self._n % self._local_batch
        if r == 0:
            return None
        rng = np.random.default_rng(self.seed)
        rows = [self._load(i, rng=rng) for i in range(self._n - r, self._n)]
        return {k: np.stack([row[k] for row in rows]) for k in rows[0]}

    def epoch(self, mesh: Mesh, epoch_idx: int = 0
              ) -> Iterator[Dict[str, jax.Array]]:
        steps = self.steps_per_epoch()
        if steps == 0:
            raise ValueError(
                f"dataset of {self._n} rows yields no batches of local "
                f"size {self._local_batch}")
        idx = np.arange(self._n)
        if self.shuffle:
            np.random.default_rng(self.seed + epoch_idx).shuffle(idx)

        # the bounded native queue carries batch tokens; ready holds the
        # actual arrays (at most prefetch_batches + num_workers entries,
        # because push blocks when the queue is full)
        queue = NativeQueue(max_items=self.prefetch_batches)
        ready: Dict[int, Dict[str, np.ndarray]] = {}
        ready_lock = threading.Lock()
        step_iter = iter(range(steps))
        step_lock = threading.Lock()
        errors: List[BaseException] = []

        def worker(wid: int) -> None:
            rng = np.random.default_rng(
                (self.seed + epoch_idx) * 10007 + wid)
            while True:
                with step_lock:
                    step = next(step_iter, None)
                if step is None:
                    return
                sel = idx[step * self._local_batch:
                          (step + 1) * self._local_batch]
                if len(sel) < self._local_batch:   # pad last partial batch
                    sel = np.resize(sel, self._local_batch)
                try:
                    rows = [self._load(int(i), rng=rng) for i in sel]
                    batch = {k: np.stack([r[k] for r in rows])
                             for k in rows[0]}
                except BaseException as e:          # noqa: BLE001 loader bug
                    errors.append(e)
                    try:
                        queue.push(_ERROR_TOKEN.to_bytes(8, "big"))
                    except RuntimeError:
                        pass                        # consumer already gone
                    return
                with ready_lock:
                    ready[step] = batch
                try:
                    queue.push(step.to_bytes(8, "big"))  # blocks when full
                except RuntimeError:                # queue closed: abandon
                    return

        workers = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in workers:
            t.start()

        try:
            pending = None
            for _ in range(steps):
                item = None
                while item is None:                 # wait out slow decodes
                    if errors:
                        raise errors[0]
                    item = queue.pop(timeout=1.0)
                token = int.from_bytes(item[0], "big")
                if token == _ERROR_TOKEN:
                    raise (errors[0] if errors else
                           RuntimeError("worker aborted"))
                with ready_lock:
                    host_batch = ready.pop(token)
                batch = shard_batch(host_batch, mesh)
                if pending is not None:
                    yield pending                   # batch N computes while
                pending = batch                     # N+1 already on device
            if pending is not None:
                yield pending
        finally:
            queue.close()
            for t in workers:
                t.join(timeout=5)
