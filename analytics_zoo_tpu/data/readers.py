"""Distributed file readers producing XShards.

Reference (SURVEY.md §2.2): ``orca.data.pandas.read_csv/read_json``
(pyzoo/zoo/orca/data/pandas/preprocessing.py) read files into SparkXShards
with a backend switch ("spark" | "pandas").

TPU-native: files are globbed, the file list is split across host processes
(process i of N takes files i, i+N, …), and each host reads its files into
local shards in parallel.  This matches how per-host input pipelines feed TPU
infeed — no driver hop, no shuffle.
"""

from __future__ import annotations

import collections
import glob
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

import jax

from .shards import XShards


class FileReadahead:
    """Per-worker raw-file readahead: a background thread reads hinted
    files' bytes into a bounded cache so storage latency overlaps decode.

    The streaming feed's decode workers hint each batch's file list
    before decoding it (``StreamingDataFeed`` → ``ImageSet.hint_indices``
    → ``hint()``); while the worker decodes image k, the reader thread is
    already pulling image k+1's bytes off storage.  ``get(path)`` returns
    the cached bytes or — on a miss — reads inline and counts the blocked
    time, so the fraction of decode wall spent waiting on storage is an
    honest, per-worker number (``wait_ms`` is thread-local; the feed
    surfaces deltas as the ``feed.io_wait_ms`` series).

    One instance per worker (thread or forked process): ``ImageSet``
    creates them lazily keyed on pid, so fork inheritance can never share
    a dead reader thread.
    """

    def __init__(self, depth: int = 8):
        if depth < 1:
            raise ValueError(f"readahead depth must be >= 1, got {depth}")
        self.pid = os.getpid()
        self.depth = depth
        self._cond = threading.Condition(threading.Lock())
        self._want: "collections.deque[str]" = collections.deque()
        self._cache: Dict[str, bytes] = {}
        self._reading: Optional[str] = None  # path the reader holds now
        self._drop: set = set()    # in-flight reads the decoder already
        #                            satisfied inline — discard, don't cache
        self._tl = threading.local()  # per-caller-thread wait accounting
        self._closed = False
        self._thread: Optional[threading.Thread] = None

    @property
    def wait_ms(self) -> float:
        """Cumulative blocked-on-storage ms for the CALLING thread."""
        return getattr(self._tl, "ms", 0.0)

    def hint(self, paths: Sequence[str]) -> None:
        """Advise which files are about to be read (drops hints beyond
        the bound — they fall back to inline reads, never to an
        unbounded queue)."""
        with self._cond:
            if self._closed:
                return
            queued = set(self._want)
            for p in paths:
                if p in queued or p in self._cache:
                    continue
                if len(self._want) >= 4 * self.depth:
                    break
                self._want.append(p)
                queued.add(p)
            if self._want and self._thread is None:
                self._thread = threading.Thread(
                    target=self._read_loop, daemon=True,
                    name="zoo-readahead")
                self._thread.start()
            self._cond.notify_all()

    def get(self, path: str) -> bytes:
        """The file's bytes: from cache when the readahead won the race,
        else read inline with the blocked time counted.  A miss RETIRES
        the path from the readahead's queue (and marks an in-flight read
        of it for discard): without that, every lost race left a stale
        never-to-be-requested cache entry behind, and after ``depth`` of
        them the reader parked forever — readahead silently off."""
        with self._cond:
            data = self._cache.pop(path, None)
            if data is not None:
                self._cond.notify_all()  # cache slot freed: reader resumes
                return data
            try:  # we're reading it ourselves: the hint is stale now
                self._want.remove(path)
            except ValueError:
                pass
            if self._reading == path:
                self._drop.add(path)
        t0 = time.monotonic()
        with open(path, "rb") as f:
            data = f.read()
        self._tl.ms = getattr(self._tl, "ms", 0.0) \
            + (time.monotonic() - t0) * 1000.0
        return data

    def _read_loop(self) -> None:
        while True:
            with self._cond:
                self._reading = None
                while not self._closed and (
                        not self._want or len(self._cache) >= self.depth):
                    self._cond.wait()
                if self._closed:
                    return
                path = self._want.popleft()
                if path in self._cache:
                    continue
                self._reading = path
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                continue  # the decode-side read reports the real error
            with self._cond:
                if self._closed:
                    return
                if path in self._drop:   # decoder read it inline meanwhile
                    self._drop.discard(path)
                    continue
                self._cache[path] = data
                self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._want.clear()
            self._cache.clear()
            self._cond.notify_all()


def _expand(file_path: str, extensions: Sequence[str]) -> List[str]:
    # extension matching is case-INSENSITIVE: camera exports and legacy
    # datasets mix ``.CSV``/``.JPG``/``.JPEG`` freely, and a
    # case-sensitive endswith silently dropped them from globbed
    # directories (rows just vanished — no error)
    exts = tuple(e.lower() for e in extensions)
    if os.path.isdir(file_path):
        files = sorted(
            f for f in glob.glob(os.path.join(file_path, "**", "*"),
                                 recursive=True)
            if os.path.isfile(f) and f.lower().endswith(exts))
    else:
        files = sorted(glob.glob(file_path))
    if not files:
        raise FileNotFoundError(f"no files match {file_path!r}")
    return files


def _my_files(files: List[str]) -> tuple:
    """This host's slice of the global file list (SPMD round-robin).

    Returns (my_files, row_slice): when there are fewer files than hosts,
    every host reads the full file list and ``row_slice = (pid, n)`` tells the
    reader to keep only rows ``pid::n`` — so the union over hosts is exactly
    the dataset, no row duplicated."""
    pid, n = jax.process_index(), jax.process_count()
    if len(files) < n:
        return files, (pid, n)
    return files[pid::n], None


def _apply_row_slice(shards: XShards, row_slice) -> XShards:
    if row_slice is None:
        return shards
    pid, n = row_slice
    return shards.transform_shard(
        lambda d: d.iloc[pid::n] if hasattr(d, "iloc")
        else jax.tree_util.tree_map(lambda a: a[pid::n], d))


def read_csv(file_path: str, num_shards: Optional[int] = None,
             **kwargs: Any) -> XShards:
    """Read CSV file(s)/glob/dir into pandas-DataFrame XShards."""
    import pandas as pd
    files, row_slice = _my_files(_expand(file_path, (".csv",)))
    shards = _apply_row_slice(XShards(files).transform_shard(
        lambda f: pd.read_csv(f, **kwargs)), row_slice)
    if num_shards and num_shards != shards.num_partitions():
        shards = shards.repartition(num_shards)
    return shards


def read_json(file_path: str, num_shards: Optional[int] = None,
              **kwargs: Any) -> XShards:
    import pandas as pd
    files, row_slice = _my_files(_expand(file_path, (".json", ".jsonl")))
    shards = _apply_row_slice(XShards(files).transform_shard(
        lambda f: pd.read_json(f, **kwargs)), row_slice)
    if num_shards and num_shards != shards.num_partitions():
        shards = shards.repartition(num_shards)
    return shards


def read_parquet(file_path: str, num_shards: Optional[int] = None,
                 **kwargs: Any) -> XShards:
    import pandas as pd
    files, row_slice = _my_files(_expand(file_path, (".parquet", ".pq")))
    shards = _apply_row_slice(XShards(files).transform_shard(
        lambda f: pd.read_parquet(f, **kwargs)), row_slice)
    if num_shards and num_shards != shards.num_partitions():
        shards = shards.repartition(num_shards)
    return shards


def read_npz(file_path: str, keys: Optional[Sequence[str]] = None) -> XShards:
    """Read .npz archives into numpy-dict shards (one shard per file)."""
    files, row_slice = _my_files(_expand(file_path, (".npz",)))

    def load(f):
        with np.load(f) as z:
            return {k: z[k] for k in (keys or z.files)}
    return _apply_row_slice(XShards(files).transform_shard(load), row_slice)
