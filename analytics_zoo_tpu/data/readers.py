"""Distributed file readers producing XShards.

Reference (SURVEY.md §2.2): ``orca.data.pandas.read_csv/read_json``
(pyzoo/zoo/orca/data/pandas/preprocessing.py) read files into SparkXShards
with a backend switch ("spark" | "pandas").

TPU-native: files are globbed, the file list is split across host processes
(process i of N takes files i, i+N, …), and each host reads its files into
local shards in parallel.  This matches how per-host input pipelines feed TPU
infeed — no driver hop, no shuffle.
"""

from __future__ import annotations

import glob
import os
from typing import Any, List, Optional, Sequence

import numpy as np

import jax

from .shards import XShards


def _expand(file_path: str, extensions: Sequence[str]) -> List[str]:
    if os.path.isdir(file_path):
        files = sorted(
            f for f in glob.glob(os.path.join(file_path, "**", "*"),
                                 recursive=True)
            if os.path.isfile(f) and f.endswith(tuple(extensions)))
    else:
        files = sorted(glob.glob(file_path))
    if not files:
        raise FileNotFoundError(f"no files match {file_path!r}")
    return files


def _my_files(files: List[str]) -> tuple:
    """This host's slice of the global file list (SPMD round-robin).

    Returns (my_files, row_slice): when there are fewer files than hosts,
    every host reads the full file list and ``row_slice = (pid, n)`` tells the
    reader to keep only rows ``pid::n`` — so the union over hosts is exactly
    the dataset, no row duplicated."""
    pid, n = jax.process_index(), jax.process_count()
    if len(files) < n:
        return files, (pid, n)
    return files[pid::n], None


def _apply_row_slice(shards: XShards, row_slice) -> XShards:
    if row_slice is None:
        return shards
    pid, n = row_slice
    return shards.transform_shard(
        lambda d: d.iloc[pid::n] if hasattr(d, "iloc")
        else jax.tree_util.tree_map(lambda a: a[pid::n], d))


def read_csv(file_path: str, num_shards: Optional[int] = None,
             **kwargs: Any) -> XShards:
    """Read CSV file(s)/glob/dir into pandas-DataFrame XShards."""
    import pandas as pd
    files, row_slice = _my_files(_expand(file_path, (".csv",)))
    shards = _apply_row_slice(XShards(files).transform_shard(
        lambda f: pd.read_csv(f, **kwargs)), row_slice)
    if num_shards and num_shards != shards.num_partitions():
        shards = shards.repartition(num_shards)
    return shards


def read_json(file_path: str, num_shards: Optional[int] = None,
              **kwargs: Any) -> XShards:
    import pandas as pd
    files, row_slice = _my_files(_expand(file_path, (".json", ".jsonl")))
    shards = _apply_row_slice(XShards(files).transform_shard(
        lambda f: pd.read_json(f, **kwargs)), row_slice)
    if num_shards and num_shards != shards.num_partitions():
        shards = shards.repartition(num_shards)
    return shards


def read_parquet(file_path: str, num_shards: Optional[int] = None,
                 **kwargs: Any) -> XShards:
    import pandas as pd
    files, row_slice = _my_files(_expand(file_path, (".parquet", ".pq")))
    shards = _apply_row_slice(XShards(files).transform_shard(
        lambda f: pd.read_parquet(f, **kwargs)), row_slice)
    if num_shards and num_shards != shards.num_partitions():
        shards = shards.repartition(num_shards)
    return shards


def read_npz(file_path: str, keys: Optional[Sequence[str]] = None) -> XShards:
    """Read .npz archives into numpy-dict shards (one shard per file)."""
    files, row_slice = _my_files(_expand(file_path, (".npz",)))

    def load(f):
        with np.load(f) as z:
            return {k: z[k] for k in (keys or z.files)}
    return _apply_row_slice(XShards(files).transform_shard(load), row_slice)
