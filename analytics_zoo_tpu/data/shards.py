"""XShards: the sharded-data abstraction, TPU-host-native.

Reference (SURVEY.md §2.2): ``SparkXShards`` (pyzoo/zoo/orca/data/shard.py)
held a Spark RDD whose partitions were lists of Python objects (pandas
DataFrames or numpy dicts) with a map-style API (``transform_shard``,
``partition_by``, ``repartition``, ``split``); ``RayXShards``
(pyzoo/zoo/orca/data/ray_xshards.py) moved those partitions into Ray actors to
feed Ray-based estimators.

TPU-native redesign: there is no driver/executor split — one Python process
per TPU host *is* the data plane.  An ``XShards`` is a list of host-local
shards; in multi-host runs each process holds only its own slice of the
global shard set (SPMD over hosts, matching how batches are then fed to the
ICI-connected chips).  ``transform_shard`` fans out over a thread pool (the
work is pandas/numpy, which releases the GIL for the heavy parts).  The
Spark→Ray object-store copy disappears: shards are already where the
estimator needs them.
"""

from __future__ import annotations

import concurrent.futures as _futures
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


class XShards:
    """A collection of data shards local to this host process.

    API parity with the reference's XShards (pyzoo/zoo/orca/data/shard.py):
    ``transform_shard``, ``collect``, ``num_partitions``, ``repartition``,
    ``partition_by``, ``split``, ``len``; plus numpy-dict helpers used by the
    estimators.
    """

    def __init__(self, shards: Sequence[Any], max_workers: Optional[int] = None):
        self._shards: List[Any] = list(shards)
        self._max_workers = max_workers or min(16, os.cpu_count() or 4)

    # -- construction ---------------------------------------------------------

    @staticmethod
    def partition(data: Any, num_shards: Optional[int] = None) -> "XShards":
        """Partition in-memory data into shards (reference: XShards.partition).

        Accepts a numpy array, a dict of arrays ({"x": ..., "y": ...}), or a
        tuple/list of arrays; splits along axis 0.
        """
        n = num_shards or min(8, os.cpu_count() or 4)

        def split_leaf(a: np.ndarray) -> List[np.ndarray]:
            return np.array_split(a, n)

        if isinstance(data, np.ndarray):
            return XShards(split_leaf(data))
        if isinstance(data, dict):
            parts = {k: _split_nested(v, n) for k, v in data.items()}
            return XShards([{k: parts[k][i] for k in data} for i in range(n)])
        if isinstance(data, (tuple, list)):
            parts = [_split_nested(v, n) for v in data]
            return XShards([type(data)(p[i] for p in parts) for i in range(n)])
        raise TypeError(f"cannot partition data of type {type(data)}")

    # -- core API -------------------------------------------------------------

    def transform_shard(self, fn: Callable, *args: Any) -> "XShards":
        """Apply ``fn(shard, *args)`` to every shard in parallel."""
        if len(self._shards) <= 1:
            return XShards([fn(s, *args) for s in self._shards],
                           self._max_workers)
        with _futures.ThreadPoolExecutor(self._max_workers) as pool:
            out = list(pool.map(lambda s: fn(s, *args), self._shards))
        return XShards(out, self._max_workers)

    def collect(self) -> List[Any]:
        return list(self._shards)

    def num_partitions(self) -> int:
        return len(self._shards)

    def repartition(self, num_partitions: int) -> "XShards":
        """Rebalance shards; supports pandas DataFrames and numpy dicts."""
        shards = self._shards
        if not shards:
            return XShards([])
        first = shards[0]
        try:
            import pandas as pd
            if isinstance(first, pd.DataFrame):
                whole = pd.concat(shards, ignore_index=True)
                return XShards(
                    [df for df in np.array_split(whole, num_partitions)],
                    self._max_workers)
        except ImportError:
            pass
        if isinstance(first, dict):
            whole = {k: _concat_nested([s[k] for s in shards]) for k in first}
            return XShards.partition(whole, num_partitions)
        if isinstance(first, np.ndarray):
            return XShards.partition(_concat_nested(shards), num_partitions)
        # generic python objects: round-robin regroup
        flat: List[Any] = []
        for s in shards:
            flat.extend(s if isinstance(s, list) else [s])
        groups: List[List[Any]] = [[] for _ in range(num_partitions)]
        for i, item in enumerate(flat):
            groups[i % num_partitions].append(item)
        return XShards(groups, self._max_workers)

    def partition_by(self, cols: str, num_partitions: Optional[int] = None
                     ) -> "XShards":
        """Hash-partition pandas shards by a column (reference: partition_by)."""
        import pandas as pd
        whole = pd.concat(self._shards, ignore_index=True)
        n = num_partitions or self.num_partitions() or 1
        codes = pd.util.hash_array(whole[cols].to_numpy()) % n
        return XShards([whole[codes == i] for i in range(n)],
                       self._max_workers)

    def split(self) -> List["XShards"]:
        """If each shard is a tuple/list of k pieces, split into k XShards
        (reference: XShards.split)."""
        first = self._shards[0]
        if not isinstance(first, (tuple, list)):
            raise ValueError("split() requires shards that are tuples/lists")
        k = len(first)
        return [XShards([s[i] for s in self._shards], self._max_workers)
                for i in range(k)]

    def __len__(self) -> int:
        total = 0
        for s in self._shards:
            total += _shard_len(s)
        return total

    def __iter__(self):
        return iter(self._shards)

    # -- numpy-dict helpers (estimator data contract) -------------------------

    def to_numpy_dict(self, feature_cols: Optional[Sequence[str]] = None,
                      label_cols: Optional[Sequence[str]] = None) -> "XShards":
        """pandas shards → {"x": ndarray, "y": ndarray} shards, the contract
        the reference estimators consumed (pyzoo/zoo/orca/data/utils.py)."""
        def conv(df):
            out: Dict[str, Any] = {}
            if feature_cols:
                xs = [df[c].to_numpy() for c in feature_cols]
                out["x"] = np.stack(xs, axis=1) if len(xs) > 1 else xs[0]
            if label_cols:
                ys = [df[c].to_numpy() for c in label_cols]
                out["y"] = np.stack(ys, axis=1) if len(ys) > 1 else ys[0]
            return out
        return self.transform_shard(conv)

    def concatenated(self) -> Any:
        """Materialize all shards into one object (arrays concatenated)."""
        shards = self._shards
        if not shards:
            return None
        first = shards[0]
        if isinstance(first, dict):
            return {k: _concat_nested([s[k] for s in shards]) for k in first}
        if isinstance(first, (tuple, list)):
            k = len(first)
            return type(first)(
                _concat_nested([s[i] for s in shards]) for i in range(k))
        return _concat_nested(shards)


def _split_nested(v: Any, n: int) -> List[Any]:
    if isinstance(v, np.ndarray):
        return np.array_split(v, n)
    if isinstance(v, (tuple, list)):
        parts = [_split_nested(x, n) for x in v]
        return [type(v)(p[i] for p in parts) for i in range(n)]
    raise TypeError(f"cannot split leaf of type {type(v)}")


def _concat_nested(vals: List[Any]) -> Any:
    first = vals[0]
    if isinstance(first, np.ndarray):
        return np.concatenate(vals, axis=0)
    if hasattr(first, "iloc"):  # pandas
        import pandas as pd
        return pd.concat(vals, ignore_index=True)
    if isinstance(first, (tuple, list)):
        k = len(first)
        return type(first)(
            _concat_nested([v[i] for v in vals]) for i in range(k))
    return np.concatenate([np.asarray(v) for v in vals], axis=0)


def _shard_len(s: Any) -> int:
    if isinstance(s, dict):
        return _shard_len(next(iter(s.values())))
    if isinstance(s, (tuple, list)) and s and hasattr(s[0], "__len__"):
        return _shard_len(s[0])
    try:
        return len(s)
    except TypeError:
        return 1
