"""ImageSet: distributed image collection + preprocessing chain.

Reference (SURVEY.md §2.2): Scala ``feature/image/*.scala`` +
``pyzoo/zoo/feature/image/imageset.py`` — ``ImageSet.read`` produced a
Local/DistributedImageSet of OpenCV Mats, transformed by a chain of
``ImageProcessing`` stages (Resize, CenterCrop, Flip, ChannelNormalize,
MatToTensor...) before feeding training.

TPU-native redesign: decode/augment is HOST work that must overlap device
compute (SURVEY §7 names input throughput a top hard part).  ImageSet holds
*paths + labels* (cheap, shardable); decode + the transform chain run
lazily in the streaming feed's worker threads (data/stream.py), which push
ready batches through the native C++ queue while the chip trains.  NHWC
uint8→float32 throughout (TPU conv layout; models/image.py is NHWC).

Transforms are plain callables ``img[np.uint8 HWC] -> img``; the chain is
a list, matching the reference's ImageProcessing pipeline composition.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .shards import XShards

IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif")


# -- transform chain (reference: ImageProcessing subclasses) -------------------

class ImageResize:
    """Bilinear resize to (h, w) (reference: image/Resize)."""

    def __init__(self, h: int, w: int):
        self.h, self.w = h, w

    def __call__(self, img: np.ndarray) -> np.ndarray:
        from PIL import Image
        return np.asarray(Image.fromarray(img).resize(
            (self.w, self.h), Image.BILINEAR))


def _check_crop(img: np.ndarray, h: int, w: int, kind: str) -> None:
    ih, iw = img.shape[:2]
    if ih < h or iw < w:
        raise ValueError(
            f"{kind}({h}, {w}) got a {ih}x{iw} image — resize first "
            f"(a silent undersized crop would break batch stacking later)")


class ImageCenterCrop:
    def __init__(self, h: int, w: int):
        self.h, self.w = h, w

    def __call__(self, img: np.ndarray) -> np.ndarray:
        _check_crop(img, self.h, self.w, "ImageCenterCrop")
        ih, iw = img.shape[:2]
        top = (ih - self.h) // 2
        left = (iw - self.w) // 2
        return img[top:top + self.h, left:left + self.w]


class ImageRandomCrop:
    def __init__(self, h: int, w: int):
        self.h, self.w = h, w

    def __call__(self, img: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        _check_crop(img, self.h, self.w, "ImageRandomCrop")
        rng = rng or np.random.default_rng()
        ih, iw = img.shape[:2]
        top = int(rng.integers(0, ih - self.h + 1))
        left = int(rng.integers(0, iw - self.w + 1))
        return img[top:top + self.h, left:left + self.w]


class ImageRandomFlip:
    """Horizontal flip with probability p (reference: image/HFlip)."""

    def __init__(self, p: float = 0.5):
        self.p = p

    def __call__(self, img: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        rng = rng or np.random.default_rng()
        return img[:, ::-1] if rng.random() < self.p else img


class ImageNormalize:
    """uint8 HWC → float32, (x/255 - mean) / std per channel (reference:
    ChannelNormalize)."""

    def __init__(self, mean: Sequence[float] = (0.485, 0.456, 0.406),
                 std: Sequence[float] = (0.229, 0.224, 0.225)):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, img: np.ndarray) -> np.ndarray:
        return (img.astype(np.float32) / 255.0 - self.mean) / self.std


class ImageBrightness:
    """Random additive brightness jitter in [-delta, delta] (reference:
    image/Brightness).  Operates on uint8 pre-normalize."""

    def __init__(self, delta: float = 32.0):
        self.delta = float(delta)

    def __call__(self, img: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        rng = rng or np.random.default_rng()
        shift = rng.uniform(-self.delta, self.delta)
        return np.clip(img.astype(np.float32) + shift, 0, 255).astype(
            img.dtype)


class ImageContrast:
    """Random contrast scale in [lower, upper] about the mean (reference:
    image/Contrast)."""

    def __init__(self, lower: float = 0.5, upper: float = 1.5):
        self.lower, self.upper = float(lower), float(upper)

    def __call__(self, img: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        rng = rng or np.random.default_rng()
        scale = rng.uniform(self.lower, self.upper)
        f = img.astype(np.float32)
        mean = f.mean(axis=(0, 1), keepdims=True)
        return np.clip((f - mean) * scale + mean, 0, 255).astype(img.dtype)


class ImageSaturation:
    """Random saturation scale (blend with per-pixel luma; reference:
    image/Saturation)."""

    _LUMA = np.asarray([0.299, 0.587, 0.114], np.float32)

    def __init__(self, lower: float = 0.5, upper: float = 1.5):
        self.lower, self.upper = float(lower), float(upper)

    def __call__(self, img: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        rng = rng or np.random.default_rng()
        scale = rng.uniform(self.lower, self.upper)
        f = img.astype(np.float32)
        gray = (f[..., :3] @ self._LUMA)[..., None]
        out = gray + (f - gray) * scale
        return np.clip(out, 0, 255).astype(img.dtype)


class ImageColorJitter:
    """Brightness + contrast + saturation in random order per sample
    (reference: the ColorJitter chain the detection pipelines used)."""

    def __init__(self, brightness: float = 32.0,
                 contrast: Sequence[float] = (0.5, 1.5),
                 saturation: Sequence[float] = (0.5, 1.5)):
        self.stages = [ImageBrightness(brightness),
                       ImageContrast(*contrast),
                       ImageSaturation(*saturation)]

    def __call__(self, img: np.ndarray,
                 rng: Optional[np.random.Generator] = None) -> np.ndarray:
        rng = rng or np.random.default_rng()
        order = rng.permutation(len(self.stages))
        for i in order:
            img = self.stages[i](img, rng=rng)
        return img


def decode_image_bytes(data: bytes) -> np.ndarray:
    """Raw file bytes → uint8 HWC RGB — the decode half of the
    readahead split (readers.FileReadahead fetches the bytes)."""
    import io
    from PIL import Image
    with Image.open(io.BytesIO(data)) as im:
        return np.asarray(im.convert("RGB"))


def decode_image(path: str) -> np.ndarray:
    """File → uint8 HWC RGB (reference: OpenCV imdecode behind JNI; here
    PIL on the host — the chip never sees undecoded bytes)."""
    from PIL import Image
    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))


def _takes_rng(t: Callable) -> bool:
    """Does the transform accept the feed's rng (for deterministic
    augmentation)?  Detected by signature so user transforms participate,
    cached on the object."""
    cached = getattr(t, "_zoo_takes_rng", None)
    if cached is None:
        import inspect
        try:
            cached = "rng" in inspect.signature(t).parameters
        except (TypeError, ValueError):
            cached = False
        try:
            t._zoo_takes_rng = cached
        except AttributeError:
            pass  # unsettable (e.g. builtin); re-inspect next time
    return cached


def apply_chain(img: np.ndarray, transforms: Sequence[Callable],
                rng: Optional[np.random.Generator] = None) -> np.ndarray:
    for t in transforms:
        # random transforms take the feed's per-worker rng for determinism
        img = t(img, rng=rng) if _takes_rng(t) else t(img)
    return img


# -- ImageSet ------------------------------------------------------------------

class ImageSet:
    """Paths + labels + transform chain; the decode work happens in the
    streaming feed (reference: ImageSet.read → LocalImageSet /
    DistributedImageSet)."""

    def __init__(self, paths: Sequence[str],
                 labels: Optional[Sequence[int]] = None,
                 transforms: Optional[List[Callable]] = None,
                 class_names: Optional[List[str]] = None,
                 readahead: int = 0):
        self.paths = list(paths)
        self.labels = None if labels is None else np.asarray(labels,
                                                             np.int32)
        self.transforms = list(transforms or [])
        self.class_names = class_names
        # raw-file readahead depth (0 = off): decode workers hint each
        # batch's paths ahead of decoding it, so storage reads overlap
        # decode (readers.FileReadahead; one reader per worker process)
        self.readahead = int(readahead)
        self._ra_lock = threading.Lock()

    @staticmethod
    def read(path: str, with_label: bool = True,
             sharded: bool = False) -> "ImageSet":
        """Read an image directory.  With labels: class-per-subdirectory
        layout (the torchvision/ImageNet convention the reference's examples
        used); without: a flat directory.

        Multi-host: when ``sharded`` and jax.process_count() > 1, each host
        keeps only its slice of the file list (SPMD file split, same contract
        as data/readers.py)."""
        paths: List[str] = []
        labels: List[int] = []
        class_names: Optional[List[str]] = None
        if with_label:
            class_names = sorted(
                d for d in os.listdir(path)
                if os.path.isdir(os.path.join(path, d)))
            for ci, cname in enumerate(class_names):
                for f in sorted(os.listdir(os.path.join(path, cname))):
                    if f.lower().endswith(IMAGE_EXTS):
                        paths.append(os.path.join(path, cname, f))
                        labels.append(ci)
        else:
            for f in sorted(os.listdir(path)):
                if f.lower().endswith(IMAGE_EXTS):
                    paths.append(os.path.join(path, f))
        if sharded:
            import jax
            n, i = jax.process_count(), jax.process_index()
            paths = paths[i::n]
            labels = labels[i::n] if with_label else labels
        return ImageSet(paths, labels if with_label else None,
                        class_names=class_names)

    def transform(self, *transforms: Callable) -> "ImageSet":
        """Append transform stages (chainable, reference-style)."""
        self.transforms.extend(transforms)
        return self

    def __len__(self) -> int:
        return len(self.paths)

    # -- materialization paths ------------------------------------------------

    # -- streaming-feed loader protocols (data/stream.py duck-types these
    # off ``load_sample.__self__``) ------------------------------------------

    def _reader(self):
        """This worker's FileReadahead, created lazily and keyed on pid
        so a forked decode worker never inherits a dead reader thread.
        Creation is locked: concurrent worker THREADS racing the first
        hint must share one instance (every loser would otherwise leak a
        parked reader thread and duplicate its queued reads)."""
        ra = self.__dict__.get("_ra")
        if ra is not None and ra.pid == os.getpid():
            return ra
        from .readers import FileReadahead
        with self._ra_lock:
            ra = self.__dict__.get("_ra")
            if ra is None or ra.pid != os.getpid():
                ra = FileReadahead(depth=max(1, self.readahead))
                self.__dict__["_ra"] = ra
            return ra

    def hint_indices(self, indices: Sequence[int]) -> None:
        """Advisory from the streaming feed: these rows decode next."""
        if self.readahead:
            self._reader().hint([self.paths[i] for i in indices])

    def feed_stats(self) -> Dict[str, float]:
        """Cumulative blocked-on-storage ms for the calling worker
        (surfaced by the feed as ``feed.io_wait_ms``)."""
        if not self.readahead:
            return {"io_wait_ms": 0.0}
        return {"io_wait_ms": self._reader().wait_ms}

    def load_sample(self, i: int,
                    rng: Optional[np.random.Generator] = None
                    ) -> Dict[str, np.ndarray]:
        if self.readahead:
            img = decode_image_bytes(self._reader().get(self.paths[i]))
        else:
            img = decode_image(self.paths[i])
        img = apply_chain(img, self.transforms, rng)
        out: Dict[str, np.ndarray] = {"x": np.ascontiguousarray(img)}
        if self.labels is not None:
            out["y"] = self.labels[i]
        return out

    def to_feed(self, batch_size: int, shuffle: bool = True, seed: int = 0,
                num_workers: Optional[int] = None,
                prefetch_batches: int = 4,
                drop_remainder: bool = True,
                workers: Optional[str] = None,
                readahead: Optional[int] = None):
        """A StreamingDataFeed that decodes/augments in decode workers
        (``workers=``: "thread" | "process", see data/stream.py) and
        prefetches batches through the native queue.  ``readahead`` sets
        the per-worker raw-file readahead depth FOR THIS FEED (None
        keeps the ImageSet's setting; a different value loads through a
        shallow copy, so other feeds and direct ``load_sample`` calls on
        this ImageSet are untouched)."""
        import copy
        from .stream import StreamingDataFeed
        owner = self
        if readahead is not None and int(readahead) != self.readahead:
            owner = copy.copy(self)       # paths/labels/transforms shared
            owner.__dict__.pop("_ra", None)
            owner._ra_lock = threading.Lock()
            owner.readahead = int(readahead)
        return StreamingDataFeed(
            num_samples=len(owner.paths), load_sample=owner.load_sample,
            batch_size=batch_size, shuffle=shuffle, seed=seed,
            num_workers=num_workers, prefetch_batches=prefetch_batches,
            drop_remainder=drop_remainder, workers=workers)

    def to_shards(self, num_shards: int = 4) -> XShards:
        """Eagerly decode everything into numpy-dict XShards (small sets;
        the reference's LocalImageSet analog)."""
        items = [self.load_sample(i) for i in range(len(self.paths))]
        xs = np.stack([it["x"] for it in items])
        data: Dict[str, Any] = {"x": xs}
        if self.labels is not None:
            data["y"] = self.labels.copy()
        chunks = []
        for part in np.array_split(np.arange(len(self.paths)), num_shards):
            chunks.append({k: v[part] for k, v in data.items()})
        return XShards(chunks)
