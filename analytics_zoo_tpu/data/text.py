"""TextSet: the text preprocessing pipeline.

Reference (SURVEY.md §2.2): Scala ``feature/text/*.scala`` +
``pyzoo/zoo/feature/text/text_set.py`` — TextFeature records flowed through
Tokenizer → Normalizer → WordIndexer → SequenceShaper → TextSetToSample,
feeding TextClassifier/KNRM/QARanker.

TPU-native: one host-side class with the same chainable stage names
(tokenize / normalize / word2idx / shape_sequence / generate_sample); the
output is int32 id arrays that batch directly onto the mesh.  Index 0 is
PAD, index 1 is OOV (out-of-vocabulary), real words start at 2 — the
reference's WordIndexer convention.
"""

from __future__ import annotations

import json
import os
import re
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")
PAD_ID = 0
OOV_ID = 1


class TextSet:
    """texts (+ optional integer labels) → tokenized/indexed/padded arrays."""

    def __init__(self, texts: Sequence[str],
                 labels: Optional[Sequence[int]] = None):
        self.texts = list(texts)
        self.labels = None if labels is None else np.asarray(labels,
                                                             np.int32)
        if self.labels is not None and len(self.labels) != len(self.texts):
            raise ValueError(
                f"{len(self.texts)} texts but {len(self.labels)} labels")
        self.tokens: Optional[List[List[str]]] = None
        self.word_index: Optional[Dict[str, int]] = None
        self._ids: Optional[List[List[int]]] = None
        self._seq_len: Optional[int] = None

    # -- constructors (reference: TextSet.read / from RDD) ---------------------

    @staticmethod
    def from_texts(texts: Sequence[str],
                   labels: Optional[Sequence[int]] = None) -> "TextSet":
        return TextSet(texts, labels)

    @staticmethod
    def read_csv(path: str, text_col: str = "text",
                 label_col: Optional[str] = "label") -> "TextSet":
        import pandas as pd
        df = pd.read_csv(path)
        labels = (df[label_col].to_numpy()
                  if label_col and label_col in df else None)
        return TextSet(df[text_col].astype(str).tolist(), labels)

    # -- pipeline stages (chainable, reference stage names) --------------------

    def tokenize(self) -> "TextSet":
        self.tokens = [_TOKEN_RE.findall(t) for t in self.texts]
        return self

    def normalize(self) -> "TextSet":
        """Lowercase (reference Normalizer also stripped punctuation, which
        the token regex already did)."""
        if self.tokens is None:
            self.tokenize()
        self.tokens = [[w.lower() for w in toks] for toks in self.tokens]
        return self

    def word2idx(self, max_words_num: Optional[int] = None,
                 min_freq: int = 1,
                 existing_index: Optional[Dict[str, int]] = None
                 ) -> "TextSet":
        """Build (or adopt) the vocab and map tokens → ids.  Val/test sets
        pass the train set's ``word_index`` so ids agree across splits."""
        if self.tokens is None:
            self.normalize()
        if existing_index is not None:
            self.word_index = dict(existing_index)
        else:
            counts = Counter(w for toks in self.tokens for w in toks)
            vocab = [w for w, c in counts.most_common(max_words_num)
                     if c >= min_freq]
            self.word_index = {w: i + 2 for i, w in enumerate(vocab)}
        wi = self.word_index
        self._ids = [[wi.get(w, OOV_ID) for w in toks]
                     for toks in self.tokens]
        return self

    def shape_sequence(self, len: int,  # noqa: A002 — reference arg name
                       trunc_mode: str = "pre") -> "TextSet":
        """Pad (with PAD_ID) or truncate every sequence to ``len``.
        ``trunc_mode``: "pre" keeps the tail, "post" keeps the head —
        reference SequenceShaper semantics."""
        if self._ids is None:
            raise ValueError("call word2idx before shape_sequence")
        out = []
        for ids in self._ids:
            if len_ := max(0, len - np.size(ids)):
                ids = list(ids) + [PAD_ID] * len_
            elif trunc_mode == "pre":
                ids = list(ids[-len:])
            else:
                ids = list(ids[:len])
            out.append(ids)
        self._ids = out
        self._seq_len = len
        return self

    # -- materialization -------------------------------------------------------

    def generate_sample(self) -> "TextSet":  # reference-parity no-op marker
        return self

    def to_numpy(self):
        if self._ids is None or self._seq_len is None:
            raise ValueError("run tokenize/word2idx/shape_sequence first")
        x = np.asarray(self._ids, np.int32)
        if self.labels is not None:
            return x, self.labels.copy()
        return x, None

    def to_feed(self, batch_size: int, **kw: Any):
        from .feed import DataFeed
        x, y = self.to_numpy()
        return DataFeed.from_arrays(x, y, batch_size, **kw)

    def vocab_size(self) -> int:
        """Embedding-table size: ids run 0..len(word_index)+1."""
        if self.word_index is None:
            raise ValueError("call word2idx first")
        return len(self.word_index) + 2

    def __len__(self) -> int:
        return len(self.texts)

    # -- word-index persistence (reference: save/load_word_index) --------------

    def save_word_index(self, path: str) -> str:
        if self.word_index is None:
            raise ValueError("no word index: call word2idx first")
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.word_index, f)
        return path

    @staticmethod
    def load_word_index(path: str) -> Dict[str, int]:
        with open(path) as f:
            return json.load(f)
