"""Data layer: XShards, file readers, device feed (reference L4, SURVEY.md §2.2)."""

from .feed import (DataFeed, PrefetchIterator, as_feed, batch_sharding,
                   shard_batch)
from .readers import (FileReadahead, read_csv, read_json, read_npz,
                      read_parquet)
from .shards import XShards
from .stream import StreamingDataFeed, make_placer
from .shm_pool import ShmBatchPool, SlotBatch
from .augment import (DeviceAugment, DeviceNormalize, DeviceRandomCrop,
                      DeviceRandomFlip)
from .image import (ImageSet, ImageResize, ImageCenterCrop, ImageRandomCrop,
                    ImageRandomFlip, ImageNormalize, ImageBrightness,
                    ImageContrast, ImageSaturation, ImageColorJitter)
from .text import TextSet
from .interop import (IterableDataFeed, from_iterator, from_tf_dataset,
                      from_torch_dataset, from_torch_dataloader)

# reference-parity namespace: zoo.orca.data.pandas.read_csv
from . import readers as pandas  # noqa: F401

__all__ = [
    "XShards", "DataFeed", "PrefetchIterator", "as_feed", "batch_sharding",
    "shard_batch",
    "read_csv", "read_json", "read_npz", "read_parquet", "pandas",
    "FileReadahead", "StreamingDataFeed", "make_placer", "ShmBatchPool",
    "SlotBatch", "DeviceAugment", "DeviceNormalize", "DeviceRandomCrop",
    "DeviceRandomFlip", "ImageSet", "ImageResize", "ImageCenterCrop",
    "ImageRandomCrop", "ImageRandomFlip", "ImageNormalize", "ImageBrightness",
    "ImageContrast", "ImageSaturation", "ImageColorJitter", "TextSet",
    "IterableDataFeed", "from_iterator", "from_tf_dataset",
    "from_torch_dataset", "from_torch_dataloader",
]
