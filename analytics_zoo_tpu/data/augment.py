"""DeviceAugment: batch-level fused augmentation inside the jit step.

The host-side ``ImageProcessing`` chain (data/image.py) normalizes each
image to float32 on the CPU, which makes the host→device payload 4×
larger than the decoded uint8 pixels and burns decode-worker cycles on
arithmetic an accelerator does for free.  This module is the device half
of the split the streaming input pipeline wants:

- host workers only DECODE (file bytes → uint8 HWC), so the feed ships
  compact ``uint8`` NHWC batches over PCIe/ICI;
- normalize / random-crop / flip run ON DEVICE as part of the
  jit-compiled train step (``ZooEstimator(augment=...)``), fused by XLA
  into the first conv's prologue — per-step cost is effectively the
  memory read the step does anyway.

Randomness is functional: the estimator passes a per-step PRNG key
(folded from the train step's rng), each stage folds in its chain index,
and per-image decisions are drawn with batch-shaped draws — so
augmentation is reproducible from the seed and independent of host
worker scheduling (unlike the host chain, whose rng stream depends on
which worker decoded which batch).

Stages mirror the host chain (``ImageNormalize``/``ImageRandomCrop``/
``ImageRandomFlip``) closely enough that moving a pipeline from host to
device is a drop-in swap; at eval time (``training=False``) random
stages become deterministic (center crop, no flip) while shape-changing
behavior is preserved so the model always sees one static shape.

Everything here is pure ``jax.numpy`` — jit/vmap/scan composable.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = ["DeviceAugment", "DeviceNormalize", "DeviceRandomCrop",
           "DeviceRandomFlip"]


class DeviceNormalize:
    """uint8 NHWC → float32, ``(x/255 - mean) / std`` per channel — the
    device mirror of ``ImageNormalize`` (same constants, same order of
    operations, so a host-normalized and a device-normalized pipeline
    reach loss parity)."""

    random = False

    def __init__(self, mean: Sequence[float] = (0.485, 0.456, 0.406),
                 std: Sequence[float] = (0.229, 0.224, 0.225)):
        self.mean = tuple(float(m) for m in mean)
        self.std = tuple(float(s) for s in std)

    def __call__(self, x: jax.Array, key: Optional[jax.Array] = None,
                 training: bool = True) -> jax.Array:
        mean = jnp.asarray(self.mean, jnp.float32)
        std = jnp.asarray(self.std, jnp.float32)
        return (x.astype(jnp.float32) / 255.0 - mean) / std


class DeviceRandomCrop:
    """Per-image random (h, w) crop at train time, center crop at eval —
    the device mirror of ``ImageRandomCrop``/``ImageCenterCrop``.  The
    output shape is static (``[B, h, w, C]``) either way, so the jit
    step compiles once."""

    random = True

    def __init__(self, h: int, w: int):
        self.h, self.w = int(h), int(w)

    def __call__(self, x: jax.Array, key: Optional[jax.Array] = None,
                 training: bool = True) -> jax.Array:
        ih, iw = x.shape[1], x.shape[2]
        if ih < self.h or iw < self.w:
            raise ValueError(
                f"DeviceRandomCrop({self.h}, {self.w}) got {ih}x{iw} "
                f"images — resize on the host first")
        if not training or key is None:
            top = (ih - self.h) // 2
            left = (iw - self.w) // 2
            return x[:, top:top + self.h, left:left + self.w]
        kh, kw = jax.random.split(key)
        tops = jax.random.randint(kh, (x.shape[0],), 0, ih - self.h + 1)
        lefts = jax.random.randint(kw, (x.shape[0],), 0, iw - self.w + 1)

        def crop(img, t, l):
            return jax.lax.dynamic_slice(
                img, (t, l, 0), (self.h, self.w, img.shape[2]))

        return jax.vmap(crop)(x, tops, lefts)


class DeviceRandomFlip:
    """Per-image horizontal flip with probability ``p`` at train time
    (no-op at eval) — the device mirror of ``ImageRandomFlip``."""

    random = True

    def __init__(self, p: float = 0.5):
        self.p = float(p)

    def __call__(self, x: jax.Array, key: Optional[jax.Array] = None,
                 training: bool = True) -> jax.Array:
        if not training or key is None:
            return x
        coin = jax.random.bernoulli(key, self.p, (x.shape[0],))
        return jnp.where(coin[:, None, None, None], x[:, :, ::-1, :], x)


class DeviceAugment:
    """A jit-composable chain of device augmentation stages.

    ``DeviceAugment([DeviceRandomCrop(224, 224), DeviceRandomFlip(),
    DeviceNormalize()])(x, key, training)`` — each stage receives
    ``jax.random.fold_in(key, stage_index)`` so adding or reordering
    stages never silently reuses another stage's randomness.  With
    ``key=None`` or ``training=False`` the chain is deterministic
    (center crops, no flips, normalize applies) — what ``evaluate`` /
    ``predict`` use.
    """

    def __init__(self, stages: Sequence):
        self.stages = list(stages)

    def __call__(self, x: jax.Array, key: Optional[jax.Array] = None,
                 training: bool = True) -> jax.Array:
        for i, stage in enumerate(self.stages):
            k = None if key is None else jax.random.fold_in(key, i)
            x = stage(x, k, training)
        return x

    def __repr__(self) -> str:
        names = ", ".join(type(s).__name__ for s in self.stages)
        return f"DeviceAugment([{names}])"
