"""BERT fine-tune example (reference:
pyzoo/zoo/examples/tfpark/estimator/bert_classifier.py — BERTClassifier on
the TFPark BERT estimator).

Fine-tunes a (small, randomly initialized) BERTClassifier for sequence
classification through the unified Estimator.  With zero network egress the
default corpus is synthetic: class-0 sequences are drawn from the low half
of the vocab, class-1 from the high half, so the model has real signal to
fit.  To fine-tune a published checkpoint, import weights first with
``Net.load_torch`` (analytics_zoo_tpu/models/net.py) and load them into the
estimator.

Run:  python examples/bert_finetune.py --epochs 1 --samples 256
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np


def synthetic_corpus(n: int, seq_len: int, vocab: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 2, n).astype(np.int32)
    lo = rng.integers(1, vocab // 2, (n, seq_len))
    hi = rng.integers(vocab // 2, vocab, (n, seq_len))
    x = np.where(y[:, None] == 0, lo, hi).astype(np.int32)
    x[:, 0] = 0  # [CLS] slot
    return x, y


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--samples", type=int, default=256)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--seq-len", type=int, default=64)
    parser.add_argument("--vocab", type=int, default=1000)
    parser.add_argument("--hidden", type=int, default=128)
    parser.add_argument("--layers", type=int, default=2)
    args = parser.parse_args()

    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.models import BERTClassifier
    from analytics_zoo_tpu.orca.learn import Estimator

    init_orca_context("local")
    try:
        model = BERTClassifier(class_num=2, vocab_size=args.vocab,
                               hidden_size=args.hidden,
                               n_layers=args.layers,
                               n_heads=args.hidden // 32)
        x, y = synthetic_corpus(args.samples, args.seq_len, args.vocab)
        x_val, y_val = synthetic_corpus(128, args.seq_len, args.vocab,
                                        seed=1)
        est = Estimator.from_keras(
            model, loss="sparse_categorical_crossentropy",
            optimizer="adamw", learning_rate=3e-4, metrics=["accuracy"])
        est.fit((x, y), epochs=args.epochs, batch_size=args.batch_size)
        result = est.evaluate((x_val, y_val), batch_size=args.batch_size)
        print(f"validation: {result}")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
