"""Foreign-model import example — the reference's TorchNet flow
(reference: pyzoo/zoo/examples/pytorch: load a torch model, run it through
the zoo pipeline).

A graph-structured torch CNN (residual connection — the shape TorchNet ran
through libtorch JNI) is converted ONCE into native modules via torch.fx
(`Net.load_torch`), then fine-tuned and served on TPU like any native
model — something the reference's JNI bridge could not do.

Run:  python examples/torch_import.py --epochs 2
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np


def build_torch_model():
    import torch.nn as tnn

    class ResBlock(tnn.Module):
        def __init__(self, c):
            super().__init__()
            self.c1 = tnn.Conv2d(c, c, 3, padding=1)
            self.c2 = tnn.Conv2d(c, c, 3, padding=1)

        def forward(self, x):
            import torch
            h = torch.relu(self.c1(x))
            return torch.relu(self.c2(h) + x)

    class Net(tnn.Module):
        def __init__(self):
            super().__init__()
            self.stem = tnn.Conv2d(1, 8, 3, padding=1)
            self.block = ResBlock(8)
            self.pool = tnn.AdaptiveAvgPool2d(1)
            self.fc = tnn.Linear(8, 10)

        def forward(self, x):
            import torch
            h = torch.relu(self.stem(x))
            h = self.block(h)
            h = self.pool(h)
            return self.fc(torch.flatten(h, 1))

    return Net()


def synthetic_mnist(n: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = rng.normal(0.0, 0.1, (n, 1, 28, 28)).astype(np.float32)  # NCHW
    for i in range(n):
        r, c = divmod(int(y[i]), 4)
        x[i, 0, 7 * r:7 * r + 7, 7 * c:7 * c + 7] += 1.0
    return x, y


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--samples", type=int, default=256)
    parser.add_argument("--batch-size", type=int, default=64)
    args = parser.parse_args()

    import torch

    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator

    init_orca_context("local")
    try:
        tm = build_torch_model().eval()
        x, y = synthetic_mnist(args.samples)

        # differential check: converted forward matches torch
        from analytics_zoo_tpu.models import Net
        import jax
        net = Net.load_torch(tm, x[:4])
        variables = net.init(jax.random.PRNGKey(0))
        ours, _ = net.apply(variables, x[:4])
        with torch.no_grad():
            ref = tm(torch.as_tensor(x[:4])).numpy()
        err = float(np.abs(np.asarray(ours) - ref).max())
        print(f"conversion max |diff| vs torch: {err:.2e}")

        # reference-style script: one import line changed
        est = Estimator.from_torch(model=tm,
                                   loss="sparse_categorical_crossentropy",
                                   optimizer="adam", learning_rate=2e-3,
                                   metrics=["accuracy"],
                                   example_input=x[:4])
        est.fit((x, y), epochs=args.epochs, batch_size=args.batch_size,
                verbose=False)
        result = est.evaluate((x, y), batch_size=args.batch_size)
        print(f"validation: {result}")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
