"""Calibrated-int8 + AOT-artifact serving example (reference: the
OpenVINO INT8 quickstart — calibrate → save IR → load IR → serve).

Trains a small CNN classifier, calibrates static int8 activation scales
from a representative batch, serves it int8 (Dense matmuls and Conv2D
convolutions run int8 x int8 -> int32 on the MXU), then demonstrates the
OpenVINO-IR analog: ``save_executables`` writes per-shape compiled-
computation artifacts that a RESTARTED process loads without re-tracing
(and, with ``enable_aot_cache``, without re-running the XLA compile).

Run:  python examples/int8_aot_serving.py
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempfile
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    from analytics_zoo_tpu.serving import InferenceModel, enable_aot_cache

    init_orca_context("local")
    try:
        enable_aot_cache(tempfile.mkdtemp(prefix="zoo_aot_cache_"))

        # 1. train a small CNN (class signal: bright channel per class)
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (256, 16, 16, 3)).astype(np.float32)
        y = rng.integers(0, 3, 256).astype(np.int32)
        for i in range(len(x)):
            x[i, :, :, y[i]] += 2.0
        model = nn.Sequential([
            nn.Conv2D(16, 3, activation="relu"),
            nn.Conv2D(32, 3, strides=2, activation="relu"),
            nn.GlobalAveragePooling2D(),
            nn.Dense(3)])
        est = Estimator.from_keras(model,
                                   loss="sparse_categorical_crossentropy",
                                   optimizer="adam", learning_rate=3e-3)
        est.fit((x, y), epochs=3, batch_size=32, verbose=False)
        variables = est.get_model()

        # 2. calibrated int8 serving: one float pass over a
        # representative batch freezes the activation scales
        f32 = InferenceModel().load(model, variables)
        q = InferenceModel().load(model, variables, dtype="int8",
                                  calibrate=x[:64])
        out_f32 = np.asarray(f32.predict(x[:64]))
        out_q = np.asarray(q.predict(x[:64]))
        agree = float(np.mean(out_q.argmax(1) == out_f32.argmax(1)))
        print(f"int8 vs f32 top-1 agreement: {agree:.2%} "
              f"({len(q._quant_ctx.amax)} calibrated layers)")

        # 3. the OpenVINO-IR analog: serialize the compiled computations,
        # reload them in a "restarted" server without the cold compile
        aot_dir = tempfile.mkdtemp(prefix="zoo_aot_exec_")
        n = q.save_executables(aot_dir)
        restarted = InferenceModel().load(model, variables, dtype="int8",
                                          calibrate=x[:64])
        loaded = restarted.load_executables(aot_dir)
        # the reload path is what this example guards: a serialization
        # or fingerprint regression must fail here, not silently fall
        # back to a fresh compile
        assert n >= 1 and loaded == n, (n, loaded)
        t0 = time.perf_counter()
        out_r = np.asarray(restarted.predict(x[:64]))
        dt = time.perf_counter() - t0
        np.testing.assert_allclose(out_r, out_q, rtol=1e-5)
        print(f"AOT artifacts: saved {n}, loaded {loaded}; restarted "
              f"first predict {dt * 1e3:.0f} ms (no re-trace), outputs "
              f"identical")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
