"""Cluster-serving example (reference: the cluster-serving quickstart —
scripts/cluster-serving/ + pyzoo/zoo/serving: train → save → serve →
query).

Trains a small classifier, saves it as a ZooModel, starts the serving
stack (TCP micro-batcher + HTTP frontend) in-process, then queries it
through BOTH client paths — the binary InputQueue/OutputQueue protocol
and HTTP/JSON — and prints the service stats.

Run:  python examples/cluster_serving.py
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import tempfile
import urllib.request

import numpy as np


def main() -> None:
    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.models import TextClassifier
    from analytics_zoo_tpu.serving import (ClusterServing, HTTPFrontend,
                                           InferenceModel, InputQueue,
                                           OutputQueue)

    init_orca_context("local")
    try:
        # 1. train a tiny model and save it the ZooModel way
        rng = np.random.default_rng(0)
        x = rng.integers(0, 100, (128, 16)).astype(np.int32)
        y = (x.mean(axis=1) > 50).astype(np.int32)
        model = TextClassifier(class_num=2, vocab_size=100, token_length=16,
                               sequence_length=16, encoder="cnn")
        model.compile("sparse_categorical_crossentropy",
                      learning_rate=1e-2, metrics=["accuracy"])
        model.fit((x, y), epochs=3, batch_size=32)
        model_dir = tempfile.mkdtemp()
        model.save_model(model_dir)
        print(f"saved model to {model_dir}")

        # 2. serve it (equivalently: `zoo-serving --model-dir ... --port
        #    8980 --http-port 8981` from a shell)
        engine = InferenceModel().load_zoo_model(model_dir)
        with ClusterServing(engine, batch_size=16) as srv:
            with HTTPFrontend(srv.host, srv.port) as fe:
                # 3a. binary protocol client
                q = InputQueue(srv.host, srv.port)
                uid = q.enqueue("req-1", t=x[0])
                out = OutputQueue(input_queue=q).query(uid, timeout=60)
                print(f"TCP client prediction: {np.argmax(out)} "
                      f"(logits {np.round(out, 3)})")
                q.close()

                # 3b. HTTP/JSON client
                url = f"http://{fe.host}:{fe.port}"
                req = urllib.request.Request(
                    url + "/predict",
                    data=json.dumps({"instances": x[1].tolist(),
                                     "dtype": "int32"}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    preds = json.loads(r.read())["predictions"]
                print(f"HTTP client prediction: "
                      f"{int(np.argmax(preds))} (logits "
                      f"{np.round(preds, 3).tolist()})")

            print(f"service stats: {srv.stats()}")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
