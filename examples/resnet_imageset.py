"""ResNet + streaming ImageSet example — the reference's image BASELINE
config (reference: pyzoo/zoo/examples/orca/learn image-classification
examples: ImageSet.read → preprocessing chain → distributed fit).

Reads a class-per-subdirectory image folder through the streaming input
pipeline (decode + augment in worker threads, batches prefetched through
the native C++ queue — never materializing the dataset in RAM) and trains
a ResNet through the unified estimator.  With zero egress the default
dataset is synthetic JPEGs written to a temp dir; point --data-dir at any
ImageNet-style folder for real data.

Run:  python examples/resnet_imageset.py --epochs 2 --depth 18
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import tempfile

import numpy as np


def write_synthetic_dataset(root: str, n_per_class: int = 24,
                            size: int = 64, seed: int = 0) -> None:
    from PIL import Image
    rng = np.random.default_rng(seed)
    for ci, cname in enumerate(("class_a", "class_b", "class_c")):
        d = os.path.join(root, cname)
        os.makedirs(d, exist_ok=True)
        base = 60 + 60 * ci  # distinct mean brightness per class
        for i in range(n_per_class):
            arr = np.clip(rng.normal(base, 35, (size, size, 3)), 0,
                          255).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.jpg"))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--depth", type=int, default=18)
    parser.add_argument("--image-size", type=int, default=56)
    parser.add_argument("--data-dir", default=None,
                        help="class-per-subdir image folder (default: "
                             "synthetic)")
    parser.add_argument("--num-workers", type=int, default=4)
    parser.add_argument("--feed-backend", default="thread",
                        choices=("thread", "process"),
                        help="decode-worker backend: 'process' scales "
                             "GIL-bound decode across host cores via the "
                             "shared-memory slot pool (data/shm_pool.py)")
    parser.add_argument("--readahead", type=int, default=0,
                        help="per-worker raw-file readahead depth "
                             "(0 = off): overlaps storage reads with "
                             "decode")
    args = parser.parse_args()

    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.data import (ImageNormalize, ImageRandomCrop,
                                        ImageRandomFlip, ImageResize,
                                        ImageSet)
    from analytics_zoo_tpu.models import ResNet
    from analytics_zoo_tpu.orca.learn import Estimator

    init_orca_context("local")
    tmp = None
    try:
        data_dir = args.data_dir
        if data_dir is None:
            tmp = tempfile.TemporaryDirectory()
            write_synthetic_dataset(tmp.name)
            data_dir = tmp.name

        pad = args.image_size + 8
        image_set = ImageSet.read(data_dir, with_label=True).transform(
            ImageResize(pad, pad),
            ImageRandomCrop(args.image_size, args.image_size),
            ImageRandomFlip(),
            ImageNormalize((0.485, 0.456, 0.406), (0.229, 0.224, 0.225)),
        )
        n_classes = len(image_set.class_names)
        print(f"{len(image_set)} images, {n_classes} classes "
              f"({image_set.class_names})")

        model = ResNet(depth=args.depth, class_num=n_classes)
        est = Estimator.from_keras(
            model, loss="sparse_categorical_crossentropy",
            optimizer="adam", learning_rate=1e-3, metrics=["accuracy"])
        # streaming feed: decode/augment in workers, native-queue prefetch
        feed = image_set.to_feed(batch_size=args.batch_size,
                                 num_workers=args.num_workers,
                                 workers=args.feed_backend,
                                 readahead=args.readahead)
        est.fit(feed, epochs=args.epochs, batch_size=args.batch_size)

        eval_set = ImageSet.read(data_dir, with_label=True).transform(
            ImageResize(args.image_size, args.image_size),
            ImageNormalize((0.485, 0.456, 0.406), (0.229, 0.224, 0.225)),
        )
        result = est.evaluate(
            eval_set.to_feed(batch_size=args.batch_size, shuffle=False,
                             num_workers=args.num_workers,
                             drop_remainder=False),
            batch_size=args.batch_size)
        print(f"train-set eval: {result}")
    finally:
        if tmp is not None:
            tmp.cleanup()
        stop_orca_context()


if __name__ == "__main__":
    main()
