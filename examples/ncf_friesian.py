"""NCF + Friesian example — the reference's recsys BASELINE config
(reference: pyzoo/zoo/examples/friesian + orca NCF examples: tabular
feature engineering → NeuralCF end-to-end).

Builds implicit-feedback training data with the Friesian FeatureTable
(string-id encode → negative sampling → split) and trains NeuralCF through
the unified estimator, then serves top-k recommendations per user.  With
zero egress the interactions are synthetic (a hidden block structure so
the model has real signal); pass --csv to use a ratings file with
user,item columns instead.

Run:  python examples/ncf_friesian.py --epochs 3
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np


def synthetic_ratings(n_users=120, n_items=80, n_rows=2000, seed=0):
    import pandas as pd
    rng = np.random.default_rng(seed)
    users = rng.integers(0, n_users, n_rows)
    # block structure: even users prefer the first item half, odd the second
    half = n_items // 2
    items = np.where(users % 2 == 0,
                     rng.integers(0, half, n_rows),
                     rng.integers(half, n_items, n_rows))
    return pd.DataFrame({"user": [f"u{u}" for u in users],
                         "item": [f"i{i}" for i in items]})


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--neg-num", type=int, default=2)
    parser.add_argument("--csv", default=None,
                        help="ratings csv with user,item columns")
    args = parser.parse_args()

    import pandas as pd

    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.friesian import FeatureTable
    from analytics_zoo_tpu.models import NeuralCF

    init_orca_context("local")
    try:
        df = (pd.read_csv(args.csv) if args.csv else synthetic_ratings())
        tbl = FeatureTable.from_pandas(df)

        # feature engineering: string ids → ints, implicit negatives, split
        enc, idxs = tbl.encode_string(["user", "item"])
        user_size, item_size = idxs[0].size, idxs[1].size
        data = enc.negative_sample(item_size=item_size, item_col="item",
                                   neg_num=args.neg_num)
        train, test = data.random_split([0.8, 0.2], seed=0)

        model = NeuralCF(user_count=user_size, item_count=item_size,
                         class_num=2)
        model.compile(loss="sparse_categorical_crossentropy",
                      optimizer="adam", learning_rate=1e-3,
                      metrics=["accuracy"])
        model.fit(train.to_feed(feature_cols=["user", "item"],
                                label_col="label",
                                batch_size=args.batch_size),
                  epochs=args.epochs, batch_size=args.batch_size)
        result = model.evaluate(
            test.to_feed(feature_cols=["user", "item"], label_col="label",
                         batch_size=args.batch_size, shuffle=False,
                         drop_remainder=False),
            batch_size=args.batch_size)
        print(f"test: {result}")

        # top-3 recommendations (reference: recommend_for_user)
        recs = model.recommend_for_user([1, 2], max_items=3)
        print(f"top-3 per user: {recs[:6]}")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
