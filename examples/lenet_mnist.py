"""LeNet image-classification example (reference:
pyzoo/zoo/examples/orca/learn/*/lenet_mnist.py — the reference's canonical
"hello world" for the Orca estimator).

Trains a LeNet-style CNN through the unified Estimator on MNIST-shaped data.
With zero network egress in CI this script generates a synthetic MNIST-like
dataset by default (28x28x1 digit-blob images, 10 classes); pass --data-dir
pointing at npz files with "x"/"y" arrays to train on real data via the
orca.data readers.

Run:  python examples/lenet_mnist.py --epochs 2 --samples 512
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse

import numpy as np


def synthetic_mnist(n: int, seed: int = 0):
    """Class-conditional blob images: each class lights a distinct 7x7
    region plus noise, so a small CNN can actually learn the mapping."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = rng.normal(0.0, 0.1, (n, 28, 28, 1)).astype(np.float32)
    for i in range(n):
        r, c = divmod(int(y[i]), 4)
        x[i, 7 * r:7 * r + 7, 7 * c:7 * c + 7, 0] += 1.0
    return x, y


def build_lenet():
    import analytics_zoo_tpu.nn as nn

    return nn.Sequential([
        nn.Conv2D(6, 5, padding="same", activation="tanh"),
        nn.MaxPooling2D(2),
        nn.Conv2D(16, 5, activation="tanh"),
        nn.MaxPooling2D(2),
        nn.Flatten(),
        nn.Dense(120, activation="tanh"),
        nn.Dense(84, activation="tanh"),
        nn.Dense(10),
    ])


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--samples", type=int, default=512)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--data-dir", default=None,
                        help="npz dir with x/y arrays (default: synthetic)")
    args = parser.parse_args()

    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator

    init_orca_context("local")
    try:
        if args.data_dir:
            from analytics_zoo_tpu.data import read_npz
            shards = read_npz(args.data_dir)
            train_data: object = shards
            x_val, y_val = synthetic_mnist(256, seed=1)
        else:
            x, y = synthetic_mnist(args.samples)
            x_val, y_val = synthetic_mnist(256, seed=1)
            train_data = (x, y)

        est = Estimator.from_keras(
            build_lenet(), loss="sparse_categorical_crossentropy",
            optimizer="adam", learning_rate=1e-3, metrics=["accuracy"])
        est.fit(train_data, epochs=args.epochs,
                batch_size=args.batch_size,
                validation_data=(x_val, y_val))
        result = est.evaluate((x_val, y_val), batch_size=args.batch_size)
        print(f"validation: {result}")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
