"""Chronos AutoTS example (reference:
pyzoo/zoo/examples/chronos/... + the AutoTS quickstart in the reference
docs: TSDataset → AutoTSEstimator.fit → TSPipeline).

Searches over forecaster families + hyperparameters on a synthetic
daily-seasonality series, then predicts with the winning TSPipeline and
round-trips it through save/load.

Run:  python examples/chronos_autots.py --epochs 2 --n-sampling 2
"""

from __future__ import annotations

# allow `python examples/<script>.py` straight from a checkout (the
# CI harness sets PYTHONPATH; a user following the README should not
# need to): put the repo root ahead of the script's own directory
import os
import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import tempfile

import numpy as np
import pandas as pd


def synthetic_series(n: int = 600, seed: int = 0) -> pd.DataFrame:
    rng = np.random.default_rng(seed)
    t = np.arange(n)
    value = (10.0 + 3.0 * np.sin(2 * np.pi * t / 24)
             + 0.01 * t + rng.normal(0, 0.3, n))
    return pd.DataFrame({
        "timestamp": pd.date_range("2026-01-01", periods=n, freq="h"),
        "value": value.astype(np.float32),
    })


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--n-sampling", type=int, default=2)
    parser.add_argument("--lookback", type=int, default=24)
    parser.add_argument("--horizon", type=int, default=4)
    args = parser.parse_args()

    from analytics_zoo_tpu.chronos import (AutoTSEstimator, TSDataset,
                                           TSPipeline)
    from analytics_zoo_tpu.core import init_orca_context, stop_orca_context

    init_orca_context("local")
    try:
        df = synthetic_series()
        train, _, test = TSDataset.from_pandas(
            df, dt_col="timestamp", target_col="value", with_split=True,
            test_ratio=0.1)
        train.scale()
        test.scale(train.scaler, fit=False)

        auto = AutoTSEstimator(model=["lstm", "tcn"],
                               past_seq_len=args.lookback,
                               future_seq_len=args.horizon)
        pipeline = auto.fit(train, epochs=args.epochs,
                            n_sampling=args.n_sampling)
        print(f"best config: {auto.best_config}")

        test.roll(args.lookback, args.horizon)
        x_test, y_test = test.to_numpy()
        metrics = pipeline.evaluate((x_test, y_test))
        print(f"test metrics: {metrics}")

        with tempfile.TemporaryDirectory() as d:
            pipeline.save(d)
            reloaded = TSPipeline.load(d)
            pred = reloaded.predict(x_test[:4])
            print(f"reloaded prediction shape: {pred.shape}")
    finally:
        stop_orca_context()


if __name__ == "__main__":
    main()
