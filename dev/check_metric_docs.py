#!/usr/bin/env python
"""CI guard: the metric AND span catalogs in docs/observability.md match
the code.

The metric catalog drifted risk-free through four PRs — nothing failed
when a new series was registered but never documented, or a documented
series was renamed away.  This checker closes the loop without importing
(or running) anything; since ISSUE 9 it guards the SPAN catalog the same
way, so span naming can't drift undocumented either:

- **metrics, code side**: every metric name registered through the
  ``core/metrics.py`` registry is found by scanning ``analytics_zoo_tpu``
  sources for ``counter("...")`` / ``gauge("...")`` /
  ``histogram("...")`` / ``inc("...")`` / ``observe("...")`` /
  ``set_gauge("...")`` string literals, PLUS the three known dynamic
  registration sites (``"client." + key`` over the client's stats dict,
  ``"server." + k`` over the server's counters dict, ``"frontend." +
  key`` over ``_FRONTEND_COUNTERS``) whose key sets are extracted from
  the same files;
- **spans, code side**: every span name recorded through ``core/trace.py``
  — the second argument of ``trace.record(...)`` / ``trace_lib.record``
  call sites and the first argument of ``trace.span("...")`` /
  ``.child("...")`` — as string literals (span names are a closed
  vocabulary by design; build one from a variable and this guard can't
  see it, so don't);
- **docs side**: the first column of the catalog tables (rows starting
  with ``| `` + a backtick), splitting ``a / b`` cells — metric rows
  from the "## Metric catalog" section, span rows from the
  "## Span catalog" section.

Exit 1 (with a readable diff) when code and catalog disagree in either
direction, for either vocabulary.  Wired into the test suite
(``tests/test_observability.py::test_metric_catalog_matches_code``).
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "analytics_zoo_tpu"
DOC = REPO / "docs" / "observability.md"

#: registry write/handle calls whose first argument is the series name
_LITERAL = re.compile(
    r'\.(?:counter|gauge|histogram|inc|observe|set_gauge)\(\s*'
    r'"([a-z0-9_.]+)"')

#: span-producing calls: record(<expr>, "name", ...) / span("name") /
#: sp.child("name").  The record() first argument never contains a
#: comma at this call depth (a bare name, attribute, or subscript).
_SPAN_RECORD = re.compile(
    r'\.record\(\s*\n?\s*[^,()]+,\s*\n?\s*"([a-z0-9_.]+)"', re.S)
_SPAN_CTX = re.compile(r'\.(?:span|child)\(\s*"([a-z0-9_.]+)"')

#: dynamic registration sites: (file, metric prefix, regex whose group 1
#: holds the key set as quoted strings)
_DYNAMIC = [
    ("serving/client.py", "client.",
     re.compile(r"CONN_STATS_KEYS = \(([^)]*)\)", re.S)),
    ("serving/server.py", "server.",
     re.compile(r"self\._counters = \{([^}]*)\}", re.S)),
    ("serving/http_frontend.py", "frontend.",
     re.compile(r"_FRONTEND_COUNTERS = \(([^)]*)\)", re.S)),
]

_KEY = re.compile(r'"([a-z0-9_]+)"')

#: catalog table rows: | `name` \| `a` / `b` | type | ...
_DOC_ROW = re.compile(r"^\|\s*(`[^|]*`)\s*\|", re.M)
_DOC_NAME = re.compile(r"`([a-z0-9_.]+)`")


def code_metrics() -> set:
    names: set = set()
    for py in sorted(PKG.rglob("*.py")):
        text = py.read_text()
        names.update(_LITERAL.findall(text))
    for rel, prefix, pattern in _DYNAMIC:
        text = (PKG / rel).read_text()
        m = pattern.search(text)
        if not m:
            print(f"check_metric_docs: dynamic-site pattern for {rel} "
                  f"no longer matches — update _DYNAMIC", file=sys.stderr)
            sys.exit(2)
        names.update(prefix + k for k in _KEY.findall(m.group(1)))
    # "client." + key literals are covered by _DYNAMIC; a bare prefix
    # fragment like "client." itself is not a series
    return {n for n in names if not n.endswith(".")}


def code_spans() -> set:
    names: set = set()
    for py in sorted(PKG.rglob("*.py")):
        text = py.read_text()
        names.update(_SPAN_RECORD.findall(text))
        names.update(_SPAN_CTX.findall(text))
    return names


def _doc_section(heading: str) -> str:
    text = DOC.read_text()
    m = re.search(rf"\n(#{{2,3}}) {re.escape(heading)}\n", text)
    if m is None:
        print(f"check_metric_docs: docs/observability.md has no "
              f"'{heading}' section", file=sys.stderr)
        sys.exit(2)
    body = text[m.end():]
    # the section runs until the next heading of the same-or-higher level
    nxt = re.search(rf"\n#{{2,{len(m.group(1))}}} ", body)
    return body if nxt is None else body[:nxt.start()]


def documented(heading: str) -> set:
    names: set = set()
    for cell in _DOC_ROW.findall(_doc_section(heading)):
        names.update(_DOC_NAME.findall(cell))
    return names


def _diff(kind: str, code: set, docs: set) -> bool:
    undocumented = sorted(code - docs)
    stale = sorted(docs - code)
    if undocumented:
        print(f"{kind} in code but MISSING from the docs/observability.md "
              "catalog:")
        for n in undocumented:
            print(f"  - {n}")
    if stale:
        print(f"{kind} documented in docs/observability.md but no longer "
              "in analytics_zoo_tpu/:")
        for n in stale:
            print(f"  - {n}")
    return bool(undocumented or stale)


def main() -> int:
    bad = _diff("metrics", code_metrics(), documented("Metric catalog"))
    bad = _diff("span names", code_spans(),
                documented("Span catalog")) or bad
    if bad:
        return 1
    print(f"metric catalog in sync: {len(code_metrics())} series; "
          f"span catalog in sync: {len(code_spans())} names")
    return 0


if __name__ == "__main__":
    sys.exit(main())
