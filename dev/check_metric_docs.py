#!/usr/bin/env python
"""CI guard: the metric catalog in docs/observability.md matches the code.

The catalog drifted risk-free through four PRs — nothing failed when a
new series was registered but never documented, or a documented series
was renamed away.  This checker closes the loop without importing (or
running) anything:

- **code side**: every metric name registered through the
  ``core/metrics.py`` registry is found by scanning ``analytics_zoo_tpu``
  sources for ``counter("...")`` / ``gauge("...")`` /
  ``histogram("...")`` / ``inc("...")`` / ``observe("...")`` /
  ``set_gauge("...")`` string literals, PLUS the three known dynamic
  registration sites (``"client." + key`` over the client's stats dict,
  ``"server." + k`` over the server's counters dict, ``"frontend." +
  key`` over ``_FRONTEND_COUNTERS``) whose key sets are extracted from
  the same files;
- **docs side**: the first column of the catalog table (rows starting
  with ``| `` + a backtick), splitting ``a / b`` cells.

Exit 1 (with a readable diff) when the code registers a series the
catalog doesn't document, or the catalog documents a series no code
registers.  Wired into the test suite
(``tests/test_observability.py::test_metric_catalog_matches_code``).
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "analytics_zoo_tpu"
DOC = REPO / "docs" / "observability.md"

#: registry write/handle calls whose first argument is the series name
_LITERAL = re.compile(
    r'\.(?:counter|gauge|histogram|inc|observe|set_gauge)\(\s*'
    r'"([a-z0-9_.]+)"')

#: dynamic registration sites: (file, metric prefix, regex whose group 1
#: holds the key set as quoted strings)
_DYNAMIC = [
    ("serving/client.py", "client.",
     re.compile(r"CONN_STATS_KEYS = \(([^)]*)\)", re.S)),
    ("serving/server.py", "server.",
     re.compile(r"self\._counters = \{([^}]*)\}", re.S)),
    ("serving/http_frontend.py", "frontend.",
     re.compile(r"_FRONTEND_COUNTERS = \(([^)]*)\)", re.S)),
]

_KEY = re.compile(r'"([a-z0-9_]+)"')

#: catalog table rows: | `name` \| `a` / `b` | type | ...
_DOC_ROW = re.compile(r"^\|\s*(`[^|]*`)\s*\|", re.M)
_DOC_NAME = re.compile(r"`([a-z0-9_.]+)`")


def code_metrics() -> set:
    names: set = set()
    for py in sorted(PKG.rglob("*.py")):
        text = py.read_text()
        names.update(_LITERAL.findall(text))
    for rel, prefix, pattern in _DYNAMIC:
        text = (PKG / rel).read_text()
        m = pattern.search(text)
        if not m:
            print(f"check_metric_docs: dynamic-site pattern for {rel} "
                  f"no longer matches — update _DYNAMIC", file=sys.stderr)
            sys.exit(2)
        names.update(prefix + k for k in _KEY.findall(m.group(1)))
    # "client." + key literals are covered by _DYNAMIC; a bare prefix
    # fragment like "client." itself is not a series
    return {n for n in names if not n.endswith(".")}


def documented_metrics() -> set:
    names: set = set()
    for cell in _DOC_ROW.findall(DOC.read_text()):
        names.update(_DOC_NAME.findall(cell))
    return names


def main() -> int:
    code = code_metrics()
    docs = documented_metrics()
    undocumented = sorted(code - docs)
    stale = sorted(docs - code)
    if undocumented:
        print("metrics registered in code but MISSING from the "
              "docs/observability.md catalog:")
        for n in undocumented:
            print(f"  - {n}")
    if stale:
        print("metrics documented in docs/observability.md but no longer "
              "registered anywhere in analytics_zoo_tpu/:")
        for n in stale:
            print(f"  - {n}")
    if undocumented or stale:
        return 1
    print(f"metric catalog in sync: {len(code)} series")
    return 0


if __name__ == "__main__":
    sys.exit(main())
