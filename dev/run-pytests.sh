#!/usr/bin/env bash
# Sharded test runner (reference pattern: pyzoo/dev/run-pytests*.sh —
# separate pytest processes per shard).  See tests/run.py.
set -u
cd "$(dirname "$0")/.."
exec python -m tests.run "$@"
