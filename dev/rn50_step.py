"""Fast RN50 resident-step timer for perf iteration (dev tool).

Mirrors bench.py's resnet50 resident phase exactly (same model, batch,
space-to-depth stem, uint8 normalize-on-device) but skips streaming /
host-feed phases, so one A/B costs ~60s instead of minutes.  Knobs via
env so two variants can run back-to-back in one tunnel window:

  RN50_BATCH=128     per-chip batch
  RN50_STEPS=20      steps per timed scan
  RN50_REPEATS=5     timed repeats (prints each; best is the signal)
  RN50_VARIANT=...   free-form tag echoed in the output line
  RN50_STEM=space_to_depth|conv
  RN50_NORM=bn|nf    bn (default) = classic exact-BN ResNet-50;
                     nf = normalizer-free (ScaledWSConv + SkipInit)

Usage: python dev/rn50_step.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.data import as_feed
    from analytics_zoo_tpu.models import ResNet
    from analytics_zoo_tpu.orca.learn import Estimator

    size, classes = 224, 1000
    batch = int(os.environ.get("RN50_BATCH", "128"))
    steps = int(os.environ.get("RN50_STEPS", "20"))
    repeats = int(os.environ.get("RN50_REPEATS", "5"))
    variant = os.environ.get("RN50_VARIANT", "base")
    stem = os.environ.get("RN50_STEM", "space_to_depth")
    norm = os.environ.get("RN50_NORM", "bn")

    class TrainNet(nn.Module):
        def __init__(self):
            super().__init__()
            kw = {}
            if norm != "bn":
                kw["norm"] = norm
            self.net = ResNet(depth=50, class_num=classes,
                              dtype="bfloat16", stem=stem, **kw)

        def forward(self, scope, x):
            x = (x.astype(jnp.bfloat16) - 127.0) * (1.0 / 64.0)
            return scope.child(self.net, x, name="resnet")

    mesh = init_orca_context("local")
    n_chips = jax.device_count()
    global_batch = batch * n_chips

    rng = np.random.default_rng(0)
    imgs = rng.integers(0, 256, (global_batch, size, size, 3),
                        dtype=np.uint8)
    labels = rng.integers(0, classes, global_batch).astype(np.int32)

    est = Estimator.from_keras(TrainNet(),
                               loss="sparse_categorical_crossentropy",
                               optimizer="sgd", learning_rate=0.1)
    b0 = next(as_feed((imgs, labels), global_batch, shuffle=False)
              .epoch(mesh, 0))
    est._ensure_initialized(b0["x"])

    est._ts, warm = est._multi_step(est._ts, b0, steps)
    _ = float(warm[-1])

    dts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        est._ts, losses = est._multi_step(est._ts, b0, steps)
        _ = float(losses[-1])
        dts.append((time.perf_counter() - t0) / steps)
    best = min(dts)
    ips = global_batch / best
    # canonical fwd estimate; MFU here is for RELATIVE comparison only
    mfu = ips * 3 * 8.023e9 / (197e12 * n_chips)
    print(f"[{variant}] step_ms={[round(1e3 * d, 2) for d in dts]} "
          f"best={1e3 * best:.2f}ms ips={ips:.0f} mfu~{mfu:.4f}")


if __name__ == "__main__":
    main()
