"""Fast BERT resident-step timer for perf iteration (dev tool).

Mirrors bench.py's bert resident phase (same encoder, grad_accum, MFU
math imported from bench) without the streaming phase.  Defaults match
the benchmarked config (micro 4 x accum 8, remat attention).  Knobs:

  BERT_BATCH=4      per-chip micro batch
  BERT_ACCUM=8      grad accumulation (global batch = batch*accum)
  BERT_STEPS=50     steps per timed scan
  BERT_REPEATS=5    timed repeats
  BERT_FLASH=0|1    flash-attention kernel in the training path
  BERT_REMAT=1|0    rematerialized dense attention (the bench default;
                    mutually exclusive with BERT_FLASH=1)
  BERT_SEQ=512      sequence length (long-context: 2048/4096 with
                    BERT_FLASH=1 — the flash kernel's regime)
  BERT_VARIANT=tag  echoed in the output line
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.data import as_feed
    from analytics_zoo_tpu.orca.learn import Estimator

    d_model, n_heads, n_layers, vocab = 768, 12, 12, 30522
    seq = int(os.environ.get("BERT_SEQ", "512"))
    batch = int(os.environ.get("BERT_BATCH", "4"))
    accum = int(os.environ.get("BERT_ACCUM", "8"))
    steps = int(os.environ.get("BERT_STEPS", "50"))
    repeats = int(os.environ.get("BERT_REPEATS", "5"))
    use_flash = os.environ.get("BERT_FLASH", "0") == "1"
    remat = os.environ.get("BERT_REMAT", "1") == "1"
    variant = os.environ.get("BERT_VARIANT", "base")

    class Encoder(nn.Module):
        def forward(self, scope, ids):
            x = scope.child(nn.Embedding(vocab, d_model), ids, name="tok")
            pos = scope.param("pos", nn.initializers.get("normal"),
                              (1, ids.shape[1], d_model))
            x = (x + pos).astype(jnp.bfloat16)
            for i in range(n_layers):
                x = scope.child(
                    nn.TransformerLayer(n_heads, use_flash=use_flash,
                                        remat_attention=remat),
                    x, name=f"block{i}")
            return scope.child(nn.Dense(vocab), x, name="head")

    mesh = init_orca_context("local")
    n_chips = jax.device_count()
    global_batch = batch * accum * n_chips

    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (global_batch, seq))
    labels = rng.integers(0, vocab, (global_batch, seq))
    est = Estimator.from_keras(Encoder(),
                               loss="sparse_categorical_crossentropy",
                               optimizer="adamw", learning_rate=1e-4,
                               grad_accum=accum)
    b0 = next(as_feed((ids, labels), global_batch, shuffle=False)
              .epoch(mesh, 0))
    est._ensure_initialized(b0["x"])
    est._ts, warm = est._multi_step(est._ts, b0, steps)
    _ = float(warm[-1])

    dts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        est._ts, losses = est._multi_step(est._ts, b0, steps)
        _ = float(losses[-1])
        dts.append((time.perf_counter() - t0) / steps)
    best = min(dts)
    tps = global_batch * seq / best
    from bench import flops_per_token, peak_flops_per_chip
    fpt = flops_per_token(d_model, n_layers, seq, vocab)
    mfu = tps * fpt / (peak_flops_per_chip() * n_chips)
    print(f"[{variant}] step_ms={[round(1e3 * d, 2) for d in dts]} "
          f"best={1e3 * best:.2f}ms tok/s={tps:.0f} mfu={mfu:.4f}")


if __name__ == "__main__":
    main()
