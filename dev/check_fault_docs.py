#!/usr/bin/env python
"""CI guard: the fault-injection point table in docs/robustness.md
matches the code (sibling of check_metric_docs.py).

ISSUE 14 found the table had ALREADY drifted — ``batch.shard_fail``
shipped in the batch-scoring PR without a row — and the chaos harness
multiplies the cost of drift: a storm author picks points from the
documented table, and an undocumented point is a storm nobody writes.
This closes the loop the same way the metric/span catalogs are closed,
without importing (or running) anything:

- **code side**: the ``KNOWN_POINTS = {...}`` set literal in
  ``core/faults.py``, PLUS every ``register_point("...")`` call site
  across ``analytics_zoo_tpu/`` (subsystems grown later register their
  points at import time; both spellings are first-class).  Points
  registered dynamically from a variable are invisible to this guard —
  the point vocabulary is closed by design, so don't.
- **docs side**: the first column of the "## Injection points" table in
  docs/robustness.md (rows starting with ``| `` + a backtick).

Exit 1 with a readable diff when they disagree in either direction.
Wired into the test suite
(``tests/test_chaos.py::test_fault_point_docs_match_code``).
"""

from __future__ import annotations

import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "analytics_zoo_tpu"
DOC = REPO / "docs" / "robustness.md"

#: the KNOWN_POINTS set literal (module level, core/faults.py)
_KNOWN_BLOCK = re.compile(r"^KNOWN_POINTS = \{([^}]*)\}", re.M | re.S)
#: register_point("name") call sites — not the def itself
_REGISTER = re.compile(r'register_point\(\s*"([a-z0-9_.]+)"')
_NAME = re.compile(r'"([a-z0-9_.]+)"')

#: table rows: | `point` | seam ... |
_DOC_ROW = re.compile(r"^\|\s*`([a-z0-9_.]+)`\s*\|", re.M)


def code_points() -> set:
    text = (PKG / "core" / "faults.py").read_text()
    m = _KNOWN_BLOCK.search(text)
    if m is None:
        print("check_fault_docs: KNOWN_POINTS literal not found in "
              "core/faults.py — update _KNOWN_BLOCK", file=sys.stderr)
        sys.exit(2)
    points = set(_NAME.findall(m.group(1)))
    for py in sorted(PKG.rglob("*.py")):
        points.update(_REGISTER.findall(py.read_text()))
    return points


def documented() -> set:
    text = DOC.read_text()
    m = re.search(r"\n## Injection points\n", text)
    if m is None:
        print("check_fault_docs: docs/robustness.md has no "
              "'## Injection points' section", file=sys.stderr)
        sys.exit(2)
    body = text[m.end():]
    nxt = re.search(r"\n## ", body)
    if nxt is not None:
        body = body[:nxt.start()]
    return set(_DOC_ROW.findall(body))


def main() -> int:
    code = code_points()
    docs = documented()
    undocumented = sorted(code - docs)
    stale = sorted(docs - code)
    if undocumented:
        print("fault points in code but MISSING from the "
              "docs/robustness.md injection-point table:")
        for n in undocumented:
            print(f"  - {n}")
    if stale:
        print("fault points documented in docs/robustness.md but not "
              "in KNOWN_POINTS or any register_point() call:")
        for n in stale:
            print(f"  - {n}")
    if undocumented or stale:
        return 1
    print(f"fault-point table in sync: {len(code)} points")
    return 0


if __name__ == "__main__":
    sys.exit(main())
