"""Offline batch scoring (ISSUE 13): the BatchScorer job engine —
journaled resumable shards through the ReplicaSet as klass="batch"
traffic, shadow validation against a pinned candidate version, and
zero-downtime promotion via ModelRegistry.promote().

Resilience coverage: shard-level fault injection (``batch.shard_fail``),
a HARD client kill (SIGKILL of a zoo-score subprocess mid-job) followed
by resume, a replica hard-kill mid-job, and crc rejection of corrupted
shard bytes — in every case the concatenated output must be row-for-row
identical to an uninterrupted run (zero lost, zero duplicated rows).

The ≥50k-row acceptance run (replica kill + client crash + resume +
concurrent-interactive p99 guard) is ``slow``-marked.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.core.faults import FaultRegistry, get_registry
from analytics_zoo_tpu.serving import (BatchJobError, BatchScorer,
                                       ClusterServing, ModelRegistry,
                                       ReplicaSet, read_output)
from analytics_zoo_tpu.serving.batch import _read_journal
from analytics_zoo_tpu.serving.client import RetryPolicy

pytestmark = pytest.mark.faults

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _Model:
    """Multiplies by k; optional per-batch delay to stretch jobs."""

    def __init__(self, k: float = 2.0, delay: float = 0.0):
        self.k = k
        self.delay = delay

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(x) * self.k


def _fast_retry(**kw) -> RetryPolicy:
    kw.setdefault("max_attempts", 3)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.1)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


def _serve(model=None, faults=None, port=0, **kw) -> ClusterServing:
    kw.setdefault("batch_size", 8)
    kw.setdefault("batch_timeout_ms", 2)
    return ClusterServing(model or _Model(), port=port, faults=faults,
                          **kw).start()


def _rows(n, d=4, seed=0):
    return np.random.default_rng(seed).normal(
        size=(n, d)).astype(np.float32)


# -- the basic job ------------------------------------------------------------

def test_job_row_exact_through_two_replicas(tmp_path):
    """203 rows / shard 50 through a 2-replica pool: the journaled
    output is row-for-row the model's answer, the journal carries a
    verifiable crc per shard, and the batch.* counters add up."""
    rows = _rows(203)
    with _serve() as s1, _serve() as s2:
        rs = ReplicaSet([(s1.host, s1.port), (s2.host, s2.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=50,
                         max_inflight=8, retry=_fast_retry()) as sc:
            rep = sc.score(rows)
        rs.close()
    assert (rep.rows, rep.n_shards, rep.scored_shards) == (203, 5, 5)
    assert rep.resumed_shards == 0 and rep.promoted is None
    np.testing.assert_allclose(rep.output(), rows * 2.0, rtol=1e-6)
    entries = _read_journal(str(tmp_path / "job"))
    assert sorted(e["shard"] for e in entries) == list(range(5))
    # every journal entry's crc matches the bytes on disk
    from analytics_zoo_tpu.serving.batch import _crc32_file
    for e in entries:
        assert _crc32_file(str(tmp_path / "job" / e["file"])) \
            == e["crc32"]
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("batch.rows") == 203
    assert snap["batch.inflight"]["value"] == 0  # window fully drained


def test_read_output_names_missing_shards(tmp_path):
    rows = _rows(100)
    with _serve() as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=25,
                         retry=_fast_retry()) as sc:
            sc.score(rows)
        rs.close()
    # drop shard 1's journal line: the gap must be named, not glossed
    jpath = tmp_path / "job" / "journal.jsonl"
    lines = [l for l in jpath.read_text().splitlines()
             if json.loads(l)["shard"] != 1]
    jpath.write_text("\n".join(lines) + "\n")
    with pytest.raises(BatchJobError, match=r"missing shard\(s\) \[1\]"):
        read_output(str(tmp_path / "job"))


def test_shard_fail_injection_retries_and_recovers(tmp_path):
    rows = _rows(160)
    with _serve() as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=40,
                         retry=_fast_retry()) as sc:
            with get_registry().armed("batch.shard_fail", times=2):
                rep = sc.score(rows)
        rs.close()
    assert rep.retries == 2
    np.testing.assert_allclose(rep.output(), rows * 2.0, rtol=1e-6)
    assert metrics_lib.get_registry().snapshot().get("batch.retries") == 2


# -- crash + resume -----------------------------------------------------------

def test_abort_dumps_flight_record_then_resume_is_row_identical(
        tmp_path, monkeypatch):
    """Retries exhausted mid-job → BatchJobError + a ``batch_abort``
    flight record; a resume skips the journaled prefix and the final
    output equals an UNINTERRUPTED run of the same job, row for row."""
    monkeypatch.setenv("ZOO_FLIGHTREC_DIR", str(tmp_path / "rec"))
    rows = _rows(200)
    with _serve() as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        # the uninterrupted reference run
        with BatchScorer(rs, str(tmp_path / "ref"), shard_size=40,
                         retry=_fast_retry()) as ref_sc:
            want = ref_sc.score(rows).output()
        sc = BatchScorer(rs, str(tmp_path / "job"), shard_size=40,
                         retry=_fast_retry())
        with get_registry().armed("batch.shard_fail", times=100,
                                  after=2):
            with pytest.raises(BatchJobError, match="shard 2"):
                sc.score(rows)
        dumps = os.listdir(tmp_path / "rec")
        assert any(f.startswith("flightrec") for f in dumps), dumps
        rec = json.load(open(tmp_path / "rec" / sorted(dumps)[0]))
        assert rec["reason"] == "batch_abort"

        rep = sc.score(rows, resume=True)
        sc.close()
        rs.close()
    assert rep.resumed_shards == 2 and rep.scored_shards == 3
    got = rep.output()
    assert got.shape == want.shape
    np.testing.assert_array_equal(got, want)  # row-for-row identical
    assert metrics_lib.get_registry().snapshot().get(
        "batch.resumed_shards") == 2


def test_hard_client_kill_then_resume_is_row_identical(tmp_path):
    """THE client-crash leg: a zoo-score subprocess is SIGKILLed
    mid-job; resuming the same job directory in-process re-scores only
    the unjournaled tail and the output matches an uninterrupted run
    row for row — zero lost, zero duplicated."""
    rows = _rows(400)
    np.save(tmp_path / "rows.npy", rows)
    model = _Model(delay=0.02)  # stretch the job so the kill lands mid-way
    with _serve(model) as srv:
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "analytics_zoo_tpu.serving.batch",
             "--backend", f"{srv.host}:{srv.port}",
             "--input", str(tmp_path / "rows.npy"),
             "--out", str(tmp_path / "job"), "--shard-size", "40",
             "--max-inflight", "4"],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        try:
            # wait for a partial journal (some, not all, of 10 shards)
            deadline = time.monotonic() + 120
            while True:
                n_done = len(_read_journal(str(tmp_path / "job")))
                if 1 <= n_done <= 8:
                    break
                assert proc.poll() is None, \
                    "job finished before the kill landed — slow it down"
                assert time.monotonic() < deadline
                time.sleep(0.02)
            os.kill(proc.pid, signal.SIGKILL)
        finally:
            proc.wait(timeout=30)
        model.delay = 0.0  # the resume leg can run at full speed
        rs = ReplicaSet([(srv.host, srv.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=40,
                         max_inflight=4, retry=_fast_retry()) as sc:
            rep = sc.score(rows, resume=True)
        rs.close()
    assert rep.resumed_shards >= 1      # the pre-kill prefix survived
    assert rep.scored_shards >= 1       # and the tail was re-scored
    assert rep.resumed_shards + rep.scored_shards == rep.n_shards == 10
    np.testing.assert_allclose(rep.output(), rows * 2.0, rtol=1e-6)


def test_resume_rejects_config_mismatch(tmp_path):
    rows = _rows(100)
    with _serve() as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=25,
                         retry=_fast_retry()) as sc:
            sc.score(rows)
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=50,
                         retry=_fast_retry()) as sc2:
            with pytest.raises(BatchJobError, match="config mismatch"):
                sc2.score(rows, resume=True)
        rs.close()


def test_resume_rescores_corrupted_shard(tmp_path):
    """Bit-rot in a journaled shard file must not be trusted: the crc
    check fails, the shard re-scores, and the output stays exact."""
    rows = _rows(120)
    with _serve() as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        sc = BatchScorer(rs, str(tmp_path / "job"), shard_size=40,
                         retry=_fast_retry())
        sc.score(rows)
        bad = tmp_path / "job" / "shard_00001.npz"
        blob = bytearray(bad.read_bytes())
        blob[len(blob) // 2] ^= 0xFF
        bad.write_bytes(bytes(blob))
        rep = sc.score(rows, resume=True)
        sc.close()
        rs.close()
    assert rep.resumed_shards == 2 and rep.scored_shards == 1
    np.testing.assert_allclose(rep.output(), rows * 2.0, rtol=1e-6)


# -- replica failure under a running job --------------------------------------

def test_replica_hard_kill_mid_job_zero_lost_rows(tmp_path):
    """2 replicas, one dies hard (``serving.replica_down``) while the
    job streams: the router fails the in-flight rows over and the job
    completes with every row scored exactly once."""
    rows = _rows(240)
    f1 = FaultRegistry()
    s1 = _serve(_Model(delay=0.005), faults=f1)
    s2 = _serve(_Model(delay=0.005))
    rs = ReplicaSet([(s1.host, s1.port), (s2.host, s2.port)],
                    retry=_fast_retry(max_attempts=4),
                    health_interval=0.08, health_timeout=0.5,
                    breaker_threshold=3, breaker_reset_s=0.2)
    try:
        sc = BatchScorer(rs, str(tmp_path / "job"), shard_size=30,
                         max_inflight=4,
                         retry=_fast_retry(max_attempts=4),
                         request_timeout=30.0)
        result = {}

        def run():
            result["report"] = sc.score(rows)

        t = threading.Thread(target=run)
        t.start()
        # kill replica 1 once the job is demonstrably in flight
        deadline = time.monotonic() + 60
        while len(_read_journal(str(tmp_path / "job"))) < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        f1.enable("serving.replica_down", times=1)
        t.join(timeout=120)
        assert not t.is_alive(), "job wedged after the replica kill"
        sc.close()
    finally:
        rs.close()
        s2.stop()
        s1.stop()
    rep = result["report"]
    assert rep.rows == 240 and rep.n_shards == 8
    np.testing.assert_allclose(rep.output(), rows * 2.0, rtol=1e-6)


# -- shadow validation + promotion --------------------------------------------

def test_shadow_validation_promotes_identical_candidate(tmp_path):
    """Candidate == active → zero deltas → the gate passes and the
    candidate goes live through ModelRegistry.promote() (counted in
    registry.swaps), with interactive clients serving throughout."""
    rows = _rows(150)
    reg = ModelRegistry()
    reg.register("default", _Model(2.0))                     # v1 active
    reg.register("default", _Model(2.0), make_active=False)  # v2 shadow
    with ClusterServing(models=reg, batch_size=8,
                        batch_timeout_ms=2) as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=50,
                         retry=_fast_retry()) as sc:
            rep = sc.score(rows, shadow_version="v2",
                           promote_if=lambda d:
                               d["mismatch_rate"] == 0.0
                               and d["max_abs_delta"] < 1e-6,
                           registry=reg)
        assert rep.promoted == "v2"
        assert reg.active_version("default") == "v2"
        assert rep.deltas.rows == 150
        assert rep.deltas.max_abs_delta == 0.0
        # both versions' outputs were journaled
        np.testing.assert_allclose(
            read_output(str(tmp_path / "job"), key="y_shadow"),
            rows * 2.0, rtol=1e-6)
        # zero client-visible errors: the promoted version serves
        out = rs.predict(rows[0], deadline=10.0)
        assert out is not None
        rs.close()
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("registry.swaps") == 1


def test_shadow_validation_gate_rejects_drifted_candidate(tmp_path):
    """Candidate with different math → nonzero deltas → the gate holds
    and the active version stays put."""
    rows = _rows(120)
    reg = ModelRegistry()
    reg.register("default", _Model(2.0))
    reg.register("default", _Model(-2.0), make_active=False)  # drifted
    with ClusterServing(models=reg, batch_size=8,
                        batch_timeout_ms=2) as srv:
        rs = ReplicaSet([(srv.host, srv.port)])
        with BatchScorer(rs, str(tmp_path / "job"), shard_size=60,
                         retry=_fast_retry()) as sc:
            rep = sc.score(rows, shadow_version="v2",
                           promote_if=lambda d:
                               d["mismatch_rate"] == 0.0,
                           registry=reg)
        rs.close()
    assert rep.promoted is None
    assert reg.active_version("default") == "v1"
    assert rep.deltas.mismatch_rate > 0.0
    assert rep.deltas.max_abs_delta > 0.0


def test_promote_requires_loaded_version_and_is_idempotent():
    reg = ModelRegistry()
    reg.register("m", _Model(1.0), version="a")
    reg.register("m", _Model(1.0), version="b", make_active=False)
    with pytest.raises(KeyError):
        reg.promote("m", "zzz")
    assert reg.promote("m", "b") == "b"
    assert reg.active_version("m") == "b"
    # promoting the active version is a no-op (no extra swap counted)
    before = metrics_lib.get_registry().snapshot().get("registry.swaps")
    assert reg.promote("m", "b") == "b"
    assert metrics_lib.get_registry().snapshot().get(
        "registry.swaps") == before


# -- THE acceptance (slow) ----------------------------------------------------

@pytest.mark.slow
def test_acceptance_50k_job_survives_kill_and_crash_with_p99_guard(
        tmp_path):
    """ISSUE 13 acceptance: a 50k-row job through a 2-replica pool
    survives a mid-job replica hard-kill AND a client crash+resume with
    zero lost/duplicated rows, while concurrent interactive p99 stays
    within 1.5x its batch-free baseline (per-class admission)."""
    rows = _rows(50_000, d=4)
    f1 = FaultRegistry()
    s1 = _serve(_Model(), faults=f1)
    s2 = _serve(_Model())
    ports = (s1.port, s2.port)
    rs = ReplicaSet([(s1.host, p) for p in ports],
                    retry=_fast_retry(max_attempts=4),
                    health_interval=0.08, health_timeout=0.5,
                    breaker_threshold=3, breaker_reset_s=0.2)
    x1 = rows[0]

    def p99_of(samples):
        return float(np.percentile(np.asarray(samples), 99))

    def interactive(n, out):
        for _ in range(n):
            t0 = time.monotonic()
            r = rs.predict(x1, deadline=15.0, klass="interactive")
            assert r is not None
            out.append((time.monotonic() - t0) * 1000.0)

    try:
        # batch-free interactive baseline
        base = []
        interactive(300, base)
        baseline_p99 = p99_of(base)

        sc = BatchScorer(rs, str(tmp_path / "job"), shard_size=1000,
                         max_inflight=4,
                         retry=_fast_retry(max_attempts=4))
        state = {}
        lat = []
        stop = threading.Event()

        def closed_loop():
            while not stop.is_set():
                t0 = time.monotonic()
                r = rs.predict(x1, deadline=15.0, klass="interactive")
                assert r is not None
                lat.append((time.monotonic() - t0) * 1000.0)

        def run_job():
            try:
                sc.score(rows)
            except BatchJobError as e:
                state["abort"] = e  # the scripted client crash

        loader = threading.Thread(target=closed_loop)
        job = threading.Thread(target=run_job)
        loader.start()
        job.start()
        # phase 1: replica hard-kill once the job is under way
        deadline = time.monotonic() + 300
        while len(_read_journal(str(tmp_path / "job"))) < 5:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        f1.enable("serving.replica_down", times=1)
        # phase 2: scripted client crash a few shards later
        while len(_read_journal(str(tmp_path / "job"))) < 20:
            assert time.monotonic() < deadline
            time.sleep(0.05)
        get_registry().enable("batch.shard_fail", times=100)
        job.join(timeout=300)
        assert not job.is_alive()
        get_registry().disable("batch.shard_fail")
        assert isinstance(state.get("abort"), BatchJobError)
        # resume to completion (one replica may still be down — fine)
        rep = sc.score(rows, resume=True)
        stop.set()
        loader.join(timeout=60)
        sc.close()
    finally:
        rs.close()
        s2.stop()
        s1.stop()
    assert rep.resumed_shards >= 20
    assert rep.resumed_shards + rep.scored_shards == rep.n_shards == 50
    out = rep.output()
    assert out.shape == rows.shape  # zero lost / duplicated rows
    np.testing.assert_allclose(out, rows * 2.0, rtol=1e-6)
    assert lat, "no interactive samples under batch load"
    assert p99_of(lat) <= 1.5 * max(baseline_p99, 5.0), \
        (p99_of(lat), baseline_p99)
