"""Real 2-process jax.distributed tests (SURVEY.md §3.1/§5.4).

The reference validated its distributed layer on clusters-in-a-box
(local-cluster Spark + Ray in tests, SURVEY.md §4.3).  The analog here:
two OS processes, each a jax.distributed participant with 2 virtual CPU
devices, coordinated over localhost — exercising init, per-process data,
cross-process fsdp sharding, global metrics, and the per-host sharded
checkpoint, none of which a single-process test can reach."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


import pytest


@pytest.mark.parametrize("nprocs,devices_per_proc", [(2, 2), (4, 1)])
def test_multiprocess_fit_eval_sharded_checkpoint(tmp_path, nprocs,
                                                  devices_per_proc):
    import jax
    if jax.default_backend() == "cpu":
        pytest.skip("Multiprocess computations aren't implemented on the "
                    "CPU backend (jax restriction); needs a TPU/GPU run")
    from analytics_zoo_tpu.core.launcher import _child_env, _free_port

    coordinator = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nprocs):
        env = _child_env(coordinator, nprocs, pid,
                         devices_per_proc=devices_per_proc,
                         platform="cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        procs.append(subprocess.Popen(
            [sys.executable, WORKER, str(tmp_path / "ckpt")],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=420)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        assert "MULTIHOST_OK" in out, out[-3000:]
    # global (not host-local) metrics: every process prints the same loss
    lines = [next(l for l in out.splitlines() if "MULTIHOST_OK" in l)
             for out in outs]
    assert len(set(lines)) == 1, lines
    # per-host sharded layout on disk: one shard file per process
    ckpt = tmp_path / "ckpt"
    names = sorted(p.name for p in ckpt.iterdir())
    for pid in range(nprocs):
        assert any(n.startswith("shards_") and n.endswith(f"_p{pid}.npz")
                   for n in names), (pid, names)


def test_zoo_launch_cli(tmp_path):
    """The zoo-launch console entry point end-to-end (simulation mode)."""
    script = tmp_path / "job.py"
    script.write_text(
        "import jax\n"
        "from analytics_zoo_tpu.core import init_orca_context\n"
        "init_orca_context('multihost', mesh_shape={'data': 0})\n"
        "print(f'LAUNCH_OK {jax.process_index()}/{jax.process_count()} "
        "{jax.device_count()}')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "analytics_zoo_tpu.core.launcher",
         "--nprocs", "2", "--devices-per-proc", "2", "--platform", "cpu",
         str(script)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "LAUNCH_OK 0/2 4" in proc.stdout
    assert "LAUNCH_OK 1/2 4" in proc.stdout
