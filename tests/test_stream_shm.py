"""Streaming-input rebuild tests (ISSUE 7): shared-memory slot pool
lifecycle, process decode backend (ordering / identity / crash
resilience / fallback), device-side augmentation, the PrefetchIterator
place hook, and the uint8→device loss-parity acceptance criterion."""

import glob
import os
import time

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import get_mesh, init_orca_context
from analytics_zoo_tpu.data import (DeviceAugment, DeviceNormalize,
                                    DeviceRandomCrop, DeviceRandomFlip,
                                    PrefetchIterator, ShmBatchPool,
                                    SlotBatch, StreamingDataFeed)
from analytics_zoo_tpu.data import shm_pool
from analytics_zoo_tpu.data.image import ImageNormalize
from analytics_zoo_tpu.orca.learn import Estimator

needs_process = pytest.mark.skipif(
    not shm_pool.available(),
    reason="multiprocessing.shared_memory / fork unavailable")


def _mesh():
    return init_orca_context("local")


def _shm_leaks():
    return glob.glob(f"/dev/shm/{shm_pool.SHM_PREFIX}*")


def _det_load(i, rng=None):
    """Deterministic from the index (what a decode is), rng-free."""
    r = np.random.default_rng(i)
    return {"x": r.normal(size=(3,)).astype(np.float32),
            "y": np.int32(i % 5)}


# -- pool lifecycle -----------------------------------------------------------

class TestShmPool:
    def test_roundtrip_and_views_shared(self):
        pool = ShmBatchPool(2, 4, {"x": ((3,), np.float32),
                                   "y": ((), np.int32)})
        try:
            s = pool.acquire(timeout=1)
            v = pool.views(s)
            v["x"][:] = 7.0
            v["y"][:] = np.arange(4)
            again = pool.views(s)
            np.testing.assert_array_equal(again["x"], np.full((4, 3), 7.0))
            np.testing.assert_array_equal(again["y"], np.arange(4))
            pool.release(s)
            assert pool.acquire(timeout=1) is not None
        finally:
            pool.close()

    def test_acquire_blocks_at_capacity(self):
        pool = ShmBatchPool(2, 2, {"x": ((2,), np.float32)})
        try:
            a = pool.acquire(timeout=1)
            b = pool.acquire(timeout=1)
            assert a is not None and b is not None
            assert pool.acquire(timeout=0.1) is None  # the memory bound
            pool.release(a)
            assert pool.acquire(timeout=1) == a
        finally:
            pool.close()

    def test_close_unlinks_every_segment(self):
        assert not _shm_leaks()
        pool = ShmBatchPool(3, 4, {"x": ((8,), np.uint8)})
        assert len(_shm_leaks()) == 3
        pool.close()
        assert not _shm_leaks()
        pool.close()  # idempotent

    def test_slot_batch_release_idempotent_and_on_gc(self):
        pool = ShmBatchPool(2, 2, {"x": ((2,), np.float32)})
        try:
            s = pool.acquire(timeout=1)
            sb = SlotBatch(pool.views(s), s, pool)
            sb.release()
            sb.release()  # idempotent: slot must not enter the pool twice
            assert pool.acquire(timeout=1) is not None
            assert pool.acquire(timeout=1) is not None
            assert pool.acquire(timeout=0.1) is None
            # GC safety net: dropping an unreleased batch frees its slot
            pool2 = ShmBatchPool(2, 2, {"x": ((2,), np.float32)})
            try:
                s2 = pool2.acquire(timeout=1)
                SlotBatch(pool2.views(s2), s2, pool2)  # dropped immediately
                assert pool2.acquire(timeout=1) is not None
            finally:
                pool2.close()
        finally:
            pool.close()


# -- process backend ----------------------------------------------------------

@needs_process
class TestProcessBackend:
    def test_bitwise_identical_to_thread_backend(self):
        mesh = _mesh()
        kw = dict(batch_size=4, shuffle=True, seed=11, num_workers=2)
        ft = StreamingDataFeed(24, _det_load, workers="thread", **kw)
        fp = StreamingDataFeed(24, _det_load, workers="process", **kw)
        bt = [{k: np.asarray(v) for k, v in b.items()}
              for b in ft.epoch(mesh, 0)]
        bp = [{k: np.asarray(v) for k, v in b.items()}
              for b in fp.epoch(mesh, 0)]
        assert len(bt) == len(bp) == 6
        for a, b in zip(bt, bp):
            np.testing.assert_array_equal(a["x"], b["x"])
            np.testing.assert_array_equal(a["y"], b["y"])
        assert not _shm_leaks()

    def test_step_order_survives_straggler_decodes(self):
        mesh = _mesh()

        def slow_early(i, rng=None):
            if i < 4:
                time.sleep(0.05)  # first batch decodes LAST
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(16, slow_early, batch_size=4,
                                 shuffle=False, num_workers=3,
                                 workers="process")
        rows = [np.asarray(b["x"])[:, 0] for b in feed.epoch(mesh, 0)]
        flat = [float(v) for batch in rows for v in batch]
        assert flat == [float(i) for i in range(16)]  # strict step order

    def test_worker_crash_mid_write_releases_slot(self):
        mesh = _mesh()
        main_pid = os.getpid()

        def killer(i, rng=None):
            if i == 6 and os.getpid() != main_pid:
                os._exit(3)  # hard death while its slot is checked out
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(32, killer, batch_size=4, shuffle=False,
                                 num_workers=2, workers="process")
        with pytest.raises(RuntimeError, match="died"):
            list(feed.epoch(mesh, 0))
        # the crashed worker's half-written slot was reclaimed and every
        # segment unlinked — nothing left in /dev/shm
        assert not _shm_leaks()

    def test_abandoned_epoch_unlinks_segments(self):
        mesh = _mesh()
        feed = StreamingDataFeed(64, _det_load, batch_size=4,
                                 shuffle=False, num_workers=2,
                                 workers="process")
        it = feed.epoch(mesh, 0)
        next(it)
        assert _shm_leaks()    # pool is live mid-epoch
        it.close()
        assert not _shm_leaks()

    def test_thread_fallback_when_shm_unavailable(self, monkeypatch,
                                                  caplog):
        monkeypatch.setattr(shm_pool, "available", lambda: False)
        feed = StreamingDataFeed(8, _det_load, batch_size=4,
                                 shuffle=False, workers="process")
        assert feed.workers == "thread"
        mesh = _mesh()
        assert len(list(feed.epoch(mesh, 0))) == 2

    def test_invalid_backend_rejected(self):
        with pytest.raises(ValueError, match="workers"):
            StreamingDataFeed(8, _det_load, batch_size=4, workers="actor")

    def test_host_batches_are_slot_views_and_release(self):
        mesh = _mesh()
        feed = StreamingDataFeed(16, _det_load, batch_size=4,
                                 shuffle=False, num_workers=2,
                                 workers="process")
        seen = []
        for b in feed.epoch(mesh, 0, place=False):
            assert isinstance(b, SlotBatch)
            seen.append({k: np.asarray(v).copy() for k, v in b.items()})
            b.release()
        assert len(seen) == 4
        np.testing.assert_array_equal(
            seen[0]["x"][0], _det_load(0)["x"])
        assert not _shm_leaks()

    def test_multi_epoch_reuse_and_counter_sync(self):
        mesh = _mesh()

        def corrupt(i, rng=None):
            if i == 2:
                raise OSError("bad sample")
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(8, corrupt, batch_size=4, shuffle=False,
                                 num_workers=2, on_error="skip",
                                 workers="process")
        list(feed.epoch(mesh, 0))
        assert feed.skipped_rows == 1
        list(feed.epoch(mesh, 1))
        assert feed.skipped_rows == 2  # counters accumulate across epochs
        assert not _shm_leaks()


# -- pooled tail loading ------------------------------------------------------

class TestTailThroughWorkerPool:
    def test_remainder_values_and_parallelism(self):
        _mesh()
        calls = []

        def load(i, rng=None):
            calls.append(i)
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(10, load, batch_size=4, shuffle=False,
                                 num_workers=4)
        rem = feed.remainder()
        np.testing.assert_array_equal(rem["x"][:, 0], [8.0, 9.0])
        assert sorted(calls) == [8, 9]

    def test_dropped_rows_match_epoch_permutation(self):
        _mesh()
        feed = StreamingDataFeed(10, _det_load, batch_size=4, shuffle=True,
                                 seed=3, num_workers=4)
        sel = feed._epoch_index(0)[8:]
        dropped = feed.dropped_rows(0)
        for k, i in enumerate(sel):
            np.testing.assert_array_equal(dropped["x"][k],
                                          _det_load(int(i))["x"])


# -- device augmentation ------------------------------------------------------

class TestDeviceAugment:
    def test_normalize_matches_host_chain(self):
        _mesh()
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (4, 6, 6, 3), dtype=np.uint8)
        host = np.stack([ImageNormalize()(im) for im in imgs])
        dev = np.asarray(DeviceNormalize()(imgs, None, training=True))
        np.testing.assert_allclose(dev, host, rtol=1e-6)

    def test_flip_probabilities_and_eval_identity(self):
        import jax
        _mesh()
        x = np.arange(2 * 1 * 4 * 1, dtype=np.float32).reshape(2, 1, 4, 1)
        key = jax.random.PRNGKey(0)
        always = np.asarray(DeviceRandomFlip(1.0)(x, key, training=True))
        np.testing.assert_array_equal(always, x[:, :, ::-1, :])
        never = np.asarray(DeviceRandomFlip(0.0)(x, key, training=True))
        np.testing.assert_array_equal(never, x)
        eval_out = np.asarray(DeviceRandomFlip(1.0)(x, key, training=False))
        np.testing.assert_array_equal(eval_out, x)

    def test_random_crop_shape_and_center_eval(self):
        import jax
        _mesh()
        x = np.arange(2 * 6 * 6 * 1, dtype=np.float32).reshape(2, 6, 6, 1)
        key = jax.random.PRNGKey(1)
        out = np.asarray(DeviceRandomCrop(4, 4)(x, key, training=True))
        assert out.shape == (2, 4, 4, 1)
        center = np.asarray(DeviceRandomCrop(4, 4)(x, None, training=False))
        np.testing.assert_array_equal(center, x[:, 1:5, 1:5, :])
        with pytest.raises(ValueError, match="resize"):
            DeviceRandomCrop(8, 8)(x, key)

    def test_chain_is_deterministic_per_key_and_jittable(self):
        import jax
        _mesh()
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)
        aug = DeviceAugment([DeviceRandomCrop(6, 6), DeviceRandomFlip(),
                             DeviceNormalize()])
        key = jax.random.PRNGKey(42)
        a = np.asarray(jax.jit(lambda x, k: aug(x, k, True))(x, key))
        b = np.asarray(jax.jit(lambda x, k: aug(x, k, True))(x, key))
        np.testing.assert_array_equal(a, b)
        c = np.asarray(aug(x, jax.random.PRNGKey(43), True))
        assert a.shape == c.shape == (4, 6, 6, 3)
        assert not np.array_equal(a, c)  # different key, different draws


# -- PrefetchIterator place hook ----------------------------------------------

class TestPrefetchPlace:
    def test_place_runs_in_producer_and_retires_slots(self):
        released = []

        class FakeSlot(dict):
            def __init__(self, i):
                super().__init__(x=np.full((2,), float(i)))
                self.i = i

            def release(self):
                released.append(self.i)

        placed_order = []

        def place(b):
            placed_order.append(b.i)
            return dict(b)

        items = [FakeSlot(i) for i in range(5)]
        out = list(PrefetchIterator(iter(items), depth=2, place=place))
        assert len(out) == 5
        assert placed_order == [0, 1, 2, 3, 4]
        assert sorted(released) == [0, 1, 2, 3, 4]
        # retirement trails placement by exactly one item
        assert released[0] == 0 and released[-1] == 4

    def test_plain_items_pass_through_unreleased(self):
        out = list(PrefetchIterator(iter([{"x": 1}, {"x": 2}]), depth=2,
                                    place=lambda b: b))
        assert out == [{"x": 1}, {"x": 2}]


# -- acceptance: uint8-to-device loss parity ----------------------------------

class TestUint8DeviceAugmentParity:
    """The uint8-batch + DeviceAugment path must reach loss parity with
    the host-float32 path (same seed, rtol 1e-5) — ISSUE 7 acceptance."""

    MEAN, STD = (0.5, 0.5, 0.5), (0.25, 0.25, 0.25)

    def _build(self, augment):
        return Estimator.from_keras(
            nn.Sequential([nn.Conv2D(8, 3, activation="relu"),
                           nn.Flatten(), nn.Dense(4)]),
            loss="sparse_categorical_crossentropy", learning_rate=1e-2,
            seed=0, augment=augment)

    @pytest.mark.parametrize("backend", ["thread", "process"])
    def test_loss_parity_host_f32_vs_uint8_device(self, backend):
        if backend == "process" and not shm_pool.available():
            pytest.skip("process backend unavailable")
        _mesh()
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (64, 8, 8, 3), dtype=np.uint8)
        labels = rng.integers(0, 4, 64).astype(np.int32)
        mean = np.asarray(self.MEAN, np.float32)
        std = np.asarray(self.STD, np.float32)

        def load_f32(i, rng=None):
            return {"x": (imgs[i].astype(np.float32) / 255.0 - mean) / std,
                    "y": labels[i]}

        def load_u8(i, rng=None):
            return {"x": imgs[i], "y": labels[i]}

        host = self._build(None)
        h_hist = host.fit(
            StreamingDataFeed(64, load_f32, batch_size=16, shuffle=False,
                              num_workers=2),
            epochs=2, batch_size=16, verbose=False)
        dev = self._build(DeviceAugment([DeviceNormalize(self.MEAN,
                                                         self.STD)]))
        d_hist = dev.fit(
            StreamingDataFeed(64, load_u8, batch_size=16, shuffle=False,
                              num_workers=2, workers=backend),
            epochs=2, batch_size=16, verbose=False)
        np.testing.assert_allclose(h_hist["loss"], d_hist["loss"],
                                   rtol=1e-5)
        assert not _shm_leaks()

    def test_augmented_eval_is_deterministic(self):
        mesh = _mesh()
        rng = np.random.default_rng(0)
        imgs = rng.integers(0, 256, (32, 8, 8, 3), dtype=np.uint8)
        labels = rng.integers(0, 4, 32).astype(np.int32)
        est = self._build(DeviceAugment([DeviceRandomCrop(6, 6),
                                         DeviceRandomFlip(),
                                         DeviceNormalize()]))
        est.fit((imgs, labels), epochs=1, batch_size=16, verbose=False)
        m1 = est.evaluate((imgs, labels), batch_size=16)
        m2 = est.evaluate((imgs, labels), batch_size=16)
        assert m1 == m2  # random stages are off at eval
