"""Worker for the preemption test: trains "forever" until SIGTERM arrives,
then exits 143 after the consensus checkpoint (core/failover.py).  On a
second run with a checkpoint present, auto-resumes and prints the resumed
step."""

import sys

import numpy as np


def main() -> None:
    model_dir = sys.argv[1]
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 100000
    import jax
    jax.config.update("jax_platforms", "cpu")

    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import Preempted, init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)])
    est = Estimator.from_keras(model, loss="mse", learning_rate=1e-3,
                               model_dir=model_dir,
                               preemption_checkpoint=True,
                               preemption_sync_every=2)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    y = rng.normal(size=(256, 1)).astype(np.float32)
    print("TRAINING_STARTED", flush=True)
    try:
        est.fit((x, y), epochs=epochs, batch_size=32, auto_resume=True,
                verbose=False)
    except Preempted as e:
        print(f"PREEMPTED step={e.step} path={e.path}", flush=True)
        sys.exit(143)
    print(f"FINISHED step={est._py_step}", flush=True)


if __name__ == "__main__":
    main()
