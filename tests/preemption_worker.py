"""Worker for the preemption and gang-supervision tests.

Default mode: trains "forever" until SIGTERM arrives, then exits 143
after the consensus checkpoint (core/failover.py).  On a second run with
a checkpoint present, auto-resumes and prints the resumed step.

Gang mode (``ZOO_GANG_MODE=1``, set by the zoo-launch supervisor tests):
each worker of the gang trains independently into
``<model_dir>/w<ZOO_PROCESS_ID>`` with an every-epoch checkpoint trigger
and ``auto_resume``, and writes ``<model_dir>/done_w<pid>`` with its
final step on success.  On the FIRST attempt (``ZOO_RESTART_COUNT=0``)
the worker whose rank equals ``ZOO_TEST_FAULT_WORKER`` arms the
requested injection point:

- ``ZOO_TEST_CRASH_AFTER=K``  →  ``worker.crash`` (os._exit) at step K+1
- ``ZOO_TEST_HANG_DELAY=S`` [+ ``ZOO_TEST_HANG_AFTER=K``]  →
  ``worker.hang`` wedges one step for S seconds (heartbeats stop)

so the supervisor's crash/hang handling runs against real processes,
deterministically."""

import os
import sys

import numpy as np


def main() -> None:
    model_dir = sys.argv[1]
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 100000
    import jax
    jax.config.update("jax_platforms", "cpu")

    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import Preempted, init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator

    gang = os.environ.get("ZOO_GANG_MODE") == "1"
    pid = os.environ.get("ZOO_PROCESS_ID", "0")
    base_dir = model_dir
    if gang:
        model_dir = os.path.join(base_dir, f"w{pid}")
        if (os.environ.get("ZOO_TEST_FAULT_WORKER") == pid
                and os.environ.get("ZOO_RESTART_COUNT", "0") == "0"):
            from analytics_zoo_tpu.core import faults
            crash_after = os.environ.get("ZOO_TEST_CRASH_AFTER")
            if crash_after is not None:
                faults.get_registry().enable(
                    "worker.crash", times=1, after=int(crash_after))
            hang_delay = os.environ.get("ZOO_TEST_HANG_DELAY")
            if hang_delay is not None:
                faults.get_registry().enable(
                    "worker.hang", times=1, delay=float(hang_delay),
                    after=int(os.environ.get("ZOO_TEST_HANG_AFTER", "0")))

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)])
    est = Estimator.from_keras(model, loss="mse", learning_rate=1e-3,
                               model_dir=model_dir,
                               preemption_checkpoint=not gang,
                               preemption_sync_every=2)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 4)).astype(np.float32)
    y = rng.normal(size=(256, 1)).astype(np.float32)
    print("TRAINING_STARTED", flush=True)
    try:
        est.fit((x, y), epochs=epochs, batch_size=32, auto_resume=True,
                checkpoint_trigger="every_epoch" if gang else None,
                verbose=False)
    except Preempted as e:
        print(f"PREEMPTED step={e.step} path={e.path}", flush=True)
        sys.exit(143)
    if gang:
        with open(os.path.join(base_dir, f"done_w{pid}"), "w") as f:
            f.write(str(est._py_step))
    print(f"FINISHED step={est._py_step}", flush=True)


if __name__ == "__main__":
    main()
