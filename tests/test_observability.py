"""Unified telemetry: metrics registry, end-to-end request tracing, and
training-loop instrumentation (ISSUE 3).

Covers: registry thread-safety under concurrent writers, histogram
bucket-edge semantics, the Prometheus exposition golden format, end-to-end
trace-id propagation through a live ClusterServing round trip, the
``/stats`` namespacing fix + flat back-compat view, the healthy-server
counter invariant, step-loop instrumentation (snapshot + SummaryWriter
mirror), heartbeat JSON payloads + supervisor aggregation, fault
arming/firing counted through the registry, and the instrumentation
overhead guard (slow).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context, metrics, trace
from analytics_zoo_tpu.core.metrics import MetricsRegistry
from analytics_zoo_tpu.serving import (ClusterServing, HTTPFrontend,
                                       InferenceModel, InputQueue,
                                       OutputQueue)


def _linear_model():
    init_orca_context("local")

    class M(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(3), x, name="fc")

    m = M()
    variables = m.init(__import__("jax").random.PRNGKey(0),
                       np.zeros((1, 4), np.float32))
    return InferenceModel(batch_buckets=(1, 4, 8)).load(m, variables)


# -- registry primitives ------------------------------------------------------

def test_counter_thread_safety_under_concurrent_writers():
    reg = MetricsRegistry()
    c = reg.counter("t.hits")
    h = reg.histogram("t.lat_ms")
    n_threads, n_iter = 8, 5000

    def work():
        for i in range(n_iter):
            c.inc()
            h.observe(float(i % 100))

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    snap = reg.snapshot()["t.lat_ms"]
    assert snap["count"] == n_threads * n_iter
    assert snap["sum"] == pytest.approx(
        n_threads * sum(range(100)) * (n_iter // 100))


def test_histogram_bucket_edges():
    """Prometheus ``le`` semantics: bucket i counts values <= edges[i];
    one overflow bucket catches the rest."""
    reg = MetricsRegistry()
    h = reg.histogram("t.edges", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 2.0, 4.0, 5.0):
        h.observe(v)
    assert h.counts == [2, 2, 1, 1]  # le=1, le=2, le=4, +Inf
    assert h.count == 6 and h.sum == pytest.approx(14.0)
    # quantiles interpolate within the winning bucket and stay ordered
    assert 0.0 <= h.percentile(0.25) <= h.percentile(0.75) <= 4.0
    # the exposition renders CUMULATIVE bucket counts
    text = reg.prometheus()
    assert 'zoo_t_edges_bucket{le="1"} 2' in text
    assert 'zoo_t_edges_bucket{le="2"} 4' in text
    assert 'zoo_t_edges_bucket{le="4"} 5' in text
    assert 'zoo_t_edges_bucket{le="+Inf"} 6' in text


def test_gauge_tracks_high_water_mark():
    reg = MetricsRegistry()
    g = reg.gauge("t.depth")
    g.add(3)
    g.add(2)
    g.add(-4)
    assert g.value == 1 and g.max == 5
    assert reg.snapshot()["t.depth"] == {"value": 1, "max": 5}


def test_metric_type_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("t.x")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("t.x")
    # type uniqueness is per NAME, not per (name, labels): a counter and
    # a histogram sharing a name would corrupt the exposition, which
    # renders all of a name's label series under one # TYPE line
    reg.inc("t.y")
    with pytest.raises(ValueError, match="already registered"):
        reg.observe("t.y", 1.0, route="a")
    reg.prometheus()  # still renders cleanly


def test_labels_create_distinct_series():
    reg = MetricsRegistry()
    reg.inc("t.req", route="/a")
    reg.inc("t.req", route="/a")
    reg.inc("t.req", route="/b")
    snap = reg.snapshot()
    assert snap["t.req{route=/a}"] == 2
    assert snap["t.req{route=/b}"] == 1


def test_prometheus_exposition_golden():
    """Byte-exact golden for the three metric kinds — scrapers parse this
    format mechanically, so it must not drift by accident."""
    reg = MetricsRegistry()
    reg.counter("app.requests").inc(3)
    reg.counter("app.requests", route="/x").inc(1)
    reg.gauge("app.depth").set(2)
    h = reg.histogram("app.lat_ms", buckets=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    h.observe(50.0)
    assert reg.prometheus() == (
        "# TYPE zoo_app_depth gauge\n"
        "zoo_app_depth 2\n"
        "zoo_app_depth_max 2\n"
        "# TYPE zoo_app_lat_ms histogram\n"
        'zoo_app_lat_ms_bucket{le="1"} 1\n'
        'zoo_app_lat_ms_bucket{le="10"} 2\n'
        'zoo_app_lat_ms_bucket{le="+Inf"} 3\n'
        "zoo_app_lat_ms_sum 55.5\n"
        "zoo_app_lat_ms_count 3\n"
        "# TYPE zoo_app_requests counter\n"
        "zoo_app_requests 3\n"
        'zoo_app_requests{route="/x"} 1\n')


def test_export_jsonl_and_flat_view(tmp_path):
    reg = MetricsRegistry()
    reg.inc("server.requests", 4)
    reg.gauge("server.queue_depth").set(7)
    reg.observe("server.lat_ms", 3.0)
    path = str(tmp_path / "metrics.jsonl")
    reg.export_jsonl(path)
    reg.export_jsonl(path)  # append-only: one record per call
    lines = [json.loads(ln) for ln in open(path)]
    assert len(lines) == 2
    assert lines[0]["metrics"]["server.requests"] == 4
    assert lines[0]["wall"] <= lines[1]["wall"]
    flat = reg.flat(prefix="server.")
    # counters + gauge values only, prefix stripped, histograms excluded
    assert flat == {"requests": 4, "queue_depth": 7}


def test_reset_zeroes_in_place_keeping_handles():
    reg = MetricsRegistry()
    c = reg.counter("t.n")
    c.inc(5)
    reg.reset()
    assert c.value == 0
    c.inc()  # the old handle still feeds the same registered series
    assert reg.snapshot()["t.n"] == 1


def test_disabled_registry_drops_writes():
    reg = MetricsRegistry()
    c = reg.counter("t.n")
    reg.enabled = False
    c.inc()
    reg.observe("t.h", 1.0)
    reg.enabled = True
    assert reg.snapshot()["t.n"] == 0


# -- end-to-end tracing through live serving ---------------------------------

def test_trace_id_propagation_through_serving_round_trip():
    """One request's trace id is observable at the client, at the
    batcher, and in the reply's stage breakdown — the acceptance
    criterion's single-request correlation."""
    im = _linear_model()
    with ClusterServing(im, batch_size=4) as srv:
        inq = InputQueue(port=srv.port)
        outq = OutputQueue(input_queue=inq)
        uid = inq.enqueue("t", t=np.ones((4,), np.float32))
        tid = inq.trace_id(uid)
        assert tid is not None and len(tid) == 16
        out = outq.query(uid, timeout=30)
        assert out is not None
        recs = trace.find(tid)
        wheres = [r.where for r in recs]
        assert "server.batch" in wheres  # the batcher saw this id
        assert "client" in wheres        # the client closed it out
        client_rec = recs[wheres.index("client")]
        # reply stages: the server's breakdown rode the reply header
        for stage in ("client.total_ms", "server.queue_wait_ms",
                      "server.inference_ms", "server.batch_size"):
            assert stage in client_rec.stages, stage
        assert (client_rec.stages["client.total_ms"]
                >= client_rec.stages["server.inference_ms"] > 0)
        # and the latency landed in the registry histograms
        snap = metrics.get_registry().snapshot()
        assert snap["client.request_ms"]["count"] >= 1
        assert snap["server.inference_ms"]["count"] >= 1
        assert snap["server.queue_wait_ms"]["count"] >= 1
        inq.close()


def test_frontend_propagates_caller_trace_id():
    im = _linear_model()
    with ClusterServing(im, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            req = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Trace-Id": "cafe0123cafe0123"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.headers.get("X-Trace-Id") == "cafe0123cafe0123"
            recs = trace.find("cafe0123cafe0123")
            assert {r.where for r in recs} >= {"server.batch", "client"}


# -- /metrics + /stats --------------------------------------------------------

def test_frontend_metrics_endpoint_serves_prometheus():
    """GET /metrics is valid text exposition covering serving, client,
    and frontend series in one scrape (acceptance criterion)."""
    im = _linear_model()
    with ClusterServing(im, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            req = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30):
                pass
            with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
    for needle in ("# TYPE zoo_server_requests counter",
                   "zoo_server_requests 1",
                   "zoo_server_replies 1",
                   "# TYPE zoo_server_queue_wait_ms histogram",
                   "# TYPE zoo_client_request_ms histogram",
                   "zoo_client_request_ms_count 1",
                   "# TYPE zoo_frontend_requests counter",
                   "zoo_frontend_requests 1",
                   'zoo_frontend_request_ms_count{route="/predict"} 1'):
        assert needle in text, needle
    # every non-comment line is "<name>[{labels}] <number>"
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, value = line.rsplit(" ", 1)
        float(value)
        assert name[0].isalpha()


def test_stats_namespaced_and_flat_backcompat():
    """The /stats key-collision fix: frontend and client counters are
    namespaced (``frontend.*`` / ``client.*``); the flat old-name view
    rides along for existing dashboards."""
    im = _linear_model()
    with ClusterServing(im, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            req = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                headers={"Content-Type": "application/json"})
            for _ in range(2):
                with urllib.request.urlopen(req, timeout=30):
                    pass
            with urllib.request.urlopen(url + "/stats", timeout=10) as r:
                stats = json.load(r)
    assert stats["frontend.requests"] == 2
    assert stats["client.retries"] == 0
    # per-route latency summaries ride along
    assert stats["frontend.request_ms{route=/predict}"]["count"] == 2
    # flat back-compat view: the pre-registry key names still work
    assert stats["requests"] == 2 and stats["timeouts"] == 0
    for key in ("reconnects", "resends", "retries"):
        assert key in stats


def test_server_stats_healthy_invariant():
    """The docstring-backed invariant from ``ClusterServing.stats()``:
    requests == replies + errors + pending — nothing silently dropped.
    Also: the queue-depth gauge recorded a high-water mark."""
    im = _linear_model()
    with ClusterServing(im, batch_size=4) as srv:
        inq = InputQueue(port=srv.port)
        outq = OutputQueue(input_queue=inq)
        uids = [inq.enqueue("t", t=np.full((4,), float(i), np.float32))
                for i in range(6)]
        for uid in uids:
            assert outq.query(uid, timeout=30) is not None
        s = srv.stats()
        inq.close()
    assert "requests == replies + errors + pending" in \
        ClusterServing.stats.__doc__
    assert s["requests"] == s["replies"] + s["errors"] + s["pending"] == 6
    assert s["pending"] == 0
    assert s["queue_depth_max"] >= 1  # at least one request was queued
    assert s["shed_batches"] == 0
    # stop() zeroes the occupancy gauge: a stopped server (or a successor
    # sharing the process registry) must not report phantom queue depth
    assert srv.stats()["queue_depth"] == 0


@pytest.mark.faults
def test_shed_counts_surface_per_batch():
    """Deadline shedding shows up in stats() as shed_batches (how many
    batches shed anything) next to the cumulative shed count, and in the
    ``server.shed_per_batch`` histogram."""
    from analytics_zoo_tpu.core import faults
    im = _linear_model()
    with ClusterServing(im, batch_size=4, batch_timeout_ms=1) as srv:
        inq = InputQueue(port=srv.port)
        outq = OutputQueue(input_queue=inq)
        with faults.get_registry().armed("serving.model_latency", times=1,
                                         delay=0.4):
            blocker = inq.enqueue("t", t=np.ones((4,), np.float32))
            time.sleep(0.1)  # batcher is now sleeping in the armed delay
            doomed = inq.enqueue("t", deadline=0.05,
                                 t=np.ones((4,), np.float32))
            with pytest.raises(RuntimeError, match="deadline exceeded"):
                outq.query(doomed, timeout=30)
            assert outq.query(blocker, timeout=30) is not None
        s = srv.stats()
        inq.close()
    assert s["shed"] == 1 and s["shed_batches"] == 1
    snap = metrics.get_registry().snapshot()
    assert snap["server.shed_per_batch"]["count"] == 1


# -- faults counted through the registry --------------------------------------

@pytest.mark.faults
def test_fault_arming_and_firing_counted_in_registry():
    """Resilience tests can assert injections via public metrics
    (``faults.armed`` / ``faults.fired{point=...}``) instead of the
    fault registry's private state."""
    from analytics_zoo_tpu.core import faults
    reg = faults.get_registry()
    with reg.armed("feed.stall", times=2):
        reg.fire("feed.stall")
        reg.fire("feed.stall")
        reg.fire("feed.stall")  # spec exhausted: does not fire
    snap = metrics.get_registry().snapshot()
    assert snap["faults.armed{point=feed.stall}"] == 1
    assert snap["faults.fired{point=feed.stall}"] == 2


# -- training-loop instrumentation -------------------------------------------

def _tiny_fit(log_dir=None, epochs=2, n=128, batch=32):
    from analytics_zoo_tpu.orca.learn import Estimator
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    y = rng.normal(size=(n, 1)).astype(np.float32)
    est = Estimator.from_keras(
        nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)]),
        loss="mse", learning_rate=1e-3, log_dir=log_dir)
    hist = est.fit((x, y), epochs=epochs, batch_size=batch, verbose=False)
    return est, hist


def test_fit_reports_step_time_and_data_wait_split(tmp_path):
    """Acceptance criterion: fit() reports step-time and the data-wait /
    compute split in BOTH the registry snapshot and the SummaryWriter
    scalars."""
    init_orca_context("local")
    est, hist = _tiny_fit(log_dir=str(tmp_path), epochs=2)
    steps = 2 * (128 // 32)
    snap = metrics.get_registry().snapshot()
    assert snap["train.step_ms"]["count"] == steps
    assert snap["train.data_wait_ms"]["count"] == steps
    assert snap["train.steps"] == steps
    assert snap["train.samples"] == steps * 32
    assert snap["train.step_ms"]["sum"] >= snap["train.data_wait_ms"]["sum"]
    for tag in ("step_time_ms", "data_wait_ms", "compute_ms",
                "samples_per_sec", "throughput", "loss"):
        scalars = est.get_train_summary(tag)
        assert len(scalars) == 2, tag  # one point per epoch
    # the split adds up: step ≈ data_wait + compute, per epoch
    step = dict(est.get_train_summary("step_time_ms"))
    wait = dict(est.get_train_summary("data_wait_ms"))
    comp = dict(est.get_train_summary("compute_ms"))
    for ep in step:
        assert step[ep] == pytest.approx(wait[ep] + comp[ep], rel=1e-3,
                                         abs=1e-3)


def test_checkpoint_save_restore_durations_recorded(tmp_path):
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    rng = np.random.default_rng(0)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               learning_rate=1e-3,
                               model_dir=str(tmp_path / "ckpt"))
    est.fit((rng.normal(size=(64, 4)).astype(np.float32),
             rng.normal(size=(64, 1)).astype(np.float32)),
            epochs=1, batch_size=32, verbose=False)
    est.save()
    est.load()
    snap = metrics.get_registry().snapshot()
    assert snap["checkpoint.save_ms"]["count"] >= 1
    assert snap["checkpoint.restore_ms"]["count"] >= 1


def test_streaming_feed_load_latency_and_counters():
    from analytics_zoo_tpu.data.stream import StreamingDataFeed
    mesh = init_orca_context("local")

    def load(i, rng=None):
        return {"x": np.full((4,), float(i), np.float32)}

    feed = StreamingDataFeed(num_samples=32, load_sample=load,
                             batch_size=8, shuffle=False, num_workers=2)
    n = sum(1 for _ in feed.epoch(mesh, 0))
    assert n == 4
    snap = metrics.get_registry().snapshot()
    assert snap["feed.load_ms"]["count"] == 32


def test_automl_trial_timings_recorded():
    from analytics_zoo_tpu.automl.search import RandomSearchEngine
    from analytics_zoo_tpu.automl import hp

    eng = RandomSearchEngine(metric_mode="min")
    eng.run(lambda cfg, report: cfg["x"] * 2,
            {"x": hp.uniform(0.0, 1.0)}, n_trials=3)
    snap = metrics.get_registry().snapshot()
    assert snap["automl.trial_ms"]["count"] == 3
    assert snap["automl.trials{status=done}"] == 3


# -- heartbeat payloads + supervisor aggregation ------------------------------

def test_heartbeat_file_carries_json_status(tmp_path):
    from analytics_zoo_tpu.core import ZooConfig
    hb = tmp_path / "hb"
    init_orca_context("local", config=ZooConfig(
        heartbeat_file=str(hb), heartbeat_interval=0.0))
    _tiny_fit(epochs=1)
    payload = json.loads(hb.read_text())
    assert payload["step"] == 4
    assert "loss" in payload and "samples_per_sec" in payload
    assert payload["wall"] <= time.time()


def test_gang_status_aggregates_heartbeats(tmp_path, caplog):
    """The supervisor turns heartbeat JSON payloads into one periodic
    gang-status log line and a metrics_w<rank>.jsonl per worker."""
    import logging
    from analytics_zoo_tpu.core.launcher import _GangStatus

    class FakeProc:
        def poll(self):
            return None

    hb_files = []
    for rank in range(2):
        hb = tmp_path / f"hb_w{rank}"
        hb.write_text(json.dumps({"step": 10 + rank, "loss": 0.5,
                                  "samples_per_sec": 100.0,
                                  "wall": time.time()}))
        hb_files.append(str(hb))
    status = _GangStatus(interval=0.0, metrics_dir=str(tmp_path / "m"))
    procs = [FakeProc(), FakeProc()]
    with caplog.at_level(logging.INFO, logger="analytics_zoo_tpu"):
        status.maybe_emit(procs, hb_files, attempt=0)
        status.maybe_emit(procs, hb_files, attempt=0)
    lines = [r.message for r in caplog.records
             if "gang status" in r.message]
    assert lines and "step=10" in lines[0] and "step=11" in lines[0]
    for rank in range(2):
        recs = [json.loads(ln) for ln in
                (tmp_path / "m" / f"metrics_w{rank}.jsonl").open()]
        assert len(recs) == 2
        assert recs[0]["rank"] == rank and recs[0]["step"] == 10 + rank


def test_gang_status_tolerates_legacy_touch_files(tmp_path):
    from analytics_zoo_tpu.core.launcher import _read_heartbeat_payload
    hb = tmp_path / "hb"
    hb.write_text("")  # the supervisor's baseline touch
    assert _read_heartbeat_payload(str(hb)) == {}
    assert _read_heartbeat_payload(str(tmp_path / "missing")) == {}
    hb.write_text("{half a json")  # torn write from a dying worker
    assert _read_heartbeat_payload(str(hb)) == {}


def test_bench_registry_detail_populates_after_fit():
    """bench.py's record detail carries the step-time p50/p99 snapshot
    (the bench-trajectory satellite)."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    init_orca_context("local")
    _tiny_fit(epochs=1)
    out = bench._train_registry_detail()
    for key in ("train.step_ms.p50", "train.step_ms.p99",
                "train.data_wait_ms.p50", "train.steps", "train.samples"):
        assert key in out, key
    assert out["train.steps"] == 4


# -- overhead guard -----------------------------------------------------------

@pytest.mark.slow
def test_step_loop_instrumentation_overhead_under_5_percent():
    """Acceptance criterion: the per-step telemetry (two histogram
    observes + two counter incs + the heartbeat check) costs < 5% of a
    tiny model's step loop.  Best-of-5 epochs per mode to shave CPU
    scheduling noise; compiled executables are warmed first."""
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 4)).astype(np.float32)
    y = rng.normal(size=(2048, 1)).astype(np.float32)
    est = Estimator.from_keras(
        nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(1)]),
        loss="mse", learning_rate=1e-3)
    est.fit((x, y), epochs=1, batch_size=16, verbose=False)  # compile

    reg = metrics.get_registry()

    def best_epoch_time(repeats=5):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.monotonic()
            est.fit((x, y), epochs=1, batch_size=16, verbose=False)
            best = min(best, time.monotonic() - t0)
        return best

    try:
        reg.enabled = False
        t_off = best_epoch_time()
        reg.enabled = True
        t_on = best_epoch_time()
    finally:
        reg.enabled = True
    # 5% relative plus a 5 ms absolute floor: at 128 steps/epoch the
    # telemetry budget is ~40 µs/step, two orders above its real cost
    assert t_on <= t_off * 1.05 + 0.005, (t_on, t_off)


def test_metric_catalog_matches_code():
    """The docs/observability.md catalog must track the code: a series
    registered but undocumented (or documented but gone) fails here —
    the catalog drifted risk-free for four PRs before this guard."""
    import pathlib
    import subprocess
    import sys
    script = (pathlib.Path(__file__).resolve().parent.parent
              / "dev" / "check_metric_docs.py")
    proc = subprocess.run([sys.executable, str(script)],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
