"""Load-adaptive control plane (ISSUE 12): windowed metrics helpers,
runtime pool membership, per-class admission, self-tuning hedging, and
the ServingController observe→decide→actuate loop.

Everything runs in-process (InProcessReplicaFactory) and ticks are
driven MANUALLY — the controller's loop thread calls the same public
``tick()``, so nothing here sleeps through wall-clock intervals.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.core.faults import FaultRegistry
from analytics_zoo_tpu.serving import (ClusterServing, HysteresisPolicy,
                                       InProcessReplicaFactory, InputQueue,
                                       OutputQueue, ReplicaSet, RetryPolicy,
                                       ServingController)
from analytics_zoo_tpu.serving import controller as controller_lib
from analytics_zoo_tpu.serving import protocol


class _Model:
    """Doubles its input, optionally slowly (per-batch sleep = explicit
    capacity per replica: more replicas, more concurrent batches)."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        return np.asarray(x) * 2.0


def _serve(delay: float = 0.0, **kw) -> ClusterServing:
    kw.setdefault("batch_size", 4)
    kw.setdefault("batch_timeout_ms", 2)
    return ClusterServing(_Model(delay), port=0, **kw).start()


def _fast_retry(**kw) -> RetryPolicy:
    kw.setdefault("max_attempts", 3)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.1)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


# -- metrics: public windowed-quantile API --------------------------------------

def test_histogram_quantile_and_snapshot_delta():
    reg = metrics_lib.MetricsRegistry()
    h = reg.histogram("t.ms")
    for _ in range(90):
        h.observe(5.0)
    prev = reg.snapshot()
    assert h.quantile(0.5) == pytest.approx(h.percentile(0.5))
    # window: only what happened since `prev`
    for _ in range(10):
        h.observe(500.0)
    reg.counter("t.count").inc(7)
    delta = metrics_lib.snapshot_delta(prev, reg.snapshot())
    assert delta["t.count"] == 7
    w = delta["t.ms"]
    assert w["count"] == 10
    # the lifetime histogram is dominated by 5ms samples; the WINDOW
    # quantile must see only the 500ms ones
    assert metrics_lib.quantile_from_snapshot(w, 0.5) > 100.0
    # non-histogram / empty-window entries answer None
    assert metrics_lib.quantile_from_snapshot(delta["t.count"], 0.5) is None
    empty = metrics_lib.snapshot_delta(reg.snapshot(), reg.snapshot())
    assert metrics_lib.quantile_from_snapshot(
        empty.get("t.ms", {"count": 0}), 0.5) in (None,)


def test_snapshot_delta_series_absent_from_baseline():
    reg = metrics_lib.MetricsRegistry()
    prev = reg.snapshot()
    reg.counter("fresh.count").inc(3)
    delta = metrics_lib.snapshot_delta(prev, reg.snapshot())
    assert delta["fresh.count"] == 3


# -- router: runtime pool membership --------------------------------------------

def test_add_remove_replica_updates_pool_and_metrics():
    reg = metrics_lib.get_registry()
    a, b = _serve(), _serve()
    rs = ReplicaSet([(a.host, a.port)], start_health=False)
    try:
        assert len(rs.replicas) == 1
        rep = rs.add_replica((b.host, b.port))
        assert len(rs.replicas) == 2
        snap = reg.snapshot()
        assert snap["router.replicas"]["value"] == 2
        assert snap["router.scale_events{direction=up}"] == 1
        # the joined replica takes traffic
        for _ in range(8):
            assert np.allclose(
                rs.predict(np.ones((2,), np.float32)), 2.0)
        # duplicate join refused
        with pytest.raises(ValueError):
            rs.add_replica((b.host, b.port))
        assert rs.remove_replica(rep, drain=True) is True
        assert len(rs.replicas) == 1
        snap = reg.snapshot()
        assert snap["router.replicas"]["value"] == 1
        assert snap["router.scale_events{direction=down}"] == 1
        # the retired replica's per-replica series left the registry
        assert f"router.requests{{replica={rep.name}}}" not in snap
        # unknown and last-replica removals refused
        with pytest.raises(ValueError):
            rs.remove_replica((b.host, b.port))
        with pytest.raises(ValueError):
            rs.remove_replica((a.host, a.port))
    finally:
        rs.close()
        a.stop()
        b.stop()


# -- per-class admission ---------------------------------------------------------

def test_admission_gate_sheds_batch_first():
    """The batch tier faces a halved depth cap and a doubled
    attainability bar; interactive and unclassified keep the exact
    pre-klass gate."""
    srv = _serve()
    try:
        srv._wait_ewma = 50.0
        srv._m_depth.set(2)
        assert srv._admission_reject(80.0, klass="interactive") is None
        assert srv._admission_reject(80.0, klass=None) is None
        rej = srv._admission_reject(80.0, klass="batch")
        assert rej is not None and "batch margin" in rej
        # depth cap: limit 4 -> batch limit 2, trips at depth 2
        srv._wait_ewma = 0.0
        srv.admission_queue_limit = 4
        assert srv._admission_reject(None, klass="interactive") is None
        assert "queue full" in srv._admission_reject(None, klass="batch")
    finally:
        srv._m_depth.set(0)
        srv.stop()


def test_klass_rides_header_and_counts():
    """klass travels the optional-header mechanism end to end and lands
    in per-class counters; an absent klass never touches the wire (the
    frame is byte-identical to a pre-klass client's)."""
    h = protocol.request_header("u", (2,), "<f4", klass="batch")
    assert h["klass"] == "batch"
    assert "klass" not in protocol.request_header("u", (2,), "<f4")
    srv = _serve()
    iq = InputQueue(srv.host, srv.port)
    oq = OutputQueue(input_queue=iq)
    try:
        x = np.ones((2,), np.float32)
        for klass in ("interactive", "batch", None):
            uid = iq.enqueue("t", klass=klass, t=x)
            assert np.allclose(oq.query(uid, timeout=10.0), 2.0)
        snap = metrics_lib.get_registry().snapshot()
        assert snap["server.requests{klass=interactive}"] == 1
        assert snap["server.requests{klass=batch}"] == 1
        assert snap["server.requests"] == 3  # klass'd or not, all count
    finally:
        iq.close()
        srv.stop()


def test_interactive_holds_while_batch_sheds():
    """Under queue pressure the batch tier is rejected (retryably) at
    the door while interactive traffic keeps being admitted."""
    private = FaultRegistry()
    srv = _serve(batch_size=1, batch_timeout_ms=1, faults=private,
                 admission_queue_limit=6)
    iq = InputQueue(srv.host, srv.port, retry=_fast_retry(max_attempts=2))
    oq = OutputQueue(input_queue=iq)
    try:
        x = np.ones((2,), np.float32)
        # wedge assembly so depth builds: batch cap is 6*0.5 = 3
        private.enable("serving.model_latency", times=1, delay=0.5)
        uids = [iq.enqueue("t", klass="interactive", t=x)
                for _ in range(4)]
        time.sleep(0.1)  # let depth register
        with pytest.raises(RuntimeError, match="queue full"):
            uid_b = iq.enqueue("t", klass="batch", t=x)
            oq.query(uid_b, timeout=5.0)  # retries exhaust -> raises
        # interactive admitted throughout and all answered
        for uid in uids:
            assert np.allclose(oq.query(uid, timeout=10.0), 2.0)
        snap = metrics_lib.get_registry().snapshot()
        assert snap.get("server.admission_rejected{klass=batch}", 0) >= 1
        assert "server.admission_rejected{klass=interactive}" not in snap
    finally:
        iq.close()
        srv.stop()


# -- self-tuning hedging ---------------------------------------------------------

def test_hedge_auto_retunes_freezes_and_tracks():
    reg = metrics_lib.get_registry()
    rs = ReplicaSet([("127.0.0.1", 1)], hedge_ms="auto",
                    hedge_min_samples=20, hedge_margin_ms=5.0,
                    start_health=False)
    try:
        assert rs.hedge_auto and rs.hedge_ms is None  # off until tuned
        h = reg.histogram("client.request_ms", replica="127.0.0.1:1")
        for _ in range(50):
            h.observe(20.0)
        first = rs.retune_hedge()
        assert first is not None and first < 60.0
        # sparse window: below min_samples the threshold FREEZES ...
        for _ in range(5):
            h.observe(500.0)
        assert rs.retune_hedge() == first
        # ... but the unconsumed window ACCUMULATES: once enough samples
        # arrive, the retune sees all of them and tracks the shift up
        for _ in range(45):
            h.observe(500.0)
        shifted = rs.retune_hedge()
        assert shifted > first and shifted > 100.0
        snap = reg.snapshot()
        assert snap["router.hedge_retunes"] == 2
        assert snap["router.hedge_ms"]["value"] == pytest.approx(shifted)
    finally:
        rs.close()


def test_hedge_numeric_is_untouched_by_retune():
    rs = ReplicaSet([("127.0.0.1", 1)], hedge_ms=50.0, start_health=False)
    try:
        assert not rs.hedge_auto
        assert rs.retune_hedge() == 50.0
        assert rs.hedge_ms == 50.0
        # zeroed-in-place pinned handles from other tests may exist; a
        # numeric-hedge retune must not have COUNTED anything
        assert metrics_lib.get_registry().snapshot().get(
            "router.hedge_retunes", 0) == 0
    finally:
        rs.close()


def test_hedge_auto_tracks_injected_latency_shift():
    """End to end: arm ``serving.model_latency`` on a real server and
    the auto-tuned threshold follows the observed client latency up."""
    private = FaultRegistry()
    srv = _serve(faults=private)
    rs = ReplicaSet([(srv.host, srv.port)], hedge_ms="auto",
                    hedge_min_samples=10, start_health=False)
    try:
        x = np.ones((2,), np.float32)
        for _ in range(15):
            rs.predict(x)
        fast = rs.retune_hedge()
        assert fast is not None
        private.enable("serving.model_latency", times=15, delay=0.12)
        for _ in range(15):
            rs.predict(x)
        slow = rs.retune_hedge()
        assert slow > fast and slow >= 100.0
    finally:
        rs.close()
        srv.stop()


# -- scaling policy (pure unit) ---------------------------------------------------

def test_hysteresis_policy_decisions():
    pol = HysteresisPolicy(slo_p99_ms=100.0, queue_high=50.0,
                           min_replicas=1, max_replicas=3,
                           up_cooldown_s=10.0, down_cooldown_s=30.0,
                           low_water_frac=0.5, down_ticks=2)

    def sig(now, p99, depth, n):
        return {"now": now, "p99_ms": p99, "queue_depth": depth,
                "replicas": n, "window_requests": 100}

    assert pol.decide(sig(0.0, 150.0, 0.0, 1)) == 1      # SLO breach
    assert pol.decide(sig(5.0, 150.0, 0.0, 2)) == 0      # up cooldown
    assert pol.decide(sig(20.0, 50.0, 60.0, 2)) == 1     # queue high-water
    assert pol.decide(sig(40.0, 150.0, 0.0, 3)) == 0     # at max
    # scale-down needs `down_ticks` CONSECUTIVE calm ticks ...
    assert pol.decide(sig(60.0, 10.0, 0.0, 3)) == 0
    assert pol.decide(sig(61.0, 80.0, 0.0, 3)) == 0      # not calm: resets
    assert pol.decide(sig(62.0, 10.0, 0.0, 3)) == 0
    assert pol.decide(sig(63.0, None, 0.0, 3)) == -1     # idle counts calm
    # ... and the down cooldown after a scale event in either direction
    assert pol.decide(sig(64.0, 10.0, 0.0, 2)) == 0
    assert pol.decide(sig(65.0, 10.0, 0.0, 2)) == 0
    assert pol.decide(sig(94.0, 10.0, 0.0, 2)) == -1
    # floor respected even when calm
    assert pol.decide(sig(200.0, 10.0, 0.0, 1)) == 0
    assert pol.decide(sig(201.0, 10.0, 0.0, 1)) == 0
    with pytest.raises(ValueError):
        HysteresisPolicy(slo_p99_ms=10.0, min_replicas=3, max_replicas=2)


# -- the controller ---------------------------------------------------------------

def test_controller_scales_up_then_down_with_zero_errors(tmp_path):
    """The PR-5 acceptance path: a load step pushes p99 over the SLO ->
    the controller creates a WARM replica and joins it; when load drops
    it drains and retires the same replica — zero client errors end to
    end, and the scale-down decision leaves a flight record naming the
    retired replica and the triggering metrics."""
    seed = _serve(delay=0.01)
    rs = ReplicaSet([(seed.host, seed.port)], start_health=False)
    factory = InProcessReplicaFactory(lambda: _serve(delay=0.01))
    pol = HysteresisPolicy(slo_p99_ms=60.0, min_replicas=1,
                           max_replicas=2, up_cooldown_s=0.0,
                           down_cooldown_s=0.0, down_ticks=2)
    ctl = ServingController(rs, factory, policy=pol, interval_s=60.0,
                            flightrec_dir=str(tmp_path))
    errors = []
    x = np.ones((2,), np.float32)

    def drive(n):
        for _ in range(n):
            try:
                out = rs.predict(x, deadline=10.0)
                assert np.allclose(out, 2.0)
            except Exception as e:  # noqa: BLE001 - counted, not masked
                errors.append(e)

    try:
        # calm baseline: sequential trickle stays under the SLO
        drive(5)
        assert ctl.tick() == 0 and len(rs.replicas) == 1
        # 10x step: concurrent closed-loop clients queue behind the
        # 10ms-per-batch model and p99 blows through the SLO
        threads = [threading.Thread(target=drive, args=(10,))
                   for _ in range(10)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # mid-burst: the tick sees hot signals
        assert ctl.tick() == 1
        assert len(rs.replicas) == 2
        for t in threads:
            t.join()
        # load drops: two calm ticks later the added replica drains out
        drive(3)
        ctl.tick()
        assert ctl.tick() == -1
        assert len(rs.replicas) == 1
        assert not errors, errors
        assert [e["direction"] for e in ctl.events] == ["up", "down"]
        snap = metrics_lib.get_registry().snapshot()
        assert snap["controller.scale_ups"] == 1
        assert snap["controller.scale_downs"] == 1
        assert snap.get("controller.errors", 0) == 0
        # the flight record names the victim and the signals
        dumps = [f for f in os.listdir(tmp_path) if "flightrec" in f]
        assert dumps, os.listdir(tmp_path)
        rec = json.loads((tmp_path / dumps[0]).read_text())
        assert rec["reason"] == "scale_down"
        ctx = rec["context"]
        assert ctx["replica"] == ctl.events[-1]["replica"]
        assert "p99_ms" in ctx and "queue_depth" in ctx
    finally:
        ctl.close()
        rs.close()
        seed.stop()


def test_controller_retunes_auto_hedge_each_tick():
    seed = _serve()
    rs = ReplicaSet([(seed.host, seed.port)], hedge_ms="auto",
                    hedge_min_samples=5, start_health=False)
    ctl = ServingController(rs, InProcessReplicaFactory(_serve),
                            policy=HysteresisPolicy(slo_p99_ms=1e9),
                            interval_s=60.0)
    try:
        for _ in range(10):
            rs.predict(np.ones((2,), np.float32))
        assert rs.hedge_ms is None
        ctl.tick()
        assert rs.hedge_ms is not None
        assert metrics_lib.get_registry().snapshot()[
            "router.hedge_retunes"] == 1
    finally:
        ctl.close()
        rs.close()
        seed.stop()


def test_controller_loop_thread_and_leak_accounting():
    seed = _serve()
    rs = ReplicaSet([(seed.host, seed.port)], start_health=False)
    ctl = ServingController(rs, InProcessReplicaFactory(_serve),
                            policy=HysteresisPolicy(slo_p99_ms=1e9),
                            interval_s=0.05)
    try:
        assert not ctl.running
        assert ctl not in controller_lib.live_controllers()
        ctl.start()
        assert ctl.running
        assert ctl in controller_lib.live_controllers()
        deadline = time.monotonic() + 5.0
        reg = metrics_lib.get_registry()
        while time.monotonic() < deadline:
            if reg.snapshot().get("controller.ticks", 0) >= 2:
                break
            time.sleep(0.02)
        assert reg.snapshot().get("controller.ticks", 0) >= 2
        ctl.stop()
        assert not ctl.running
        assert ctl not in controller_lib.live_controllers()
    finally:
        ctl.close()
        rs.close()
        seed.stop()


def test_controller_close_retires_managed_replicas():
    seed = _serve(delay=0.01)
    rs = ReplicaSet([(seed.host, seed.port)], start_health=False)
    created = []

    def make():
        srv = _serve(delay=0.01)
        created.append(srv)
        return srv

    pol = HysteresisPolicy(slo_p99_ms=1.0, min_replicas=1, max_replicas=2,
                           up_cooldown_s=0.0)
    ctl = ServingController(rs, InProcessReplicaFactory(make), policy=pol,
                            interval_s=60.0)
    try:
        threads = [threading.Thread(
            target=lambda: [rs.predict(np.ones((2,), np.float32))
                            for _ in range(5)]) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        assert ctl.tick() == 1 and len(rs.replicas) == 2
        for t in threads:
            t.join()
    finally:
        ctl.close()  # retires the created replica: pool back to 1
        assert len(rs.replicas) == 1
        assert all(s.state == "stopped" for s in created)
        rs.close()
        seed.stop()
