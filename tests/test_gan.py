"""GANEstimator tests (reference: pyzoo/zoo/tfpark/gan/gan_estimator.py —
alternating D/G training; test pattern: learn a toy distribution)."""

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def _gan(noise_dim=8):
    from analytics_zoo_tpu.orca.learn import GANEstimator
    gen = nn.Sequential([nn.Dense(16, activation="relu"), nn.Dense(2)])
    disc = nn.Sequential([nn.Dense(16, activation="relu"), nn.Dense(1)])
    return GANEstimator(gen, disc, noise_dim=noise_dim,
                        generator_lr=3e-3, discriminator_lr=3e-3)


def test_gan_learns_shifted_gaussian():
    rng = np.random.default_rng(0)
    real = (rng.normal(size=(512, 2)) * 0.3 + [4.0, -2.0]).astype(
        np.float32)
    gan = _gan()
    hist = gan.fit(real, epochs=60, batch_size=64, verbose=False)
    assert np.isfinite(hist["d_loss"][-1]) and np.isfinite(
        hist["g_loss"][-1])
    samples = gan.generate(256)
    assert samples.shape == (256, 2)
    center = samples.mean(axis=0)
    # generator output should have moved toward the real mode
    assert abs(center[0] - 4.0) < 2.0 and abs(center[1] + 2.0) < 2.0


def test_gan_d_g_step_ratio_and_history():
    rng = np.random.default_rng(1)
    real = rng.normal(size=(64, 2)).astype(np.float32)
    from analytics_zoo_tpu.orca.learn import GANEstimator
    gen = nn.Sequential([nn.Dense(4), nn.Dense(2)])
    disc = nn.Sequential([nn.Dense(4), nn.Dense(1)])
    gan = GANEstimator(gen, disc, noise_dim=4, d_steps=2, g_steps=1)
    hist = gan.fit(real, epochs=2, batch_size=32, verbose=False)
    assert len(hist["d_loss"]) == 2 and len(hist["g_loss"]) == 2
    # after fit, step counts both D and G sub-steps: 2 epochs * 2 batches
    # * (2 + 1)
    assert int(np.asarray(gan._ts["step"])) == 12


def test_gan_save_load_roundtrip(tmp_path):
    rng = np.random.default_rng(2)
    real = rng.normal(size=(64, 2)).astype(np.float32)
    gan = _gan()
    gan.fit(real, epochs=1, batch_size=32, verbose=False)
    before = gan.generate(8, seed=9)
    d = str(tmp_path / "gan")
    gan.save(d)
    gan2 = _gan()
    gan2.load(d, real[:32])
    after = gan2.generate(8, seed=9)
    np.testing.assert_allclose(before, after, atol=1e-6)

def test_gan_empty_epoch_raises_clearly():
    """Regression (round-2 advisor): a dataset smaller than one batch must
    raise a descriptive error, not a cryptic jnp.stack([]) failure.  The
    in-RAM feed already rejects this up front; the masked-tail path (foreign
    iterables pad + mask, GAN skips masked batches) is the one that used to
    reach jnp.stack([])."""
    from analytics_zoo_tpu.data.interop import from_iterator
    gan = _gan(noise_dim=4)
    rng = np.random.default_rng(0)
    rows = [{"x": rng.normal(size=(2,)).astype("float32")} for _ in range(3)]
    feed = from_iterator(lambda e: iter(rows), batch_size=32)
    with pytest.raises(ValueError, match="no full batches"):
        gan.fit(feed, epochs=1, batch_size=32)


def test_gan_zero_step_sides_train_without_stack_error():
    """d_steps=0 (or g_steps=0) pretrains one side only: full batches must
    NOT trigger the empty-epoch guard, and the idle side records nan."""
    import math
    from analytics_zoo_tpu.orca.learn import GANEstimator
    gen = nn.Sequential([nn.Dense(2)])
    disc = nn.Sequential([nn.Dense(1)])
    data = np.random.default_rng(0).normal(size=(64, 2)).astype("float32")
    gan = GANEstimator(gen, disc, noise_dim=4, d_steps=0, g_steps=1)
    hist = gan.fit(data, epochs=1, batch_size=32, verbose=False)
    assert math.isnan(hist["d_loss"][0]) and not math.isnan(hist["g_loss"][0])
    gan2 = GANEstimator(gen, disc, noise_dim=4, d_steps=1, g_steps=0)
    hist2 = gan2.fit(data, epochs=1, batch_size=32, verbose=False)
    assert math.isnan(hist2["g_loss"][0]) and not math.isnan(hist2["d_loss"][0])
