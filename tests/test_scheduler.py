"""Pluggable serving scheduler + multi-model registry (ISSUE 6).

Covers the scheduler subsystem (window vs continuous admission,
weighted-fair multi-model dequeue, stop()-time backlog drain), the
ModelRegistry (routing, version pinning, in-flight drain accounting),
the warm-before-flip hot-swap path (THE acceptance: a version swap
under 4-thread client load with zero client-visible failures and zero
post-warmup XLA compiles), and AOT-executable persistence across model
versions (a v1→v2 swap reuses the saved executables — compile-counter
asserted).  Directional perf comparisons live in tests/test_perf.py
(slow); this module is tier-1.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from collections import deque

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context, metrics
from analytics_zoo_tpu.core.config import ZooConfig
from analytics_zoo_tpu.serving import (ClusterServing, ContinuousScheduler,
                                       HTTPFrontend, InferenceModel,
                                       InputQueue, ModelRegistry,
                                       OutputQueue, WindowScheduler)
from analytics_zoo_tpu.serving import scheduler as scheduler_lib
from analytics_zoo_tpu.serving.server import _Pending


class _Stub:
    """Model stand-in: multiplies by ``k`` (distinguishes versions)."""

    concurrent_num = 4

    def __init__(self, k: float, delay_s: float = 0.0):
        self.k = k
        self.delay_s = delay_s

    def predict(self, x):
        if self.delay_s:
            time.sleep(self.delay_s)
        return np.asarray(x) * self.k


def _roundtrip(srv, arr, model=None, version=None, timeout=15.0):
    iq = InputQueue(srv.host, srv.port)
    oq = OutputQueue(input_queue=iq)
    try:
        uid = iq.enqueue("t", model=model, version=version, t=arr)
        return oq.query(uid, timeout=timeout)
    finally:
        iq.close()


# -- scheduler construction ---------------------------------------------------

def test_scheduler_factory_and_default():
    assert isinstance(scheduler_lib.make("window"), WindowScheduler)
    assert isinstance(scheduler_lib.make("continuous"),
                      ContinuousScheduler)
    pre = ContinuousScheduler(backlog_factor=2)
    assert scheduler_lib.make(pre) is pre
    with pytest.raises(ValueError, match="unknown scheduler"):
        scheduler_lib.make("nope")
    with pytest.raises(ValueError):
        ContinuousScheduler(backlog_factor=0)
    srv = ClusterServing(_Stub(1.0), batch_size=4)
    try:
        assert srv.scheduler.name == "window"  # bisection default
        assert srv.stats()["scheduler"] == "window"
    finally:
        srv.stop()


def test_zoo_config_grows_scheduler_and_models_knobs():
    cfg = ZooConfig.from_dict({"scheduler": "continuous",
                               "models": {"a": "/models/a"}})
    assert cfg.scheduler == "continuous"
    assert cfg.models == {"a": "/models/a"}
    assert ZooConfig().scheduler == "window"


# -- continuous admission -----------------------------------------------------

def test_continuous_round_trip_and_invariant():
    with ClusterServing(_Stub(3.0), batch_size=4,
                        scheduler="continuous") as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uids = [iq.enqueue("t", t=np.full((i % 3 + 2,), i, np.float32))
                for i in range(12)]
        for i, uid in enumerate(uids):
            out = oq.query(uid, timeout=15.0)
            np.testing.assert_allclose(out, 3.0 * np.full((i % 3 + 2,), i))
        st = srv.stats()
        assert st["requests"] == st["replies"] + st["errors"] \
            + st["pending"]
        assert st["pending"] == 0
        iq.close()


def test_continuous_has_no_window_tail():
    """A lone request must NOT wait out ``batch_timeout_ms``: the window
    batcher holds the batch open hoping for more rows; continuous
    admission dispatches what has arrived."""
    def lone_latency(scheduler):
        with ClusterServing(_Stub(1.0), batch_size=8,
                            batch_timeout_ms=150,
                            scheduler=scheduler) as srv:
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            # warm the path (connection setup out of the clock)
            oq.query(iq.enqueue("w", t=np.ones(4, np.float32)), 15.0)
            t0 = time.monotonic()
            assert oq.query(iq.enqueue("t", t=np.ones(4, np.float32)),
                            15.0) is not None
            dt = time.monotonic() - t0
            iq.close()
        return dt

    assert lone_latency("window") > 0.12       # the tail is real
    assert lone_latency("continuous") < 0.10   # and continuous skips it


def test_continuous_answers_health_pings():
    with ClusterServing(_Stub(1.0), scheduler="continuous") as srv:
        iq = InputQueue(srv.host, srv.port)
        pong = iq.conn.ping(timeout=5.0)
        assert pong is not None and pong["state"] == "serving"
        iq.close()


def test_continuous_stop_drains_backlog_with_explicit_replies():
    """Rows parked in the scheduler's backlog at stop() must get the
    explicit ``server shutting down`` reply, not a silent timeout."""
    with ClusterServing(_Stub(1.0, delay_s=0.3), batch_size=1,
                        inference_workers=1,
                        scheduler="continuous") as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uids = [iq.enqueue("t", t=np.ones(4, np.float32))
                for _ in range(6)]
        time.sleep(0.15)  # let the scheduler pull rows into its backlog
        outcomes = []

        def drain_queries():
            for uid in uids:
                try:
                    r = oq.query(uid, timeout=10.0)
                    outcomes.append("ok" if r is not None else "timeout")
                except (RuntimeError, OSError):
                    outcomes.append("error")

        t = threading.Thread(target=drain_queries)
        t.start()
        srv.stop()
        t.join(timeout=30)
        assert not t.is_alive()
        assert len(outcomes) == len(uids)
        assert "timeout" not in outcomes, outcomes
        st = srv.stats()
        # backlog rows got the explicit drain reply (the scheduler
        # handed them back instead of letting them vanish); requests
        # admitted before stop() are all accounted for.  (A client
        # RETRY racing stop() may add a request the closing socket
        # never answers, so the exact ==-invariant doesn't apply here.)
        assert st["drained"] >= 1, st
        assert st["replies"] + st["errors"] >= len(uids), st
        iq.close()


def test_weighted_fair_admission_across_models():
    """With both backlogs full, one admission round realizes the weight
    ratio (3:1 over a batch of 8 → 6 and 2 rows); a higher-priority
    tier drains before any lower-tier row is admitted."""
    reg = ModelRegistry()
    reg.register("heavy", _Stub(1.0), weight=3.0)
    reg.register("light", _Stub(1.0), weight=1.0)
    reg.register("urgent", _Stub(1.0), weight=1.0, priority=1)
    srv = ClusterServing(models=reg, batch_size=8,
                         scheduler="continuous")
    try:
        sched = srv.scheduler

        def pend(name, n):
            return deque(_Pending(f"{name}-{i}", np.ones(2, np.float32),
                                  None, None, None, model=name)
                         for i in range(n))

        # tier test: urgent drains first even at weight parity
        sched._backlog = {"heavy": pend("heavy", 20),
                          "light": pend("light", 20),
                          "urgent": pend("urgent", 3)}
        batch = sched._admit(srv)
        assert len(batch) == 8
        by_model = {}
        for p in batch:
            by_model[p.model] = by_model.get(p.model, 0) + 1
        assert by_model["urgent"] == 3  # the whole priority tier
        # remaining 5 rows split ~3:1 between heavy and light
        assert by_model["heavy"] > by_model["light"] >= 1, by_model

        # pure weight ratio with two models
        sched._backlog = {"heavy": pend("heavy", 20),
                          "light": pend("light", 20)}
        batch = sched._admit(srv)
        counts = {}
        for p in batch:
            counts[p.model] = counts.get(p.model, 0) + 1
        assert counts == {"heavy": 6, "light": 2}, counts
    finally:
        # the synthetic rows have no sockets for stop()'s drain replies
        sched._backlog.clear()
        srv.stop()


def test_continuous_per_model_backlog_cap_and_held_row():
    """The backlog bound is PER MODEL: a flooding model parks at
    ``batch_size * backlog_factor`` rows (plus one held) while another
    model's rows — even when they arrive BEHIND the flood in the FIFO —
    still reach their own backlog, so the weighted-fair admission has
    something of every demanding model to apportion.  Held rows stay
    visible to stats and to stop()'s drain."""
    reg = ModelRegistry()
    reg.register("heavy", _Stub(1.0))
    reg.register("light", _Stub(1.0), weight=3.0)

    def pend(name, i):
        return _Pending(f"{name}-{i}", np.ones(2, np.float32),
                        None, None, None, model=name)

    rows = ([pend("heavy", i) for i in range(4)]
            + [pend("light", 0), pend("light", 1)]
            + [pend("heavy", 4), pend("heavy", 5)])

    class _Queue:
        def __init__(self, items):
            self.items = deque(items)

        def pop(self, timeout=0.0):
            return (self.items.popleft(),) if self.items else None

    class _Srv:
        batch_size = 4
        _default_name = "default"
        registry = reg
        _queue = _Queue(rows)

        @staticmethod
        def _take(p):
            return p

    sched = ContinuousScheduler(backlog_factor=1)  # per-model cap = 4
    assert sched._fill(_Srv)
    # heavy parked at its cap, light's rows flowed past it, the
    # cap-breaking heavy row is held (not dropped), heavy-5 still queued
    assert len(sched._backlog["heavy"]) == 4
    assert len(sched._backlog["light"]) == 2
    assert sched._held is not None and sched._held.model == "heavy"
    assert len(_Srv._queue.items) == 1
    assert sched.backlog() == 7  # 4 + 2 + held
    # an admission round frees heavy room; the next fill places the
    # held row and keeps pulling
    batch = sched._admit(_Srv)
    by_model = {}
    for p in batch:
        by_model[p.model] = by_model.get(p.model, 0) + 1
    assert by_model["light"] >= 2  # weight 3 model is not starved
    assert sched._fill(_Srv)
    assert sched._held is None and not _Srv._queue.items
    # nothing vanishes at stop(): drain hands back every held row
    sched._held = pend("heavy", 9)
    drained = sched.drain_rows()
    assert {p.uuid for p in drained} \
        >= {"heavy-9"} and sched.backlog() == 0


def test_scheduler_attach_rejects_second_server():
    """One scheduler instance per server: the continuous backlog is
    per-instance mutable state, so silently rebinding would let two
    assembly threads interleave on one deque."""
    sched = ContinuousScheduler()
    a = ClusterServing(_Stub(1.0), scheduler=sched)
    try:
        with pytest.raises(ValueError, match="already attached"):
            ClusterServing(_Stub(1.0), scheduler=sched)
    finally:
        a.stop()


def test_admission_gate_counts_scheduler_backlog():
    """The continuous scheduler eagerly drains the native queue into
    its backlog, so the admission gate must count backlog rows too —
    otherwise a saturated replica reads as empty at the door and the
    router never gets the retryable ``queue full`` it fails over on."""
    srv = ClusterServing(_Stub(1.0), batch_size=4,
                         scheduler="continuous", admission_queue_limit=3)
    try:
        assert srv._admission_reject(None) is None
        srv.scheduler._backlog = {"default": deque(
            _Pending(f"u{i}", np.ones(2, np.float32), None, None, None)
            for i in range(3))}
        reason = srv._admission_reject(None)
        assert reason is not None and "queue full" in reason
        # the deadline gate's depth >= 1 condition sees backlog too
        # (1 row: below the queue-full limit, above the depth gate)
        srv.scheduler._backlog["default"] = deque(
            [_Pending("u", np.ones(2, np.float32), None, None, None)])
        srv._wait_ewma = 50.0
        assert "deadline unattainable" in srv._admission_reject(1)
    finally:
        # the synthetic rows have no sockets for stop()'s drain replies
        srv.scheduler._backlog.clear()
        srv.stop()


def test_init_failure_closes_listening_socket():
    """Scheduler validation happens after the TCP socket goes
    listening; a raising constructor must close it, or a corrected
    retry on the same fixed port hits EADDRINUSE."""
    import socket as socket_mod
    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    with pytest.raises(ValueError, match="unknown scheduler"):
        ClusterServing(_Stub(1.0), port=port, scheduler="continuos")
    srv = ClusterServing(_Stub(1.0), port=port)  # port must be free
    srv.stop()


# -- model registry -----------------------------------------------------------

def test_resolve_begin_is_atomic_with_drain():
    """``resolve(begin=True)`` increments in-flight inside the same
    lock hold — a swap's drain can never observe zero in-flight while a
    batch sits between resolution and dispatch (as the separate
    resolve-then-begin() calls allowed)."""
    reg = ModelRegistry()
    reg.register("m", _Stub(2.0))
    m, name, ver = reg.resolve("m", begin=True)
    assert reg.inflight("m", ver) == 1
    assert not reg.drain_version("m", ver, timeout=0.05)
    reg.done(name, ver)
    assert reg.drain_version("m", ver, timeout=0.05)


def test_unload_retires_per_version_metric_series():
    """Refresh-style swaps mint monotone versions; unloading a version
    must retire its ``server.requests{model=,version=}`` series (and
    the handle cache) or a long-lived server's scrape grows without
    bound."""
    reg = metrics.MetricsRegistry()
    with ClusterServing(_Stub(2.0), batch_size=4, metrics=reg) as srv:
        x = np.ones(4, np.float32)
        np.testing.assert_allclose(_roundtrip(srv, x), 2.0 * x)
        v1_series = "server.requests{model=default,version=v1}"
        assert v1_series in reg.snapshot()
        srv.update_model(_Stub(5.0))  # keep_old=False: unloads v1
        np.testing.assert_allclose(_roundtrip(srv, x), 5.0 * x)
        snap = reg.snapshot()
        assert v1_series not in snap, "v1 series must retire with v1"
        assert "server.requests{model=default,version=v2}" in snap
        assert ("default", "v1") not in srv._m_model_series
        # a batch still in flight on the unloaded version (drain=False
        # swap tail) must not resurrect the retired series
        c, hist = srv._model_series("default", "v1")
        c.inc()
        hist.observe(4)
        assert v1_series not in reg.snapshot(), "series resurrected"


def test_stopped_servers_deregister_registry_unload_hook():
    """A prebuilt registry reused across server lifecycles (rolling
    restarts) must not accumulate unload hooks retaining every stopped
    server."""
    reg = ModelRegistry()
    reg.register("m", _Stub(1.0))
    for _ in range(3):
        srv = ClusterServing(models=reg, batch_size=4)
        srv.stop()
    assert not reg._unload_hooks


def test_registry_metrics_repoint_across_server_lifecycles():
    """A prebuilt registry that never chose its own metrics follows
    EACH hosting server's injected registry — the first server's
    repoint must not read as 'deliberately wired' and pin swap counts
    to a stopped server's scrape.  A registry constructed WITH its own
    metrics keeps them."""
    reg = ModelRegistry()
    reg.register("m", _Stub(1.0))
    m_a, m_b = metrics.MetricsRegistry(), metrics.MetricsRegistry()
    ClusterServing(models=reg, batch_size=4, metrics=m_a).stop()
    srv = ClusterServing(models=reg, batch_size=4, metrics=m_b)
    try:
        reg.swap("m", _Stub(2.0), keep_old=False)
        assert m_b.snapshot()["registry.swaps"] == 1
        assert m_a.snapshot()["registry.swaps"] == 0
    finally:
        srv.stop()
    own = metrics.MetricsRegistry()
    reg2 = ModelRegistry(metrics=own)
    reg2.register("m", _Stub(1.0))
    srv2 = ClusterServing(models=reg2, batch_size=4, metrics=m_a)
    try:
        reg2.swap("m", _Stub(2.0), keep_old=False)
        assert own.snapshot()["registry.swaps"] == 1
    finally:
        srv2.stop()


def test_canary_pin_on_active_version_merges_into_one_batch():
    """Rows pinning the currently-active version and unpinned rows
    resolve to the same executable — assembly must merge them into ONE
    device batch, not two half-size ones."""
    with ClusterServing(_Stub(2.0), batch_size=4,
                        batch_timeout_ms=400) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        x = np.ones(3, np.float32)
        u1 = iq.enqueue("a", t=x)                 # unpinned
        u2 = iq.enqueue("b", version="v1", t=x)   # pinned to the active
        np.testing.assert_allclose(oq.query(u1, timeout=15.0), 2.0 * x)
        np.testing.assert_allclose(oq.query(u2, timeout=15.0), 2.0 * x)
        assert srv.stats()["batches"] == 1, srv.stats()
        iq.close()


def test_warm_from_rebuckets_to_incoming_models_buckets():
    """warm_from must warm the shapes THIS model pads to, not copy the
    outgoing model's bucket keys verbatim — a version with different
    batch_buckets would otherwise be 'warmed' for shapes it never
    serves and stall on cold compiles right after the swap."""
    init_orca_context("local")
    import jax

    class M(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(3), x, name="fc")

    m = M()
    v = m.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
    im1 = InferenceModel(batch_buckets=(16,)).load(m, v)
    im1.predict(np.ones((3, 4), np.float32))   # realizes old bucket 16
    im2 = InferenceModel(batch_buckets=(4, 32)).load(m, v)
    warmed = im2.warm_from(im1)
    assert warmed == 2  # re-bucketed to im2's own 4 and 32
    pre = im2.compile_count
    im2.predict(np.ones((3, 4), np.float32))   # pads to ITS bucket 4
    im2.predict(np.ones((20, 4), np.float32))  # pads to ITS bucket 32
    assert im2.compile_count == pre, "post-swap serve compiled cold"


def test_registry_routing_version_pin_and_swap_metric():
    reg = ModelRegistry()
    v1 = reg.register("m", _Stub(2.0))
    assert v1 == "v1" and reg.active_version("m") == "v1"
    with ClusterServing(models=reg, batch_size=4,
                        scheduler="continuous") as srv:
        x = np.ones(4, np.float32)
        np.testing.assert_allclose(_roundtrip(srv, x, model="m"), 2 * x)
        v2 = reg.swap("m", _Stub(4.0))
        assert v2 == "v2" and reg.active_version("m") == "v2"
        np.testing.assert_allclose(_roundtrip(srv, x, model="m"), 4 * x)
        # canary pin: the old version stays loaded and addressable
        np.testing.assert_allclose(
            _roundtrip(srv, x, model="m", version="v1"), 2 * x)
        snap = metrics.get_registry().snapshot()
        assert snap["registry.swaps"] == 1
        # per-model labeled series rode the batches
        assert snap["server.requests{model=m,version=v1}"] >= 2
        assert snap["server.requests{model=m,version=v2}"] >= 1
        assert snap["server.batch_size{model=m}"]["count"] >= 3
        assert any(k.startswith("scheduler.admitted_rows{")
                   for k in snap)


def test_unroutable_requests_get_explicit_errors():
    reg = ModelRegistry()
    reg.register("a", _Stub(1.0))
    reg.register("b", _Stub(1.0))
    with ClusterServing(models=reg, batch_size=4) as srv:
        x = np.ones(4, np.float32)
        with pytest.raises(RuntimeError, match="unknown model"):
            _roundtrip(srv, x, model="nope")
        with pytest.raises(RuntimeError, match="unknown version"):
            _roundtrip(srv, x, model="a", version="v9")
        # two models, no "default" entry: a request naming no model
        # cannot be routed
        with pytest.raises(RuntimeError, match="no model specified"):
            _roundtrip(srv, x)
        assert srv.stats()["unknown_model"] == 3


def test_registry_swap_drains_old_version_inflight():
    reg = ModelRegistry()
    reg.register("m", _Stub(1.0))
    reg.begin("m", "v1")
    state = {}

    def do_swap():
        reg.swap("m", _Stub(2.0), drain=True, drain_timeout=10.0)
        state["done"] = time.monotonic()

    t = threading.Thread(target=do_swap)
    t.start()
    time.sleep(0.2)
    # the flip already happened (new traffic goes to v2)...
    assert reg.active_version("m") == "v2"
    # ...but the swap is still waiting on v1's in-flight batch
    assert "done" not in state
    reg.done("m", "v1")
    t.join(timeout=10)
    assert "done" in state
    assert reg.inflight("m", "v1") == 0


def test_registry_guards():
    reg = ModelRegistry()
    reg.register("m", _Stub(1.0))
    with pytest.raises(ValueError, match="already has a version"):
        reg.register("m", _Stub(2.0), version="v1")
    with pytest.raises(ValueError, match="weight"):
        reg.register("w", _Stub(1.0), weight=0.0)
    with pytest.raises(KeyError):
        reg.resolve("ghost")
    with pytest.raises(KeyError):
        reg.swap("ghost", _Stub(1.0))
    with pytest.raises(ValueError, match="active"):
        reg.unload("m", "v1")
    reg.register("m", _Stub(2.0))  # v2, becomes active
    reg.unload("m", "v1")
    assert reg.versions("m") == ["v2"]
    assert reg.route_error("m", "v1") is not None
    st = reg.stats()
    assert st["m"]["active"] == "v2"
    # auto-numbering is monotone: after unloading v1, the next swap
    # must mint v3 — not collide on the recomputed len()+1 == v2
    assert reg.swap("m", _Stub(3.0)) == "v3"
    # keep_old=False unloads the outgoing ACTIVE version with the swap
    assert reg.swap("m", _Stub(4.0), keep_old=False) == "v4"
    assert "v3" not in reg.versions("m")
    assert reg.active_version("m") == "v4"


def test_update_model_keeps_single_resident_version():
    """The legacy contract REPLACED the model in place; riding the
    registry must not turn periodic weight refreshes into an unbounded
    accumulation of resident versions (weights + executables)."""
    srv = ClusterServing(_Stub(1.0), batch_size=4)
    try:
        for k in range(2, 6):
            srv.update_model(_Stub(float(k)))
        assert len(srv.registry.versions("default")) == 1
        assert srv.model.k == 5.0
        srv.model = _Stub(9.0)  # raw setter: same replace semantics
        assert len(srv.registry.versions("default")) == 1
        assert srv.model.k == 9.0
    finally:
        srv.stop()


def test_concurrent_swaps_serialize_and_leak_nothing():
    """Two upgraders racing ``update_model`` must not interleave
    warm/flip/unload — an interleaving would strand a never-active
    resident version."""
    srv = ClusterServing(_Stub(1.0), batch_size=4)
    try:
        threads = [threading.Thread(
            target=lambda k=k: srv.update_model(_Stub(float(k))))
            for k in range(2, 10)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert len(srv.registry.versions("default")) == 1
        assert srv.model.k in {float(k) for k in range(2, 10)}
    finally:
        srv.stop()


def test_multi_model_server_model_accessors_raise_clearly():
    srv = ClusterServing(models={"a": _Stub(1.0), "b": _Stub(2.0)},
                         batch_size=4)
    try:
        with pytest.raises(AttributeError, match="no single .model"):
            srv.model
        with pytest.raises(AttributeError, match="no single .model"):
            srv.model = _Stub(3.0)
        with pytest.raises(ValueError, match="registry.swap"):
            srv.update_model(_Stub(3.0))
    finally:
        srv.stop()


def test_prebuilt_registry_follows_injected_metrics():
    """The PR-3 custom-registry injection lesson applied to
    registry.swaps: a prebuilt ModelRegistry built against the global
    metrics follows the server's injected registry."""
    reg = ModelRegistry()
    reg.register("m", _Stub(1.0))
    custom = metrics.MetricsRegistry()
    srv = ClusterServing(models=reg, batch_size=4, metrics=custom)
    try:
        reg.swap("m", _Stub(2.0))
        assert custom.snapshot().get("registry.swaps") == 1
    finally:
        srv.stop()


# -- hot swap: warm before flip ----------------------------------------------

def _lambda_model(bias, buckets=(1, 4)):
    init_orca_context("local")
    import jax
    m = nn.Sequential([nn.Lambda(lambda x: x * 0.0 + bias)])
    v = m.init(jax.random.PRNGKey(0), np.ones((1, 4), np.float32))
    return InferenceModel(batch_buckets=buckets).load(m, v)


def test_update_model_warms_before_flip():
    """The pre-registry ``update_model`` just assigned ``self.model``,
    so the first post-swap batches ate a fresh XLA compile per shape
    bucket.  Now the incoming model is warmed (the active version's
    compiled keys are copied) BEFORE the flip."""
    v1 = _lambda_model(1.0)
    v1.predict(np.ones((1, 4), np.float32))   # bucket 1
    v1.predict(np.ones((3, 4), np.float32))   # bucket 4
    assert v1.compile_count == 2
    v2 = _lambda_model(2.0)
    srv = ClusterServing(v1, batch_size=4)
    try:
        srv.update_model(v2)
        assert set(v2._compiled) >= set(v1._compiled)
        assert v2.compile_count == 2  # warmed, not cold-swapped
        assert srv.model is v2
    finally:
        srv.stop()


def test_hot_swap_under_load_zero_failures_zero_compiles():
    """THE acceptance: swapping the model version under 4-thread client
    load yields ZERO client-visible failures and zero post-warmup XLA
    compiles (compile-counter asserted), and replies flip from v1's
    output to v2's."""
    v1 = _lambda_model(1.0)
    v1.warm([(4,)])  # AOT-precompile every bucket before opening the port
    with ClusterServing(v1, batch_size=4, scheduler="continuous") as srv:
        stop_flag = threading.Event()
        failures = []
        seen = {1.0: 0, 2.0: 0}
        seen_lock = threading.Lock()

        def client(i):
            iq = InputQueue(srv.host, srv.port)
            oq = OutputQueue(input_queue=iq)
            try:
                while not stop_flag.is_set():
                    uid = iq.enqueue(f"c{i}",
                                     t=np.ones(4, np.float32))
                    out = oq.query(uid, timeout=15.0)
                    if out is None:
                        failures.append("timeout")
                        continue
                    val = float(out[0])
                    if val not in (1.0, 2.0):
                        failures.append(f"garbage value {val}")
                        continue
                    with seen_lock:
                        seen[val] += 1
            except Exception as e:  # noqa: BLE001 — recorded
                failures.append(f"{type(e).__name__}: {e}")
            finally:
                iq.close()

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.4)  # load flowing on v1
        v2 = _lambda_model(2.0)
        srv.update_model(v2)  # warm → flip, under load
        compiles_after_swap = v2.compile_count
        time.sleep(0.6)  # load flowing on v2
        stop_flag.set()
        for t in threads:
            t.join(timeout=30)
        assert not failures, failures[:5]
        assert seen[1.0] > 0 and seen[2.0] > 0, seen
        # zero post-warmup compiles: warming covered every bucket the
        # post-swap traffic hit
        assert v2.compile_count == compiles_after_swap
        assert v2.compile_count == len(v1._compiled)
        st = srv.stats()
        assert st["errors"] == 0, st
        assert st["requests"] == st["replies"], st
    assert metrics.get_registry().snapshot()["registry.swaps"] == 1


# -- AOT executable persistence across versions (satellite) -------------------

def test_aot_executables_persist_across_versions(tmp_path):
    """``save_executables``/``load_executables`` round-trip across TWO
    loaded versions of the same model: the exported artifact takes the
    variables as a call argument, so v2 (same structure, different
    weights) reuses v1's executables — the v1→v2 swap costs zero
    compiles (compile-counter asserted) and still serves v2's math."""
    init_orca_context("local")
    import jax

    class M(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(3), x, name="fc")

    m = M()
    vars1 = m.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
    vars2 = m.init(jax.random.PRNGKey(1), np.zeros((1, 4), np.float32))
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)

    im1 = InferenceModel(batch_buckets=(1, 4)).load(m, vars1)
    out1 = im1.predict(x)           # compiles buckets 1 is unused; 4 used
    im1.predict(x[:1])              # bucket 1 too
    assert im1.compile_count == 2
    assert im1.save_executables(str(tmp_path)) == 2

    im2 = InferenceModel(batch_buckets=(1, 4)).load(m, vars2)
    assert im2.load_executables(str(tmp_path)) == 2
    out2 = im2.predict(x)
    assert im2.compile_count == 0   # the swap reused cached executables
    # and it genuinely serves the NEW version's weights
    ref = InferenceModel(batch_buckets=(1, 4)).load(m, vars2).predict(x)
    np.testing.assert_allclose(out2, ref, rtol=1e-5, atol=1e-5)
    assert not np.allclose(out1, out2)


# -- HTTP frontend routing ----------------------------------------------------

def test_http_frontend_routes_by_model():
    reg = ModelRegistry()
    reg.register("double", _Stub(2.0))
    reg.register("neg", _Stub(-1.0))
    with ClusterServing(models=reg, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}/predict"

            def post(body):
                req = urllib.request.Request(
                    url, data=json.dumps(body).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=15) as r:
                    return json.load(r)["predictions"]

            out = post({"instances": [[1, 2, 3, 4]], "model": "double"})
            np.testing.assert_allclose(np.asarray(out),
                                       [[2, 4, 6, 8]])
            out = post({"instances": [[1, 2, 3, 4]], "model": "neg"})
            np.testing.assert_allclose(np.asarray(out),
                                       [[-1, -2, -3, -4]])
            with pytest.raises(urllib.error.HTTPError) as ei:
                post({"instances": [[1, 2, 3, 4]], "model": "ghost"})
            assert ei.value.code == 404
