"""Unit tests for bench.py's parent-side retry/variance harness.

The measurement children need the real chip; the PARENT's logic —
record parsing, the rel_spread contended-window retry, best-contended
fallback, skip records — is pure control flow and testable with a faked
``subprocess.run``.  (VERDICT r4 task 2: bench numbers must carry
variance evidence and never lose the headline record.)
"""

import json
import sys
import types

import pytest

sys.path.insert(0, __import__("os").path.dirname(
    __import__("os").path.dirname(__import__("os").path.abspath(__file__))))

import bench


@pytest.fixture(autouse=True)
def _no_ambient_force_cpu(monkeypatch):
    """Chipless CI exports BENCH_FORCE_CPU=1 (README runbook); these
    tests exercise the preflight's PROBE logic, which that variable
    short-circuits — clear it so they pass either way.  The one test
    that wants the short-circuit sets it back explicitly."""
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)


def _fake_proc(record: dict, rc: int = 0) -> types.SimpleNamespace:
    return types.SimpleNamespace(returncode=rc,
                                 stdout=json.dumps(record) + "\n",
                                 stderr="")


def _record(value: float, spread: float) -> dict:
    return {"metric": "bert_base_train_tokens_per_sec_per_chip",
            "value": value, "unit": "tokens/s/chip", "vs_baseline": 1.0,
            "detail": {"rel_spread": spread}}


def _run(monkeypatch, capsys, procs, attempts):
    calls = iter(procs)
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: next(calls))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    rc = bench._run_child("bert", attempts=attempts)
    out = [json.loads(l) for l in
           capsys.readouterr().out.strip().splitlines()]
    assert len(out) == 1
    return rc, out[0]


def test_clean_window_passes_through(monkeypatch, capsys):
    rc, rec = _run(monkeypatch, capsys,
                   [_fake_proc(_record(100.0, 0.02))], attempts=3)
    assert rc == 0
    assert rec["value"] == 100.0
    assert "contended" not in rec["detail"]


def test_contended_window_retries_then_clean(monkeypatch, capsys):
    rc, rec = _run(monkeypatch, capsys,
                   [_fake_proc(_record(80.0, 0.30)),
                    _fake_proc(_record(100.0, 0.03))], attempts=3)
    assert rc == 0
    assert rec["value"] == 100.0
    assert "contended" not in rec["detail"]


def test_never_settles_emits_best_with_contended_flag(monkeypatch, capsys):
    rc, rec = _run(monkeypatch, capsys,
                   [_fake_proc(_record(80.0, 0.30)),
                    _fake_proc(_record(120.0, 0.25)),
                    _fake_proc(_record(90.0, 0.20))], attempts=3)
    assert rc == 0
    assert rec["value"] == 120.0  # best contended attempt, not the last
    assert rec["detail"]["contended"] is True


def test_contended_then_hard_failures_still_emits_the_measurement(
        monkeypatch, capsys):
    """A real (contended) measurement must survive even if the retries
    spent hunting a cleaner window crash: evidence beats a skip."""
    rc, rec = _run(monkeypatch, capsys,
                   [_fake_proc(_record(95.0, 0.30)),
                    _fake_proc({}, rc=1), _fake_proc({}, rc=1)],
                   attempts=3)
    assert rc == 0
    assert rec["value"] == 95.0
    assert rec["detail"]["contended"] is True


def test_exhausted_failures_emit_skip_record(monkeypatch, capsys):
    rc, rec = _run(monkeypatch, capsys,
                   [_fake_proc({}, rc=1), _fake_proc({}, rc=1)],
                   attempts=2)
    assert rc == 1
    assert rec["metric"] == "bert_skipped"
    assert "skipped" in rec["detail"]


def test_mfu_configs_print_last():
    """The driver records only the stdout TAIL: the acceptance-bar
    records (resnet50, bert) must be the final lines of the matrix."""
    assert bench.CONFIGS[-2:] == ("resnet50", "bert")


def test_device_preflight_returns_on_success(monkeypatch):
    calls = []

    def fake_run(*a, **k):
        calls.append(1)
        return types.SimpleNamespace(returncode=0, stdout="1.0\n",
                                     stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    assert bench._device_preflight(max_wait_s=5) is True
    assert len(calls) == 1


def test_device_preflight_bails_fast_on_deterministic_failure(
        monkeypatch):
    """Instant nonzero exits (broken env) must not burn the wait
    budget — only hangs/slow errors are worth waiting out."""
    calls = []

    def fake_run(*a, **k):
        calls.append(1)
        return types.SimpleNamespace(returncode=1, stdout="",
                                     stderr="boom")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    # probes return "instantly": monotonic advances 1s per call
    t = iter(range(0, 100_000))
    monkeypatch.setattr(bench.time, "monotonic", lambda: next(t))
    assert bench._device_preflight(max_wait_s=10_000) is False
    assert len(calls) == 3


def test_device_preflight_waits_out_slow_errors(monkeypatch):
    """A nonzero exit that took ~probe-timeout (RPC deadline surfacing
    as an error) is outage weather, not deterministic breakage: the
    preflight keeps waiting instead of bailing after 3."""
    calls = []

    def fake_run(*a, **k):
        calls.append(1)
        return types.SimpleNamespace(returncode=1, stdout="",
                                     stderr="DEADLINE_EXCEEDED")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    t = iter(range(0, 100_000, 100))  # each probe "takes" 100s
    monkeypatch.setattr(bench.time, "monotonic", lambda: next(t))
    assert bench._device_preflight(max_wait_s=1300) is False
    assert len(calls) >= 4  # past the 3-failure point: no bail-out


def test_device_preflight_waits_out_hangs(monkeypatch):
    def fake_run(*a, **k):
        raise bench.subprocess.TimeoutExpired(cmd="probe", timeout=1)

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    t = iter(range(0, 10_000, 100))  # monotonic advances 100s per call
    monkeypatch.setattr(bench.time, "monotonic", lambda: next(t))
    assert bench._device_preflight(max_wait_s=250) is False


def test_device_preflight_skips_on_forced_cpu(monkeypatch):
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: (_ for _ in ()).throw(
                            AssertionError("must not probe")))
    assert bench._device_preflight() is True


def test_degraded_mode_short_leashes_device_configs(monkeypatch):
    """After a failed preflight, device configs get one short attempt
    (fast skip records); the CPU-sim scaling config keeps its budget."""
    seen = {}

    def fake_run(cmd, **k):
        seen[cmd[cmd.index("--config") + 1]] = k["timeout"]
        return types.SimpleNamespace(returncode=1, stdout="", stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._run_child("bert", degraded=True) == 1
    assert seen["bert"] == 240
    assert bench._run_child("scaling", degraded=True) == 1
    assert seen["scaling"] == bench._BUDGET["scaling"][0]


def test_degraded_mode_honors_explicit_attempts(monkeypatch):
    seen = []

    def fake_run(cmd, **k):
        seen.append(k["timeout"])
        return types.SimpleNamespace(returncode=1, stdout="", stderr="")

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._run_child("bert", attempts=3, degraded=True) == 1
    assert len(seen) == 3  # explicit attempts win over the short leash
    assert all(t == 240 for t in seen)


def test_degraded_skip_record_is_marked(monkeypatch, capsys):
    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: types.SimpleNamespace(
                            returncode=1, stdout="", stderr=""))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench._run_child("bert", degraded=True) == 1
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["detail"]["degraded"] is True
