"""Transfer-learning + autograd parity tests (reference: GraphNet surgery
newGraph/freezeUpTo in pipeline/api/net, CustomLoss in pipeline/api/
autograd — SURVEY.md §2.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def _backbone_head():
    class Model(nn.Module):
        def forward(self, scope, x):
            h = scope.child(nn.Dense(16, activation="relu"), x,
                            name="backbone")
            return scope.child(nn.Dense(2), h, name="head")
    return Model()


def test_frozen_params_do_not_move():
    from analytics_zoo_tpu.orca.learn import Estimator
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = rng.integers(0, 2, 64).astype(np.int32)
    est = Estimator.from_keras(_backbone_head(),
                               loss="sparse_categorical_crossentropy",
                               optimizer="adamw", learning_rate=5e-2,
                               frozen=["backbone"])
    est.fit((x, y), epochs=2, batch_size=32, verbose=False)
    params = jax.device_get(est._ts["params"])
    # re-init to compare: frozen backbone must equal its initialization
    ref = est.model.init(jax.random.PRNGKey(est.seed), jnp.asarray(x[:1]),
                         training=True)["params"]
    np.testing.assert_array_equal(params["backbone"]["kernel"],
                                  np.asarray(ref["backbone"]["kernel"]))
    # the head DID train
    assert not np.allclose(params["head"]["kernel"],
                           np.asarray(ref["head"]["kernel"]))


def test_frozen_survives_save_load(tmp_path):
    from analytics_zoo_tpu.orca.learn import Estimator
    rng = np.random.default_rng(1)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    est = Estimator.from_keras(_backbone_head(),
                               loss="sparse_categorical_crossentropy",
                               frozen=["backbone"])
    est.fit((x, y), epochs=1, batch_size=16, verbose=False)
    d = str(tmp_path / "ck")
    est.save(d)
    est2 = Estimator.from_keras(_backbone_head(),
                                loss="sparse_categorical_crossentropy",
                                frozen=["backbone"])
    est2.load(d)
    before = np.asarray(jax.device_get(
        est2._ts["params"]["backbone"]["kernel"]))
    est2.fit((x, y), epochs=1, batch_size=16, verbose=False)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(est2._ts["params"]["backbone"]["kernel"])),
        before)


def test_apply_with_taps_records_all_paths():
    model = nn.Sequential([nn.Dense(4, name="a"), nn.Dense(3, name="b")])
    x = jnp.ones((2, 5))
    variables = model.init(jax.random.PRNGKey(0), x)
    out, _, taps = model.apply_with_taps(variables, x)
    assert "a" in taps and "b" in taps, sorted(taps)
    assert taps["a"].shape == (2, 4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(taps["b"]))


def test_graphnet_feature_extraction_shares_weights():
    from analytics_zoo_tpu.models import GraphNet
    base = _backbone_head()
    x = jnp.ones((2, 8))
    variables = base.init(jax.random.PRNGKey(0), x)
    feat = GraphNet(base, ["backbone"])
    out, _ = feat.apply(variables, x)        # same variable tree as base
    assert out.shape == (2, 16)
    # matches running the backbone layer manually
    full, _, taps = base.apply_with_taps(variables, x)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(taps["backbone"]))


def test_graphnet_embedded_in_new_model_trains_new_head():
    from analytics_zoo_tpu.models import GraphNet
    from analytics_zoo_tpu.orca.learn import Estimator
    base = _backbone_head()

    class FineTune(nn.Module):
        def forward(self, scope, x):
            feats = scope.child(GraphNet(base, ["backbone"]), x,
                                name="feats")
            return scope.child(nn.Dense(3), feats, name="new_head")

    rng = np.random.default_rng(2)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 3, 32).astype(np.int32)
    est = Estimator.from_keras(FineTune(),
                               loss="sparse_categorical_crossentropy",
                               frozen=["feats"])
    hist = est.fit((x, y), epochs=2, batch_size=16, verbose=False)
    assert np.isfinite(hist["loss"][-1])
    preds = est.predict(x, batch_size=16)
    assert preds.shape == (32, 3)


def test_custom_loss_autograd_surface():
    from analytics_zoo_tpu import autograd as A
    from analytics_zoo_tpu.orca.learn import Estimator
    loss = A.CustomLoss(
        lambda y_true, y_pred: A.mean(A.square(y_true - y_pred), axis=-1))
    rng = np.random.default_rng(3)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = rng.normal(size=(32, 1)).astype(np.float32)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss=loss,
                               learning_rate=5e-2)
    hist = est.fit((x, y), epochs=3, batch_size=16, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]
    # spot-check a few parity functions
    v = jnp.asarray([-2.0, 3.0])
    np.testing.assert_allclose(A.l2_normalize(v),
                               np.asarray(v) / np.linalg.norm(v), rtol=1e-5)
    a = jnp.ones((2, 3, 4))
    b = jnp.ones((2, 4, 5))
    assert A.batch_dot(a, b, axes=(2, 1)).shape == (2, 3, 5)


def test_bert_ner_shapes_and_training():
    from analytics_zoo_tpu.models import BERTNER
    from analytics_zoo_tpu.orca.learn import Estimator
    rng = np.random.default_rng(4)
    x = rng.integers(0, 50, (16, 12)).astype(np.int32)
    y = rng.integers(0, 5, (16, 12)).astype(np.int32)
    model = BERTNER(entity_num=5, vocab_size=50, hidden_size=32,
                    n_layers=1, n_heads=2, max_position=16)
    est = Estimator.from_keras(model,
                               loss="sparse_categorical_crossentropy",
                               metrics=["accuracy"])
    hist = est.fit((x, y), epochs=1, batch_size=8, verbose=False)
    assert np.isfinite(hist["loss"][0])
    preds = est.predict(x, batch_size=8)
    assert preds.shape == (16, 12, 5)
def test_frozen_prefix_matches_component_boundaries():
    """Regression (round-2 advisor): frozen=["enc"] must not freeze the
    sibling subtree "enc_head"."""
    class M(nn.Module):
        def forward(self, scope, x):
            h = scope.child(nn.Dense(4), x, name="enc")
            return scope.child(nn.Dense(2), h, name="enc_head")

    from analytics_zoo_tpu.orca.learn import Estimator
    est = Estimator.from_keras(M(), loss="mse", optimizer="sgd",
                               learning_rate=0.5, frozen=["enc"])
    x = np.random.default_rng(0).normal(size=(16, 3)).astype("float32")
    y = np.random.default_rng(1).normal(size=(16, 2)).astype("float32")
    est.fit((x, y), epochs=2, batch_size=8, verbose=False)
    ref = est.model.init(jax.random.PRNGKey(est.seed), jnp.asarray(x[:1]),
                         training=True)["params"]
    got = jax.device_get(est._ts["params"])
    # frozen subtree identical to its init ...
    np.testing.assert_array_equal(np.asarray(got["enc"]["kernel"]),
                                  np.asarray(ref["enc"]["kernel"]))
    # ... while the prefix-colliding sibling DID train
    assert np.abs(np.asarray(got["enc_head"]["kernel"]) -
                  np.asarray(ref["enc_head"]["kernel"])).max() > 1e-6


def test_custom_loss_forward_traceable_under_jit():
    """Regression (round-2 advisor): CustomLoss.forward must return the jnp
    scalar, not float(), so it works inside jit/grad traces."""
    from analytics_zoo_tpu import autograd as A
    loss = A.CustomLoss(lambda y_true, y_pred: (y_pred - y_true) ** 2)

    @jax.jit
    def f(p, t):
        return loss.forward(t, p)

    out = f(jnp.ones((4, 2)), jnp.zeros((4, 2)))
    assert float(out) == pytest.approx(1.0)
