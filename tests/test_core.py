"""Core runtime tests: context/mesh bootstrap, config, checkpoint round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.core import (MeshConfig, OrcaContext, ZooConfig,
                                    checkpoint, get_mesh, init_orca_context,
                                    make_mesh, stop_orca_context)


def test_init_local_context_default_mesh():
    mesh = init_orca_context("local")
    assert mesh.devices.size == 8  # conftest forces 8 CPU devices
    assert mesh.axis_names == ("data",)
    assert OrcaContext.initialized
    assert OrcaContext.mesh is mesh


def test_init_twice_reuses():
    m1 = init_orca_context("local")
    m2 = init_orca_context("local")
    assert m1 is m2


def test_mesh_shape_axes():
    mesh = init_orca_context("local", mesh_shape={"data": 2, "model": 4})
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 2, "model": 4}


def test_mesh_auto_axis():
    mesh = make_mesh({"data": 0, "model": 2})
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 4, "model": 2}


def test_mesh_bad_shape_raises():
    with pytest.raises(ValueError):
        make_mesh({"data": 16})  # more than the 8 available
    with pytest.raises(ValueError):
        make_mesh({"data": 0, "model": 3})  # 3 does not divide 8
    with pytest.raises(ValueError):
        MeshConfig(data=0, model=0).resolved(8)  # two wildcards


def test_mesh_subset_of_devices():
    mesh = make_mesh({"data": 2})  # debugging subset on an 8-device host
    assert mesh.devices.size == 2


def test_get_mesh_autoinit():
    mesh = get_mesh()
    assert mesh.devices.size == 8


def test_psum_on_mesh():
    """Real collective on the virtual mesh — the backbone of data parallelism."""
    mesh = init_orca_context("local")
    from jax.sharding import NamedSharding, PartitionSpec as P

    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    xs = jax.device_put(x, NamedSharding(mesh, P("data", None)))

    def f(v):
        return jax.lax.psum(v.sum(), "data")

    try:
        from jax import shard_map  # jax >= 0.4.35: top-level callable
    except ImportError:  # older jax: the experimental namespace
        from jax.experimental.shard_map import shard_map
    out = jax.jit(
        shard_map(f, mesh=mesh, in_specs=P("data", None), out_specs=P())
    )(xs)
    assert float(out) == x.sum()


def test_config_from_dict_and_extra():
    cfg = ZooConfig.from_dict({
        "cluster_mode": "local",
        "mesh": {"data": 2, "model": 4},
        "custom_knob": 42,
    })
    assert cfg.mesh.model == 4
    assert cfg.extra["custom_knob"] == 42


def test_config_yaml_fallback(tmp_path):
    p = tmp_path / "conf.yaml"
    p.write_text("cluster_mode: local\nmesh:\n  data: 2\n  model: 4\n"
                 "pandas_read_backend: pandas\nremat: true\n")
    cfg = ZooConfig.from_file(str(p))
    assert cfg.mesh.model == 4
    assert cfg.remat is True


def test_checkpoint_roundtrip(tmp_path):
    tree = {
        "params": {"dense": {"w": np.ones((3, 4), np.float32),
                             "b": np.zeros((4,), np.float32)}},
        "step": 7,
        "lr": 0.1,
        "name": "m",
        "flags": (True, None),
        "history": [np.arange(5), 2.5],
    }
    path = checkpoint.save(str(tmp_path / "ckpt"), tree, step=7)
    back = checkpoint.restore(path)
    assert back["step"] == 7 and back["lr"] == 0.1 and back["name"] == "m"
    assert back["flags"] == (True, None)
    np.testing.assert_array_equal(back["params"]["dense"]["w"], tree["params"]["dense"]["w"])
    np.testing.assert_array_equal(back["history"][0], np.arange(5))
    assert checkpoint.latest_step(path) == 7
    assert checkpoint.exists(path)


def test_checkpoint_jax_arrays(tmp_path):
    tree = {"w": jnp.ones((2, 2)) * 3}
    path = checkpoint.save(str(tmp_path / "c"), tree)
    back = checkpoint.restore(path)
    np.testing.assert_array_equal(back["w"], np.ones((2, 2)) * 3)


def test_checkpoint_bfloat16_roundtrip(tmp_path):
    # npz alone degrades ml_dtypes to raw void; the uint-view encoding must
    # bring back real bfloat16 (the TPU-default training dtype)
    tree = {"w": jnp.asarray([[1.5, -2.0], [0.25, 3.0]], jnp.bfloat16),
            "f8": jnp.asarray([1.0, 0.5], jnp.float8_e4m3fn)}
    path = checkpoint.save(str(tmp_path / "c"), tree)
    back = checkpoint.restore(path)
    assert back["w"].dtype == jnp.bfloat16
    assert back["f8"].dtype == jnp.float8_e4m3fn
    np.testing.assert_array_equal(np.asarray(back["w"], np.float32),
                                  [[1.5, -2.0], [0.25, 3.0]])


def test_checkpoint_repeated_save_gc(tmp_path):
    d = str(tmp_path / "c")
    for k in range(3):
        checkpoint.save(d, {"w": np.full((2,), k, np.float32)}, step=k)
    back = checkpoint.restore(d)
    assert back["w"][0] == 2 and checkpoint.latest_step(d) == 2
    import os
    npzs = [n for n in os.listdir(d) if n.endswith(".npz")]
    assert len(npzs) == 1, npzs  # stale generations garbage-collected


def test_summary_writer(tmp_path):
    from analytics_zoo_tpu.core import SummaryWriter
    w = SummaryWriter(str(tmp_path), "train")
    for i in range(3):
        w.add_scalar("loss", 1.0 / (i + 1), i)
    w.close()
    scalars = SummaryWriter(str(tmp_path), "train").read_scalar("loss")
    assert [s for s, _ in scalars] == [0, 1, 2]
    assert scalars[0][1] == 1.0
