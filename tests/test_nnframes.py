"""NNFrames tests (reference pattern: nnframes/NNEstimatorSpec + NNClassifier
python tests — fit from DataFrame cols, transform appends prediction)."""

import numpy as np
import pytest

pd = pytest.importorskip("pandas")

from analytics_zoo_tpu.core import init_orca_context  # noqa: E402


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def _mlp(out_dim):
    import analytics_zoo_tpu.nn as nn
    return nn.Sequential([nn.Dense(16, activation="relu"),
                          nn.Dense(out_dim)])


def test_nnestimator_fit_transform_regression():
    from analytics_zoo_tpu.nnframes import NNEstimator
    rng = np.random.default_rng(0)
    df = pd.DataFrame({
        "f1": rng.normal(size=80), "f2": rng.normal(size=80),
        "label": rng.normal(size=80),
    })
    est = (NNEstimator(_mlp(1), criterion="mse")
           .setFeaturesCol("f1", "f2").setLabelCol("label")
           .setBatchSize(16).setMaxEpoch(2).setLearningRate(1e-2))
    model = est.fit(df)
    out = model.transform(df)
    assert "prediction" in out.columns and len(out) == len(df)
    assert np.asarray(out["prediction"].tolist()).shape == (80, 1)
    # original frame untouched (transform copies)
    assert "prediction" not in df.columns


def test_nnclassifier_argmax_and_array_features():
    from analytics_zoo_tpu.nnframes import NNClassifier
    rng = np.random.default_rng(1)
    feats = [rng.normal(size=4).astype(np.float32) for _ in range(60)]
    labels = [int(f.sum() > 0) for f in feats]
    df = pd.DataFrame({"features": feats, "label": labels})
    clf = (NNClassifier(_mlp(2))
           .setBatchSize(16).setMaxEpoch(8).setLearningRate(5e-2))
    model = clf.fit(df)
    out = model.setPredictionCol("cls").transform(df)
    preds = np.asarray(out["cls"].tolist())
    assert preds.dtype.kind == "i"
    assert (preds == np.asarray(labels)).mean() > 0.7


def test_nnmodel_transform_xshards():
    from analytics_zoo_tpu.data import XShards
    from analytics_zoo_tpu.nnframes import NNEstimator
    rng = np.random.default_rng(2)
    frames = [pd.DataFrame({"a": rng.normal(size=20),
                            "label": rng.normal(size=20)})
              for _ in range(3)]
    shards = XShards(frames)
    est = (NNEstimator(_mlp(1), criterion="mse")
           .setFeaturesCol("a").setBatchSize(10).setMaxEpoch(1))
    model = est.fit(shards)
    out = model.transform(shards)
    frames_out = out.collect()
    assert len(frames_out) == 3
    assert all("prediction" in f.columns and len(f) == 20
               for f in frames_out)


def test_preprocessing_hook():
    from analytics_zoo_tpu.nnframes import NNEstimator
    # feature cells are strings; preprocessing parses them (the reference's
    # Preprocessing[F, T] converter analog)
    df = pd.DataFrame({"features": ["1,2", "3,4", "5,6", "2,1"] * 8,
                       "label": [0.5, 1.2, 1.8, 0.6] * 8})
    est = NNEstimator(
        _mlp(1), criterion="mse",
        feature_preprocessing=lambda s: np.fromstring(s, sep=",",
                                                      dtype=np.float32))
    model = est.setBatchSize(8).setMaxEpoch(1).fit(df)
    out = model.transform(df)
    assert len(out) == 32


def test_nnimage_reader_to_classifier(tmp_path):
    pytest.importorskip("PIL")
    from PIL import Image
    from analytics_zoo_tpu.data import ImageNormalize, ImageResize
    from analytics_zoo_tpu.nnframes import NNClassifier, NNImageReader
    rng = np.random.default_rng(3)
    for c, base in (("cat", 40), ("dog", 200)):
        d = tmp_path / c
        d.mkdir()
        for i in range(6):
            arr = np.clip(rng.normal(base, 30, (24, 24, 3)), 0,
                          255).astype(np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg")
    df = NNImageReader.readImages(
        str(tmp_path),
        transforms=[ImageResize(16, 16),
                    ImageNormalize((0.5,) * 3, (0.5,) * 3)])
    assert set(df.columns) >= {"image", "origin", "label", "height"}
    assert len(df) == 12 and df["image"].iloc[0].shape == (16, 16, 3)

    import analytics_zoo_tpu.nn as nn
    model = nn.Sequential([nn.Flatten(), nn.Dense(8, activation="relu"),
                           nn.Dense(2)])
    clf = (NNClassifier(model).setFeaturesCol("image")
           .setBatchSize(4).setMaxEpoch(10).setLearningRate(1e-2))
    nnmodel = clf.fit(df)
    out = nnmodel.transform(df)
    acc = (np.asarray(out["prediction"].tolist())
           == df["label"].to_numpy()).mean()
    assert acc > 0.7
