"""Worker for the 2-process jax.distributed test (run via zoo-launch).

Exercises every multihost-only code path end-to-end on a CPU
cluster-in-a-box (SURVEY.md §4's contract — the reference tested its
distributed layer on clusters-in-a-box, not mocks):

- ``init_orca_context("multihost")`` → jax.distributed.initialize from the
  ZOO_* env vars the launcher sets
- per-process data → ``make_array_from_process_local_data`` (data/feed.py)
- fsdp parameter sharding ACROSS processes (leaves not fully addressable)
- jit train/eval steps whose reductions are global (identical metrics on
  every process, no host-local sums)
- per-host sharded checkpoint save + restore (core/checkpoint.py)

Prints "MULTIHOST_OK <eval_loss>" from every process on success.
"""

import sys

import numpy as np


def main() -> None:
    ckpt_dir = sys.argv[1]
    import jax

    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.core import checkpoint as ckpt_io
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.orca.learn import Estimator

    import os
    expected = int(os.environ.get("ZOO_NUM_PROCESSES", "2"))
    init_orca_context("multihost", mesh_shape={"data": 1, "fsdp": 0})
    assert jax.process_count() == expected, jax.process_count()
    pid = jax.process_index()
    nproc = jax.process_count()

    model = nn.Sequential([
        nn.Dense(32, activation="relu"),
        nn.Dense(32, activation="relu"),
        nn.Dense(2),
    ])

    # identical global dataset on every process; each contributes its slice
    rng = np.random.default_rng(0)
    x_all = rng.normal(size=(64, 8)).astype(np.float32)
    y_all = (x_all.sum(axis=1) > 0).astype(np.int32)
    per = 64 // nproc
    lo, hi = pid * per, (pid + 1) * per
    x_loc, y_loc = x_all[lo:hi], y_all[lo:hi]

    est = Estimator.from_keras(model,
                               loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2, sharding="fsdp",
                               metrics=["accuracy"])
    hist = est.fit((x_loc, y_loc), epochs=2, batch_size=16, verbose=False)
    assert np.isfinite(hist["loss"][-1]), hist

    # fsdp over 4 devices spanning 2 processes → params must be sharded
    # across hosts, or the whole point of the test is lost
    kernel = next(l for l in jax.tree_util.tree_leaves(est._ts["params"])
                  if l.ndim == 2)
    assert not kernel.is_fully_addressable, kernel.sharding

    before = est.evaluate((x_loc, y_loc), batch_size=16)
    assert np.isfinite(before["loss"]), before

    est.save(ckpt_dir)

    # fresh estimator; restore must reproduce the eval exactly
    est2 = Estimator.from_keras(model,
                                loss="sparse_categorical_crossentropy",
                                learning_rate=1e-2, sharding="fsdp",
                                metrics=["accuracy"])
    est2.load(ckpt_dir)
    after = est2.evaluate((x_loc, y_loc), batch_size=16)
    assert abs(after["loss"] - before["loss"]) < 1e-5, (before, after)
    assert abs(after["accuracy"] - before["accuracy"]) < 1e-6, (before, after)

    # direct sharded-restore path: per-device assembly under the live layout
    tree = ckpt_io.restore(ckpt_dir, shardings=jax.tree_util.tree_map(
        lambda l: l.sharding if hasattr(l, "sharding") else None,
        est._ts, is_leaf=lambda x: x is None))
    k2 = next(l for l in jax.tree_util.tree_leaves(tree["params"])
              if l.ndim == 2)
    np.testing.assert_allclose(
        np.asarray(k2.addressable_shards[0].data),
        np.asarray(kernel.addressable_shards[0].data), rtol=0, atol=0)

    # restore onto a DIFFERENT layout than saved: fsdp-sharded shards must
    # be re-tiled to a fully-replicated target (topology-change resume)
    from jax.sharding import NamedSharding, PartitionSpec as P
    from analytics_zoo_tpu.core import get_mesh
    repl = NamedSharding(get_mesh(), P())
    tree_r = ckpt_io.restore(ckpt_dir, shardings=jax.tree_util.tree_map(
        lambda l: repl, est._ts, is_leaf=lambda x: x is None))
    dense = ckpt_io.restore(ckpt_dir)  # host-side dense assembly
    k_rep = next(l for l in jax.tree_util.tree_leaves(tree_r["params"])
                 if l.ndim == 2)
    k_dense = next(l for l in jax.tree_util.tree_leaves(dense["params"])
                   if getattr(l, "ndim", 0) == 2)
    np.testing.assert_array_equal(
        np.asarray(k_rep.addressable_shards[0].data), k_dense)

    # predict returns exactly this process's rows, assembled locally
    preds = est.predict(x_loc[:16], batch_size=16)
    assert preds.shape == (16, 2), preds.shape
    assert np.all(np.isfinite(preds))

    # iterator feed across processes: strided split + per-batch consensus
    # (unequal local stream lengths; all-masked filler batches)
    from analytics_zoo_tpu.data import from_iterator

    def gen(epoch_idx):
        for i in range(37):  # 19 rows on p0, 18 on p1 via striding
            yield x_all[i % 64], y_all[i % 64]

    stream_res = est.evaluate(from_iterator(gen, batch_size=16),
                              batch_size=16)
    assert np.isfinite(stream_res["loss"]), stream_res

    print(f"MULTIHOST_OK {after['loss']:.6f} {stream_res['loss']:.6f}",
          flush=True)


if __name__ == "__main__":
    main()
