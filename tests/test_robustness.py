"""End-to-end resilience for serving + checkpointing (ISSUE 1 tentpole).

Every scenario here is DETERMINISTIC: faults come from the seeded
registry (core/faults.py), not from racing real failures, and no
injected sleep exceeds 0.5 s.  The acceptance contract:

- the client survives a dropped connection / server restart and a
  "queue full" rejection via retry with backoff;
- an expired-deadline request is shed server-side without running
  inference;
- a ``checkpoint.write_fail`` fault is retried and the save succeeds;
- ``ClusterServing.stop()`` drains with every pending client receiving
  an error reply (zero hung ``query()`` calls).
"""

import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.core import checkpoint as ckpt_io
from analytics_zoo_tpu.core.faults import FaultRegistry, get_registry
from analytics_zoo_tpu.serving import ClusterServing, InputQueue, OutputQueue
from analytics_zoo_tpu.serving.client import RetryPolicy

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clean_global_registry():
    get_registry().reset()
    yield
    get_registry().reset()


class _CountingModel:
    """Doubles its input; records every batch it actually ran."""

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.calls = []  # list of row counts per predict() call
        self._lock = threading.Lock()

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        with self._lock:
            self.calls.append(np.asarray(x).shape[0])
        return np.asarray(x) * 2.0

    @property
    def rows_seen(self) -> int:
        with self._lock:
            return sum(self.calls)


def _fast_retry(**kw) -> RetryPolicy:
    kw.setdefault("max_attempts", 5)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.2)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


# -- retry policy -------------------------------------------------------------

def test_retry_policy_backoff_grows_and_caps():
    p = RetryPolicy(base_delay=0.1, max_delay=0.5, jitter=0.0, seed=0)
    assert p.delay(1) == pytest.approx(0.1)
    assert p.delay(2) == pytest.approx(0.2)
    assert p.delay(3) == pytest.approx(0.4)
    assert p.delay(4) == pytest.approx(0.5)  # capped
    assert p.delay(9) == pytest.approx(0.5)


def test_retry_policy_jitter_is_seeded():
    a = RetryPolicy(jitter=0.5, seed=3)
    b = RetryPolicy(jitter=0.5, seed=3)
    assert [a.delay(i) for i in range(1, 5)] == \
           [b.delay(i) for i in range(1, 5)]
    assert RetryPolicy(jitter=0.5, seed=4).delay(1) != a.delay(1)


def test_retry_policy_rejects_zero_attempts():
    with pytest.raises(ValueError, match="max_attempts"):
        RetryPolicy(max_attempts=0)


# -- client resilience --------------------------------------------------------

def test_client_retries_queue_full_rejection():
    """The first two pushes are rejected ("queue full"); the client's
    bounded retry re-enqueues the SAME uuid and the request succeeds."""
    model = _CountingModel()
    faults = get_registry()
    with ClusterServing(model, batch_size=2) as srv:
        faults.enable("serving.queue_reject", times=2)
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid = iq.enqueue("t", t=x)
        out = oq.query(uid, timeout=20.0)
        assert out is not None
        np.testing.assert_allclose(out, x * 2.0)
        assert faults.fired("serving.queue_reject") == 2
        assert iq.conn.stats["resends"] >= 2
        assert srv.stats()["rejected"] == 2
        iq.close()


def test_queue_full_raises_when_retries_exhausted():
    """A persistently full queue surfaces as an error, not a hang."""
    faults = get_registry()
    with ClusterServing(_CountingModel(), batch_size=2) as srv:
        faults.enable("serving.queue_reject")  # unlimited
        iq = InputQueue(srv.host, srv.port,
                        retry=_fast_retry(max_attempts=3))
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("t", t=np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="queue full"):
            oq.query(uid, timeout=20.0)
        iq.close()


def test_client_survives_injected_connection_drop():
    """``serving.conn_drop``: the server hangs up mid-request without a
    reply.  The client notices the dead reader, reconnects with backoff,
    re-enqueues the same uuid, and the retry lands normally."""
    model = _CountingModel()
    faults = get_registry()
    with ClusterServing(model, batch_size=2) as srv:
        faults.enable("serving.conn_drop", times=1)
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid = iq.enqueue("t", t=x)
        out = oq.query(uid, timeout=20.0)
        assert out is not None
        np.testing.assert_allclose(out, x * 2.0)
        assert faults.fired("serving.conn_drop") == 1
        assert iq.conn.stats["reconnects"] >= 1
        assert iq.conn.stats["resends"] >= 1
        iq.close()


def test_client_survives_server_restart():
    """Stop the server, restart it on the same port, and the SAME client
    object's next query succeeds via reconnect + idempotent re-enqueue."""
    model = _CountingModel()
    srv = ClusterServing(model, batch_size=2).start()
    port = srv.port
    iq = InputQueue(srv.host, port,
                    retry=_fast_retry(max_attempts=8, max_delay=0.3))
    oq = OutputQueue(input_queue=iq)
    try:
        x = np.arange(4, dtype=np.float32)
        uid = iq.enqueue("a", t=x)
        assert oq.query(uid, timeout=20.0) is not None

        srv.stop()
        deadline = time.monotonic() + 10
        while True:  # wait for the OS to release the port
            try:
                srv = ClusterServing(model, port=port,
                                     batch_size=2).start()
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)

        uid2 = iq.enqueue("b", t=x)  # reconnects inside send if needed
        out = oq.query(uid2, timeout=20.0)
        assert out is not None
        np.testing.assert_allclose(out, x * 2.0)
        assert iq.conn.stats["reconnects"] >= 1
    finally:
        iq.close()
        srv.stop()


# -- deadline shedding --------------------------------------------------------

def test_expired_deadline_is_shed_without_inference():
    """While the batcher is busy (injected model latency), a request whose
    deadline lapses in the queue is shed: the client gets an explicit
    "deadline exceeded" error and the model NEVER sees its rows."""
    model = _CountingModel()
    faults = get_registry()
    with ClusterServing(model, batch_size=1, batch_timeout_ms=1) as srv:
        # first batch takes ~0.4s: one latency charge, consumed by req A
        faults.enable("serving.model_latency", times=1, delay=0.4)
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid_a = iq.enqueue("a", t=x)           # occupies the batcher
        time.sleep(0.05)                       # A reaches the model first
        uid_b = iq.enqueue("b", deadline=0.05, t=x)  # expires in queue
        with pytest.raises(RuntimeError, match="deadline exceeded"):
            oq.query(uid_b, timeout=20.0)
        assert oq.query(uid_a, timeout=20.0) is not None
        assert model.rows_seen == 1  # B never ran inference
        assert srv.stats()["shed"] == 1
        iq.close()


def test_generous_deadline_is_served_normally():
    model = _CountingModel()
    with ClusterServing(model, batch_size=2) as srv:
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid = iq.enqueue("t", deadline=10.0, t=x)
        out = oq.query(uid, timeout=20.0)
        np.testing.assert_allclose(out, x * 2.0)
        assert srv.stats()["shed"] == 0
        iq.close()


# -- graceful drain -----------------------------------------------------------

def test_stop_drains_pending_requests_with_error_replies():
    """stop() on a busy server: every request still waiting in the queue
    gets a "server shutting down" reply — zero hung query() calls."""
    model = _CountingModel(delay=0.3)
    srv = ClusterServing(model, batch_size=1, batch_timeout_ms=1).start()
    # no retries: the drain reply itself must reach every client
    iq = InputQueue(srv.host, srv.port,
                    retry=_fast_retry(max_attempts=1))
    oq = OutputQueue(input_queue=iq)
    x = np.arange(4, dtype=np.float32)
    uids = [iq.enqueue(f"r{i}", t=x) for i in range(4)]
    time.sleep(0.1)  # first request reaches the model (0.3s of latency)

    outcomes = {}

    def drain_query(uid):
        try:
            outcomes[uid] = ("ok", oq.query(uid, timeout=15.0))
        except RuntimeError as e:
            outcomes[uid] = ("error", str(e))

    threads = [threading.Thread(target=drain_query, args=(u,))
               for u in uids]
    for t in threads:
        t.start()
    srv.stop()
    for t in threads:
        t.join(timeout=20)
    assert not any(t.is_alive() for t in threads), "hung query() calls"
    assert len(outcomes) == 4
    served = [u for u, (kind, _) in outcomes.items() if kind == "ok"]
    drained = [u for u, (kind, msg) in outcomes.items()
               if kind == "error" and "server shutting down" in msg]
    # every request either completed before the drain or got the explicit
    # shutdown error — nothing timed out, nothing hung
    assert len(served) + len(drained) == 4, outcomes
    assert len(drained) >= 1  # stop() really cut work short
    assert srv.stats()["drained"] == len(drained)
    iq.close()


def test_stop_is_idempotent():
    srv = ClusterServing(_CountingModel(), batch_size=2).start()
    srv.stop()
    srv.stop()  # second call must be a no-op, not an error


def test_stop_joins_worker_threads():
    srv = ClusterServing(_CountingModel(), batch_size=2).start()
    workers = list(srv._threads)
    assert all(t.is_alive() for t in workers)
    srv.stop()
    assert all(not t.is_alive() for t in workers)


# -- checkpoint write retry ---------------------------------------------------

def test_checkpoint_write_fail_is_retried_and_save_succeeds(tmp_path):
    faults = get_registry()
    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "step": 7}
    faults.enable("checkpoint.write_fail", times=2, exc=OSError,
                  message="transient fs blip")
    path = ckpt_io.save(str(tmp_path / "ckpt"), tree, retries=3,
                        retry_delay=0.01)
    assert faults.fired("checkpoint.write_fail") == 2
    restored = ckpt_io.restore(path)
    np.testing.assert_allclose(restored["w"], tree["w"])
    assert restored["step"] == 7


def test_checkpoint_write_fail_exhausts_retries(tmp_path):
    faults = get_registry()
    faults.enable("checkpoint.write_fail", exc=OSError,
                  message="fs is gone")  # unlimited
    with pytest.raises(OSError, match="fs is gone"):
        ckpt_io.save(str(tmp_path / "ckpt"), {"w": np.ones(3)},
                     retries=3, retry_delay=0.01)
    assert faults.fired("checkpoint.write_fail") == 3


def test_checkpoint_retry_preserves_previous_generation(tmp_path):
    """A save that fails every retry must leave the previous checkpoint
    fully readable (crash-consistency holds through the retry path)."""
    faults = get_registry()
    path = str(tmp_path / "ckpt")
    ckpt_io.save(path, {"w": np.zeros(3, np.float32)}, step=1)
    faults.enable("checkpoint.write_fail", exc=OSError)
    with pytest.raises(OSError):
        ckpt_io.save(path, {"w": np.ones(3, np.float32)}, step=2,
                     retries=2, retry_delay=0.01)
    faults.reset()
    restored = ckpt_io.restore(path)
    np.testing.assert_allclose(restored["w"], np.zeros(3))
    assert ckpt_io.latest_step(path) == 1


def test_estimator_save_retries_transient_write_failure(tmp_path):
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    est = Estimator.from_keras(nn.Sequential([nn.Dense(2)]), loss="mse",
                               model_dir=str(tmp_path / "m"),
                               checkpoint_retries=4)
    x = np.random.default_rng(0).normal(size=(16, 3)).astype(np.float32)
    y = np.zeros((16, 2), np.float32)
    est.fit((x, y), epochs=1, batch_size=8, verbose=False)
    faults = get_registry()
    faults.enable("checkpoint.write_fail", times=2, exc=OSError)
    path = est.save()
    assert faults.fired("checkpoint.write_fail") == 2
    assert ckpt_io.exists(path)


def test_checkpoint_config_armed_fault_takes_retry_path(tmp_path):
    """A fault armed WITHOUT an explicit exc (the ZooConfig.faults shape)
    must still raise the call site's default OSError and be retried —
    not escape the retry loop as a RuntimeError."""
    faults = get_registry()
    faults.configure({"checkpoint.write_fail": {"times": 1}})
    path = ckpt_io.save(str(tmp_path / "ckpt"),
                        {"w": np.ones(3, np.float32)}, retries=2,
                        retry_delay=0.01)
    assert faults.fired("checkpoint.write_fail") == 1
    np.testing.assert_allclose(ckpt_io.restore(path)["w"], np.ones(3))


def test_concurrent_queries_all_recover_from_one_conn_drop():
    """Two threads share one connection with two requests in flight when
    the server drops it.  Reconnect must replay EVERY recorded in-flight
    frame — not only the one belonging to the thread that noticed the
    dead reader — so neither query times out."""
    model = _CountingModel(delay=0.3)
    faults = get_registry()
    with ClusterServing(model, batch_size=1, batch_timeout_ms=1) as srv:
        iq = InputQueue(srv.host, srv.port, retry=_fast_retry())
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid_a = iq.enqueue("a", t=x)   # batcher busy with this one
        uid_b = iq.enqueue("b", t=x)   # waiting in the queue
        faults.enable("serving.conn_drop", times=1)
        iq.enqueue("c", t=x)           # this frame triggers the drop

        results = {}

        def q(uid):
            results[uid] = oq.query(uid, timeout=15.0)

        threads = [threading.Thread(target=q, args=(u,))
                   for u in (uid_a, uid_b)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=20)
        assert not any(t.is_alive() for t in threads)
        assert results[uid_a] is not None and results[uid_b] is not None
        np.testing.assert_allclose(results[uid_a], x * 2.0)
        np.testing.assert_allclose(results[uid_b], x * 2.0)
        iq.close()


def test_query_backoff_respects_deadline():
    """A retryable 'queue full' reply near the timeout must not let the
    backoff sleep blow past the caller's budget."""
    faults = get_registry()
    with ClusterServing(_CountingModel(), batch_size=2) as srv:
        faults.enable("serving.queue_reject")  # reject everything
        iq = InputQueue(srv.host, srv.port,
                        retry=RetryPolicy(max_attempts=10, base_delay=0.5,
                                          max_delay=5.0, jitter=0.0,
                                          seed=0))
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("t", t=np.ones(4, np.float32))
        t0 = time.monotonic()
        out = oq.query(uid, timeout=0.6)
        elapsed = time.monotonic() - t0
        assert out is None                 # budget spent, not an answer
        assert elapsed < 2.0, elapsed      # no 5s backoff past the budget
        iq.close()


# -- per-server registry isolation --------------------------------------------

def test_server_accepts_private_registry():
    """A server can be given its own registry, so one test's faults never
    leak into another server in the same process."""
    private = FaultRegistry()
    private.enable("serving.queue_reject")  # reject EVERYTHING on this srv
    model = _CountingModel()
    with ClusterServing(model, batch_size=2, faults=private) as srv_f, \
            ClusterServing(model, batch_size=2) as srv_ok:
        iq_f = InputQueue(srv_f.host, srv_f.port,
                          retry=_fast_retry(max_attempts=2))
        oq_f = OutputQueue(input_queue=iq_f)
        uid = iq_f.enqueue("t", t=np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="queue full"):
            oq_f.query(uid, timeout=10.0)
        iq_ok = InputQueue(srv_ok.host, srv_ok.port, retry=_fast_retry())
        oq_ok = OutputQueue(input_queue=iq_ok)
        uid = iq_ok.enqueue("t", t=np.ones(4, np.float32))
        assert oq_ok.query(uid, timeout=10.0) is not None  # unaffected
        iq_f.close()
        iq_ok.close()


# -- HTTP frontend ------------------------------------------------------------

def test_http_deadline_propagates_and_stats_surface_counters():
    import json
    import urllib.error
    import urllib.request
    from analytics_zoo_tpu.serving import HTTPFrontend

    model = _CountingModel()
    faults = get_registry()
    with ClusterServing(model, batch_size=1, batch_timeout_ms=1) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            # one normal request proves the path, then a doomed one
            req = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert json.load(r)["predictions"] == [[2, 4, 6, 8]]

            # batcher busy for 0.4s; this request's 50ms budget expires
            # in the queue -> server sheds it -> frontend answers 504
            faults.enable("serving.model_latency", times=1, delay=0.4)
            blocker = threading.Thread(
                target=lambda: urllib.request.urlopen(req, timeout=30))
            blocker.start()
            time.sleep(0.1)
            doomed = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]],
                                 "deadline_ms": 50}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(doomed, timeout=30)
            assert ei.value.code == 504
            assert "deadline exceeded" in json.load(ei.value)["error"]
            blocker.join(timeout=20)

            with urllib.request.urlopen(url + "/stats", timeout=10) as r:
                stats = json.load(r)
            assert stats["deadline_exceeded"] == 1
            # resilient-client counters are surfaced alongside
            for key in ("reconnects", "resends", "retries"):
                assert key in stats
        assert srv.stats()["shed"] == 1


# -- checkpoint integrity (crc32, ISSUE 5 satellite) ---------------------------

def _corrupt_file(path):
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))


def test_checkpoint_save_records_crc_and_restore_verifies(tmp_path):
    import json
    import os
    d = str(tmp_path / "ckpt")
    ckpt_io.save(d, {"w": np.arange(6, dtype=np.float32)}, step=1)
    meta = json.load(open(os.path.join(d, "treedef.json")))
    assert meta["crc32"], "save must record per-file crc32s"
    data_name = next(iter(meta["crc32"]))
    _corrupt_file(os.path.join(d, data_name))
    with pytest.raises(ckpt_io.CheckpointCorruptError) as ei:
        ckpt_io.restore(d)
    # the error NAMES the corrupt file, and the counter recorded it
    assert data_name in str(ei.value)
    from analytics_zoo_tpu.core import metrics
    snap = metrics.get_registry().snapshot()
    assert snap["checkpoint.corrupt_files"] >= 1


def test_checkpoint_corrupt_latest_falls_back_to_previous_generation(
        tmp_path, caplog):
    import json
    import logging
    import os
    d = str(tmp_path / "ckpt")
    ckpt_io.save(d, {"w": np.zeros(3, np.float32)}, step=1, keep=2)
    ckpt_io.save(d, {"w": np.ones(3, np.float32)}, step=2, keep=2)
    assert os.path.exists(os.path.join(d, "treedef.prev.json"))
    latest_gen = json.load(open(os.path.join(d, "treedef.json")))["gen"]
    bad = [n for n in os.listdir(d)
           if n.endswith(".npz") and latest_gen in n][0]
    _corrupt_file(os.path.join(d, bad))
    with caplog.at_level(logging.WARNING, logger="analytics_zoo_tpu"):
        back = ckpt_io.restore(d)
    np.testing.assert_allclose(back["w"], np.zeros(3))  # previous gen
    assert any("falling back" in r.message for r in caplog.records)


def test_checkpoint_corrupt_without_fallback_raises(tmp_path):
    import json
    import os
    d = str(tmp_path / "ckpt")
    ckpt_io.save(d, {"w": np.ones(3, np.float32)}, step=1)  # keep=1
    gen = json.load(open(os.path.join(d, "treedef.json")))["gen"]
    bad = [n for n in os.listdir(d)
           if n.endswith(".npz") and gen in n][0]
    _corrupt_file(os.path.join(d, bad))
    with pytest.raises(ckpt_io.CheckpointCorruptError, match=bad):
        ckpt_io.restore(d)
