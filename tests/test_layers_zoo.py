"""Layer-zoo backfill tests (VERDICT r2 #6): fwd/grad per layer, with
tf.keras goldens where tf implements the same layer (the SURVEY §4.4
differential pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn

RNG = jax.random.PRNGKey(0)


def run(layer, x, training=False, rng=None):
    variables = layer.init(RNG, jnp.asarray(x), training=training)
    out, _ = layer.apply(variables, jnp.asarray(x), training=training,
                         rng=rng)
    return variables, np.asarray(out)


def grad_ok(layer, x, training=False, rng=None):
    variables = layer.init(RNG, jnp.asarray(x), training=training)

    def loss(v):
        out, _ = layer.apply(v, jnp.asarray(x), training=training, rng=rng)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(variables)
    leaves = jax.tree_util.tree_leaves(g["params"])
    assert all(np.all(np.isfinite(l)) for l in leaves)
    return leaves


# -- goldens vs tf.keras ------------------------------------------------------

def _set_tf_weights_from(layer_tf, mapping):
    layer_tf.set_weights(mapping)


def test_convlstm2d_matches_tf():
    tf = pytest.importorskip("tensorflow")
    x = np.random.default_rng(0).normal(size=(2, 3, 8, 8, 4)).astype(
        np.float32)
    ours = nn.ConvLSTM2D(5, 3, return_sequences=True,
                         recurrent_activation="sigmoid",
                         unit_forget_bias=False)
    variables, out = run(ours, x)
    p = variables["params"]
    ktf = tf.keras.layers.ConvLSTM2D(
        5, 3, padding="same", return_sequences=True, use_bias=True,
        recurrent_activation="sigmoid", activation="tanh")
    ktf.build(x.shape)
    # our gate order i,f,g,o == keras convlstm gate order i,f,c,o
    ktf.set_weights([np.asarray(p["kernel"]),
                     np.asarray(p["recurrent_kernel"]),
                     np.asarray(p["bias"])])
    want = ktf(x).numpy()
    np.testing.assert_allclose(out, want, atol=2e-5)
    grad_ok(nn.ConvLSTM2D(5, 3), x)


def test_convlstm2d_keras1_defaults():
    """Defaults are the keras-1/BigDL reference semantics: legacy
    hard_sigmoid gates (clip(0.2x+0.5)) and unit forget-gate bias."""
    from analytics_zoo_tpu.nn.layers_zoo import _hard_sigmoid_k1
    z = np.linspace(-4, 4, 9).astype(np.float32)
    np.testing.assert_allclose(_hard_sigmoid_k1(jnp.asarray(z)),
                               np.clip(0.2 * z + 0.5, 0, 1), atol=1e-7)
    x = np.random.default_rng(2).normal(size=(1, 2, 5, 5, 3)).astype(
        np.float32)
    layer = nn.ConvLSTM2D(4, 3)
    variables, out = run(layer, x)
    bias = np.asarray(variables["params"]["bias"])
    np.testing.assert_allclose(bias[4:8], 1.0)   # forget-gate slice
    np.testing.assert_allclose(bias[:4], 0.0)
    assert np.all(np.isfinite(out))
    # single-timestep closed form: h1 = rec(o) * tanh(rec(f)*0 + rec(i)*tanh(g))
    p = variables["params"]
    import jax.lax as lax
    z1 = (np.asarray(lax.conv_general_dilated(
        x[:, 0], np.asarray(p["kernel"]), (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))) + bias)
    i, f, g, o = np.split(z1, 4, axis=-1)
    hs = lambda v: np.clip(0.2 * v + 0.5, 0, 1)
    h1 = hs(o) * np.tanh(hs(i) * np.tanh(g))
    got, _ = nn.ConvLSTM2D(4, 3).apply(variables, x[:, :1])
    np.testing.assert_allclose(got, h1, atol=2e-5)


def test_convlstm2d_last_state_and_backwards():
    x = np.random.default_rng(0).normal(size=(2, 4, 6, 6, 3)).astype(
        np.float32)
    _, seq = run(nn.ConvLSTM2D(4, 3, return_sequences=True), x)
    _, last = run(nn.ConvLSTM2D(4, 3), x)
    np.testing.assert_allclose(last, seq[:, -1], atol=1e-6)
    _, back = run(nn.ConvLSTM2D(4, 3, go_backwards=True), x)
    assert back.shape == last.shape and not np.allclose(back, last)


def test_locally_connected2d_matches_naive():
    """Golden: naive per-position loop (keras 3 dropped the layer, so no
    tf reference exists in-image).  Patch layout from
    conv_general_dilated_patches is channel-major: [c, kh, kw]."""
    x = np.random.default_rng(1).normal(size=(2, 7, 7, 3)).astype(
        np.float32)
    ours = nn.LocallyConnected2D(4, 3, strides=2)
    variables, out = run(ours, x)
    p = variables["params"]
    kern = np.asarray(p["kernel"])      # [oh, ow, c*kh*kw, f]
    bias = np.asarray(p["bias"])        # [oh, ow, f]
    oh, ow = kern.shape[:2]
    want = np.zeros((2, oh, ow, 4), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, 2 * i:2 * i + 3, 2 * j:2 * j + 3, :]
            flat = patch.transpose(0, 3, 1, 2).reshape(2, -1)  # c-major
            want[:, i, j, :] = flat @ kern[i, j] + bias[i, j]
    np.testing.assert_allclose(out, want, atol=2e-5)
    grad_ok(ours, x)
    # unshared weights: kernel has a per-position leading grid
    assert kern.shape == (oh, ow, 27, 4)


def test_conv3d_transpose_matches_tf():
    tf = pytest.importorskip("tensorflow")
    x = np.random.default_rng(2).normal(size=(2, 4, 4, 4, 3)).astype(
        np.float32)
    ours = nn.Conv3DTranspose(5, 3, strides=2, padding="same")
    variables, out = run(ours, x)
    p = variables["params"]
    ktf = tf.keras.layers.Conv3DTranspose(5, 3, strides=2, padding="same")
    ktf.build(x.shape)
    # keras stores [kd,kh,kw,out,in]; ours [kd,kh,kw,in,out]
    ktf.set_weights([np.asarray(p["kernel"]).transpose(0, 1, 2, 4, 3),
                     np.asarray(p["bias"])])
    want = ktf(x).numpy()
    np.testing.assert_allclose(out, want, atol=2e-5)
    grad_ok(ours, x)


def test_conv1d_transpose_matches_tf():
    tf = pytest.importorskip("tensorflow")
    x = np.random.default_rng(3).normal(size=(2, 9, 3)).astype(np.float32)
    ours = nn.Conv1DTranspose(4, 3, strides=2, padding="same")
    variables, out = run(ours, x)
    p = variables["params"]
    ktf = tf.keras.layers.Conv1DTranspose(4, 3, strides=2, padding="same")
    ktf.build(x.shape)
    ktf.set_weights([np.asarray(p["kernel"]).transpose(0, 2, 1),
                     np.asarray(p["bias"])])
    np.testing.assert_allclose(out, ktf(x).numpy(), atol=2e-5)


def test_separable_conv1d_matches_tf():
    tf = pytest.importorskip("tensorflow")
    x = np.random.default_rng(4).normal(size=(2, 10, 3)).astype(np.float32)
    ours = nn.SeparableConv1D(6, 3, depth_multiplier=2)
    variables, out = run(ours, x)
    p = variables["params"]
    ktf = tf.keras.layers.SeparableConv1D(6, 3, padding="same",
                                          depth_multiplier=2)
    ktf.build(x.shape)
    # keras depthwise kernel [k, c, mult]; ours [k, 1, c*mult] with the
    # feature_group layout (channel-major blocks)
    dw = np.asarray(p["depthwise_kernel"]).reshape(3, 3, 2)
    ktf.set_weights([dw,
                     np.asarray(p["pointwise_kernel"]),
                     np.asarray(p["bias"])])
    np.testing.assert_allclose(out, ktf(x).numpy(), atol=2e-5)


def test_lrn2d_matches_tf():
    tf = pytest.importorskip("tensorflow")
    x = np.random.default_rng(5).normal(size=(2, 6, 6, 8)).astype(
        np.float32)
    _, out = run(nn.LRN2D(alpha=1e-3, k=2.0, beta=0.75, n=5), x)
    # tf depth_radius r covers 2r+1 channels and alpha is per-channel
    want = tf.nn.local_response_normalization(
        x, depth_radius=2, bias=2.0, alpha=1e-3 / 5.0, beta=0.75).numpy()
    np.testing.assert_allclose(out, want, atol=1e-5)


def test_softmax_layer():
    x = np.random.default_rng(6).normal(size=(3, 5)).astype(np.float32)
    _, out = run(nn.Softmax(), x)
    np.testing.assert_allclose(out.sum(-1), 1.0, atol=1e-6)
    _, out0 = run(nn.Softmax(axis=0), x)
    np.testing.assert_allclose(out0.sum(0), 1.0, atol=1e-6)


def test_alpha_dropout_self_normalizing():
    x = np.random.default_rng(7).normal(size=(4096, 32)).astype(np.float32)
    layer = nn.AlphaDropout(0.3)
    _, out_eval = run(layer, x)
    np.testing.assert_array_equal(out_eval, x)  # inference: identity
    _, out = run(layer, x, training=True, rng=jax.random.PRNGKey(1))
    assert not np.allclose(out, x)
    # SELU-style moment preservation
    assert abs(out.mean() - x.mean()) < 0.05
    assert abs(out.std() - x.std()) < 0.1


def test_activity_regularization_rides_aux_loss_channel():
    layer = nn.Sequential([nn.Dense(4),
                           nn.ActivityRegularization(l2=0.5)])
    x = np.ones((2, 3), np.float32)
    variables = layer.init(RNG, jnp.asarray(x))
    out, state = layer.apply(variables, jnp.asarray(x))
    from analytics_zoo_tpu.orca.learn.estimator import _collect_aux_losses
    aux = float(_collect_aux_losses(state))
    assert aux == pytest.approx(0.5 * float(np.square(out).sum()), rel=1e-5)


def test_cos_merge():
    a = np.asarray([[1.0, 0.0], [1.0, 1.0]], np.float32)
    b = np.asarray([[1.0, 0.0], [-1.0, -1.0]], np.float32)
    layer = nn.Cos()
    variables = layer.init(RNG, [jnp.asarray(a), jnp.asarray(b)])
    out, _ = layer.apply(variables, [jnp.asarray(a), jnp.asarray(b)])
    np.testing.assert_allclose(np.asarray(out).ravel(), [1.0, -1.0],
                               atol=1e-6)


def test_element_op_layers():
    x = np.asarray([[0.25, 1.0, 4.0]], np.float32)
    cases = [
        (nn.Identity(), x),
        (nn.Exp(), np.exp(x)),
        (nn.Log(), np.log(x)),
        (nn.Sqrt(), np.sqrt(x)),
        (nn.Square(), np.square(x)),
        (nn.Power(2.0, scale=2.0, shift=1.0), (2 * x + 1) ** 2),
        (nn.Negative(), -x),
        (nn.AddConstant(3.0), x + 3),
        (nn.MulConstant(0.5), x / 2),
        (nn.Threshold(0.5, -1.0), np.where(x > 0.5, x, -1.0)),
        (nn.HardShrink(0.5), np.where(np.abs(x) > 0.5, x, 0.0)),
        (nn.SoftShrink(0.5), np.sign(x) * np.maximum(np.abs(x) - 0.5, 0)),
    ]
    for layer, want in cases:
        _, out = run(layer, x)
        np.testing.assert_allclose(out, want, atol=1e-6,
                                   err_msg=type(layer).__name__)


def test_scale_layer_learnable_affine():
    x = np.random.default_rng(8).normal(size=(4, 6)).astype(np.float32)
    variables, out = run(nn.Scale(), x)
    np.testing.assert_allclose(out, x, atol=1e-6)  # ones/zeros init
    grads = grad_ok(nn.Scale(), x)
    assert any(np.abs(g).sum() > 0 for g in grads)


def test_keras1_alias_names():
    assert nn.Convolution2D is nn.Conv2D
    assert nn.Deconvolution2D is nn.Conv2DTranspose
    assert nn.Deconvolution3D is nn.Conv3DTranspose


def test_keras2_namespace_imports():
    from analytics_zoo_tpu.keras2.layers import Dense, Conv2D, AlphaDropout
    from analytics_zoo_tpu.keras2.models import Input, Model, Sequential
    inp = Input((4,))
    out = Dense(2, name="d")(inp)
    m = Model(inp, out)
    x = jnp.ones((2, 4))
    variables = m.init(RNG, x)
    y, _ = m.apply(variables, x)
    assert y.shape == (2, 2)


def test_layer_zoo_count_at_least_95():
    from analytics_zoo_tpu.nn.module import Module
    names = [n for n in dir(nn)
             if isinstance(getattr(nn, n), type)
             and issubclass(getattr(nn, n), Module)
             and getattr(nn, n) is not Module]
    assert len(set(names)) >= 95, sorted(set(names))


def test_conv2d_transpose_matches_tf():
    tf = pytest.importorskip("tensorflow")
    for k, s in ((3, 2), (4, 2), (3, 1)):
        x = np.random.default_rng(k * 10 + s).normal(
            size=(2, 7, 7, 3)).astype(np.float32)
        ours = nn.Conv2DTranspose(5, k, strides=s, padding="same")
        variables, out = run(ours, x)
        p = variables["params"]
        ktf = tf.keras.layers.Conv2DTranspose(5, k, strides=s,
                                              padding="same")
        ktf.build(x.shape)
        ktf.set_weights([np.asarray(p["kernel"]).transpose(0, 1, 3, 2),
                         np.asarray(p["bias"])])
        np.testing.assert_allclose(out, ktf(x).numpy(), atol=2e-5,
                                   err_msg=f"k={k} s={s}")


def test_convlstm2d_valid_padding():
    """Regression (r3 review): padding='valid' shrinks the input conv grid
    but the recurrent conv must stay SAME over that grid."""
    x = np.random.default_rng(9).normal(size=(2, 3, 8, 8, 3)).astype(
        np.float32)
    _, out = run(nn.ConvLSTM2D(4, 3, padding="valid"), x)
    assert out.shape == (2, 6, 6, 4)


def test_word_embedding_frozen_and_glove_loading(tmp_path):
    """WordEmbedding: pretrained table, frozen by default (no grad), GloVe
    txt loading with zero rows for OOV words."""
    glove = tmp_path / "glove.txt"
    glove.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
    layer = nn.WordEmbedding.from_glove(
        str(glove), {"hello": 1, "world": 2, "unseen": 3})
    ids = np.asarray([[1, 2, 3, 0]])
    variables, out = run(layer, ids)
    np.testing.assert_allclose(out[0, 0], [1, 2, 3])
    np.testing.assert_allclose(out[0, 1], [4, 5, 6])
    np.testing.assert_allclose(out[0, 2], 0.0)  # OOV stays zero
    # frozen: the table lives in STATE (outside the optimizer), not params
    assert "embeddings" in variables["state"]
    assert "embeddings" not in variables["params"]
    # trainable=True: a param with flowing gradients
    t = nn.WordEmbedding(np.ones((4, 3), np.float32), trainable=True)
    vt = t.init(RNG, jnp.asarray(ids))
    gt = jax.grad(lambda v: jnp.sum(t.apply(v, jnp.asarray(ids))[0] ** 2))(vt)
    assert float(np.abs(np.asarray(
        gt["params"]["embeddings"])).max()) > 0.0


def test_word_embedding_glove_skips_malformed_lines(tmp_path):
    """Regression (r3 review): multi-token words, truncated lines and
    fastText headers must be skipped, not crash or poison dim."""
    glove = tmp_path / "messy.txt"
    glove.write_text("999994 300\n"          # fastText header
                     "hello 1.0 2.0 3.0\n"
                     ". . . 9.9 9.9 9.9\n"   # word containing spaces
                     "world 4.0 5.0 6.0\n"
                     "trunc 7.0\n")          # truncated tail
    layer = nn.WordEmbedding.from_glove(
        str(glove), {"hello": 1, "world": 2})
    np.testing.assert_allclose(layer.weights[1], [1, 2, 3])
    np.testing.assert_allclose(layer.weights[2], [4, 5, 6])


def test_merge_layer_all_modes():
    """keras-1 Merge(mode=...) + merge() function parity."""
    a = np.asarray([[1.0, 2.0]], np.float32)
    b = np.asarray([[3.0, 5.0]], np.float32)
    cases = {
        "sum": a + b, "mul": a * b, "ave": (a + b) / 2,
        "max": np.maximum(a, b), "min": np.minimum(a, b),
        "concat": np.concatenate([a, b], -1),
        "dot": np.sum(a * b, -1, keepdims=True),
    }
    for mode, want in cases.items():
        layer = nn.Merge(mode=mode)
        v = layer.init(RNG, [jnp.asarray(a), jnp.asarray(b)])
        out, _ = layer.apply(v, [jnp.asarray(a), jnp.asarray(b)])
        np.testing.assert_allclose(np.asarray(out), want, atol=1e-6,
                                   err_msg=mode)
    # cos mode (delegates to a child Cos layer)
    cl = nn.Merge(mode="cos")
    vc = cl.init(RNG, [jnp.asarray(a), jnp.asarray(b)])
    outc, _ = cl.apply(vc, [jnp.asarray(a), jnp.asarray(b)])
    want_cos = (np.sum(a * b, -1, keepdims=True)
                / (np.linalg.norm(a, axis=-1, keepdims=True)
                   * np.linalg.norm(b, axis=-1, keepdims=True)))
    np.testing.assert_allclose(np.asarray(outc), want_cos, atol=1e-6)
    # dot mode honors dot_axes via the axes-aware Dot layer
    a3 = np.ones((1, 2, 3), np.float32)
    b3 = np.ones((1, 2, 3), np.float32)
    outd = nn.merge([jnp.asarray(a3), jnp.asarray(b3)], mode="dot",
                    dot_axes=2)
    assert np.asarray(outd).shape[0] == 1
    # eager-array functional spelling
    oute = nn.merge([jnp.asarray(a), jnp.asarray(b)], mode="ave")
    np.testing.assert_allclose(np.asarray(oute), (a + b) / 2, atol=1e-6)
    with pytest.raises(ValueError, match="merge mode"):
        nn.Merge(mode="xor")
    # functional spelling inside a graph
    ia, ib = nn.Input((2,)), nn.Input((2,))
    m = nn.Model([ia, ib], nn.merge([ia, ib], mode="sum"))
    vv = m.init(RNG, jnp.asarray(a), jnp.asarray(b))
    out, _ = m.apply(vv, jnp.asarray(a), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), a + b, atol=1e-6)


# -- keras-1 tail audit backfill (VERDICT r3 missing #5) ----------------------

def test_cadd_cmul_hardtanh():
    x = np.random.default_rng(0).normal(size=(2, 4, 3)).astype(np.float32)
    v, out = run(nn.CAdd((3,)), x)
    np.testing.assert_allclose(out, x)  # zero-init bias
    v["params"]["bias"] = jnp.ones(3)
    got, _ = nn.CAdd((3,)).apply(v, x)
    np.testing.assert_allclose(got, x + 1.0)
    v, out = run(nn.CMul((3,)), x)
    np.testing.assert_allclose(out, x)  # ones-init weight
    _, out = run(nn.HardTanh(-0.5, 0.5), x)
    np.testing.assert_allclose(out, np.clip(x, -0.5, 0.5))
    grad_ok(nn.CMul((3,)), x)


def test_gaussian_sampler():
    import jax
    rng = np.random.default_rng(1)
    mean = rng.normal(size=(4, 8)).astype(np.float32)
    log_var = np.full((4, 8), -2.0, np.float32)
    layer = nn.GaussianSampler()
    variables = layer.init(jax.random.PRNGKey(0), [mean, log_var])
    # eval: deterministic mean
    out, _ = layer.apply(variables, [mean, log_var], training=False)
    np.testing.assert_allclose(out, mean)
    # training: mean + eps*std, correct spread
    outs = [layer.apply(variables, [mean, log_var], training=True,
                        rng=jax.random.PRNGKey(i))[0] for i in range(30)]
    stack = np.stack(outs)
    assert abs(float(stack.mean() - mean.mean())) < 0.05
    assert abs(float(stack.std(axis=0).mean()) - np.exp(-1.0)) < 0.05


def test_resize_bilinear():
    """Golden: the reference's legacy-TF1 sampling grid
    (tf.compat.v1.image.resize_bilinear), NOT the TF2 half-pixel grid —
    the two differ on any non-trivial resize."""
    x = np.random.default_rng(8).normal(size=(2, 4, 6, 3)).astype(
        np.float32)
    _, out = run(nn.ResizeBilinear(7, 9), x)
    assert out.shape == (2, 7, 9, 3)
    tf = pytest.importorskip("tensorflow")
    want = tf.compat.v1.image.resize_bilinear(x, (7, 9)).numpy()
    np.testing.assert_allclose(out, want, atol=1e-5)
    _, out_ac = run(nn.ResizeBilinear(7, 9, align_corners=True), x)
    want_ac = tf.compat.v1.image.resize_bilinear(
        x, (7, 9), align_corners=True).numpy()
    np.testing.assert_allclose(out_ac, want_ac, atol=1e-5)
    # and it is NOT the half-pixel TF2 grid
    tf2 = tf.image.resize(x, (7, 9), method="bilinear").numpy()
    assert not np.allclose(out, tf2, atol=1e-3)


def test_convlstm3d_shapes_and_grad():
    x = np.random.default_rng(2).normal(size=(2, 3, 4, 5, 5, 2)).astype(
        np.float32)
    _, seq = run(nn.ConvLSTM3D(3, 3, return_sequences=True), x)
    assert seq.shape == (2, 3, 4, 5, 5, 3)
    _, last = run(nn.ConvLSTM3D(3, 3), x)
    np.testing.assert_allclose(last, seq[:, -1], atol=1e-6)
    grad_ok(nn.ConvLSTM3D(2, 3), x[:1, :2, :3, :4, :4])


def test_keras1_alias_layers():
    assert nn.ShareConvolution2D is nn.Conv2D
    assert nn.SparseEmbedding is nn.Embedding
    assert nn.SparseDense is nn.Dense
