"""Differential tests: flash attention vs materialized reference.

Mirrors the reference's TFNet/TorchNet differential-test pattern (SURVEY.md
§4.4): run both implementations on the same inputs, compare within tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.ops.flash_attention as fa_mod
from analytics_zoo_tpu.ops import flash_attention, mha_reference


def _qkv(rng, b=2, t=64, h=2, d=16):
    shape = (b, t, h, d)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(rng, causal):
    q, k, v = _qkv(rng, b=1, t=32, h=2, d=8)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=8,
                               block_k=8).sum()

    def loss_ref(q, k, v):
        return mha_reference(q, k, v, causal=causal).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_pallas_kernel_interpret_mode(rng):
    """Run the actual Pallas kernel (interpret mode) against the reference,
    including a T that does not divide the block size (padding path)."""
    q, k, v = _qkv(rng, b=1, t=24, h=1, d=8)
    fa_mod.INTERPRET = True
    try:
        out = flash_attention(q, k, v, block_q=16, block_k=16)
    finally:
        fa_mod.INTERPRET = False
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pallas_kernel_interpret_causal(rng):
    q, k, v = _qkv(rng, b=1, t=32, h=1, d=8)
    fa_mod.INTERPRET = True
    try:
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    finally:
        fa_mod.INTERPRET = False
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_under_jit_and_mha_layer(rng):
    """use_flash=True path of nn.MultiHeadAttention compiles and runs."""
    import analytics_zoo_tpu.nn as nn
    x = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    mha = nn.MultiHeadAttention(num_heads=4, use_flash=True)
    variables = mha.init(jax.random.PRNGKey(0), x)
    out, _ = jax.jit(lambda v, x: mha.apply(v, x))(variables, x)
    assert out.shape == (2, 16, 32)


def test_fused_softmax_xent_matches_naive():
    """Loss value AND all three gradients must match the materialized
    logits path (chunked recompute is numerics-preserving in f32)."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops import fused_softmax_xent
    rng = np.random.default_rng(0)
    B, S, D, V = 2, 8, 16, 50
    h = rng.normal(size=(B, S, D)).astype(np.float32)
    w = (rng.normal(size=(D, V)) * 0.1).astype(np.float32)
    labels = rng.integers(0, V, (B, S))

    bias = (rng.normal(size=(V,)) * 0.1).astype(np.float32)

    def naive(h, w, bias):
        logits = (h @ w).astype(jnp.float32) + bias
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        corr = jnp.take_along_axis(
            logits, jnp.asarray(labels)[..., None], axis=-1)[..., 0]
        return (lse - corr).mean()

    def fused(h, w, bias):
        return fused_softmax_xent(h, w, jnp.asarray(labels), 4, bias=bias)

    ln, gn = jax.value_and_grad(naive, argnums=(0, 1, 2))(h, w, bias)
    lf, gf = jax.value_and_grad(fused, argnums=(0, 1, 2))(h, w, bias)
    np.testing.assert_allclose(float(lf), float(ln), rtol=1e-6)
    for a, b in zip(gf, gn):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_fused_softmax_xent_bf16_close():
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops import fused_softmax_xent
    rng = np.random.default_rng(1)
    h = rng.normal(size=(1, 16, 8)).astype(np.float32)
    w = (rng.normal(size=(8, 30)) * 0.2).astype(np.float32)
    labels = jnp.asarray(rng.integers(0, 30, (1, 16)))
    lf32 = fused_softmax_xent(jnp.asarray(h), jnp.asarray(w), labels, 8)
    lbf = fused_softmax_xent(jnp.asarray(h, jnp.bfloat16),
                             jnp.asarray(w, jnp.bfloat16), labels, 8)
    np.testing.assert_allclose(float(lbf), float(lf32), rtol=3e-2)


def test_fused_softmax_xent_rejects_bad_chunk():
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops import fused_softmax_xent
    with pytest.raises(ValueError, match="divisible"):
        fused_softmax_xent(jnp.zeros((2, 5, 4)), jnp.zeros((4, 7)),
                           jnp.zeros((2, 5), jnp.int32), 3)
