"""Differential tests: flash attention vs materialized reference.

Mirrors the reference's TFNet/TorchNet differential-test pattern (SURVEY.md
§4.4): run both implementations on the same inputs, compare within tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import analytics_zoo_tpu.ops.flash_attention as fa_mod
from analytics_zoo_tpu.ops import flash_attention, mha_reference


def _qkv(rng, b=2, t=64, h=2, d=16):
    shape = (b, t, h, d)
    return (jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32),
            jnp.asarray(rng.normal(size=shape), jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(rng, causal):
    q, k, v = _qkv(rng)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(rng, causal):
    q, k, v = _qkv(rng, b=1, t=32, h=2, d=8)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=causal, block_q=8,
                               block_k=8).sum()

    def loss_ref(q, k, v):
        return mha_reference(q, k, v, causal=causal).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)


def test_pallas_kernel_interpret_mode(rng):
    """Run the actual Pallas kernel (interpret mode) against the reference,
    including a T that does not divide the block size (padding path)."""
    q, k, v = _qkv(rng, b=1, t=24, h=1, d=8)
    fa_mod.INTERPRET = True
    try:
        out = flash_attention(q, k, v, block_q=16, block_k=16)
    finally:
        fa_mod.INTERPRET = False
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_pallas_kernel_interpret_causal(rng):
    q, k, v = _qkv(rng, b=1, t=32, h=1, d=8)
    fa_mod.INTERPRET = True
    try:
        out = flash_attention(q, k, v, causal=True, block_q=8, block_k=8)
    finally:
        fa_mod.INTERPRET = False
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_under_jit_and_mha_layer(rng):
    """use_flash=True path of nn.MultiHeadAttention compiles and runs."""
    import analytics_zoo_tpu.nn as nn
    x = jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32)
    mha = nn.MultiHeadAttention(num_heads=4, use_flash=True)
    variables = mha.init(jax.random.PRNGKey(0), x)
    out, _ = jax.jit(lambda v, x: mha.apply(v, x))(variables, x)
    assert out.shape == (2, 16, 32)
