"""Foreign input-pipeline interop tests (SURVEY.md §2.2: orca TF Dataset /
TFDataset / torch data_creator parity)."""

import numpy as np
import pytest

from analytics_zoo_tpu.core import get_mesh, init_orca_context


@pytest.fixture(autouse=True)
def _ctx():
    init_orca_context("local")
    yield


def _gen(n, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        x = rng.normal(size=dim).astype(np.float32)
        yield x, np.float32(x.sum())


def test_from_iterator_rebatches_and_masks():
    from analytics_zoo_tpu.data import from_iterator
    feed = from_iterator(lambda e: _gen(37), batch_size=8)
    mesh = get_mesh()
    batches = list(feed.epoch(mesh, 0))
    assert feed.num_rows == 37
    assert len(batches) == 5  # 4 full + 1 padded
    assert all(b["x"].shape == (8, 4) for b in batches)
    assert "mask" not in batches[0]
    last = batches[-1]
    assert "mask" in last
    np.testing.assert_array_equal(
        np.asarray(last["mask"]), [1, 1, 1, 1, 1, 0, 0, 0])


def test_from_iterator_drop_remainder():
    from analytics_zoo_tpu.data import from_iterator
    feed = from_iterator(lambda e: _gen(37), batch_size=8,
                         drop_remainder=True)
    batches = list(feed.epoch(get_mesh(), 0))
    assert len(batches) == 4
    assert all("mask" not in b for b in batches)


def test_estimator_fit_evaluate_on_iterator_feed():
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.data import from_iterator
    from analytics_zoo_tpu.orca.learn import Estimator
    model = nn.Sequential([nn.Dense(1)])
    est = Estimator.from_keras(model, loss="mse", learning_rate=5e-2,
                               metrics=["mae"])
    train = from_iterator(lambda e: _gen(64, seed=e), batch_size=16,
                          drop_remainder=True)
    hist = est.fit(train, epochs=3, batch_size=16, verbose=False)
    assert hist["loss"][-1] < hist["loss"][0]
    # evaluate over a 37-row stream: padded+masked tail must be exact
    ev = from_iterator(lambda e: _gen(37, seed=7), batch_size=16)
    res = est.evaluate(ev, batch_size=16)
    x = np.stack([s[0] for s in _gen(37, seed=7)])
    y = np.stack([s[1] for s in _gen(37, seed=7)])
    pred = est.predict(x, batch_size=16)
    assert abs(res["loss"] - float(np.square(pred[:, 0] - y).mean())) < 1e-4
    assert abs(res["mae"] - float(np.abs(pred[:, 0] - y).mean())) < 1e-4


def test_evaluate_covers_tail_of_drop_remainder_feed():
    # user passes a training-style feed (drop_remainder=True): evaluate
    # must still cover the dropped tail rows (regression: code review)
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.data import DataFeed
    from analytics_zoo_tpu.orca.learn import Estimator
    rng = np.random.default_rng(5)
    x = rng.normal(size=(10, 4)).astype(np.float32)
    y = rng.normal(size=(10, 1)).astype(np.float32)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse")
    est.fit((x, y), epochs=1, batch_size=8, verbose=False)
    feed = DataFeed.from_arrays(x, y, batch_size=8, shuffle=False,
                                drop_remainder=True)
    res = est.evaluate(feed, batch_size=8)
    pred = est.predict(x, batch_size=8)
    assert abs(res["loss"] - float(np.square(pred - y).mean())) < 1e-5


def test_evaluate_shuffled_nondrop_feed_is_exact():
    # metric sums are permutation-invariant and the padded tail positions
    # are masked, so a shuffled drop_remainder=False feed evaluates exactly
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.data import DataFeed
    from analytics_zoo_tpu.orca.learn import Estimator
    rng = np.random.default_rng(6)
    x = rng.normal(size=(10, 4)).astype(np.float32)
    y = rng.normal(size=(10, 1)).astype(np.float32)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse")
    est.fit((x, y), epochs=1, batch_size=8, verbose=False)
    feed = DataFeed.from_arrays(x, y, batch_size=8, shuffle=True,
                                drop_remainder=False)
    res = est.evaluate(feed, batch_size=8)
    pred = est.predict(x, batch_size=8)
    assert abs(res["loss"] - float(np.square(pred - y).mean())) < 1e-5


def test_evaluate_empty_iterable_feed_raises():
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.data import from_iterator
    from analytics_zoo_tpu.orca.learn import Estimator
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse")
    with pytest.raises(ValueError, match="no batches"):
        est.evaluate(from_iterator(lambda e: iter([]), 32), batch_size=32)


def test_from_torch_dataset_streaming():
    torch = pytest.importorskip("torch")

    class DS(torch.utils.data.Dataset):
        def __len__(self):
            return 48

        def __getitem__(self, i):
            x = torch.full((4,), float(i))
            return x, torch.tensor(float(i))

    from analytics_zoo_tpu.data import StreamingDataFeed, from_torch_dataset
    feed = from_torch_dataset(DS(), batch_size=8, shuffle=False,
                              num_workers=2)
    assert isinstance(feed, StreamingDataFeed)
    batches = list(feed.epoch(get_mesh(), 0))
    assert len(batches) == 6
    # order-preserving: row i has value i
    first = np.asarray(batches[0]["x"])
    np.testing.assert_array_equal(first[:, 0], np.arange(8, dtype=np.float32))


def test_from_torch_dataloader_rebatch():
    torch = pytest.importorskip("torch")
    xs = torch.arange(20, dtype=torch.float32).reshape(20, 1)
    ys = torch.arange(20, dtype=torch.float32)
    loader = torch.utils.data.DataLoader(
        torch.utils.data.TensorDataset(xs, ys), batch_size=6)
    from analytics_zoo_tpu.data import from_torch_dataloader
    feed = from_torch_dataloader(loader, batch_size=8)
    batches = list(feed.epoch(get_mesh(), 0))
    assert feed.num_rows == 20
    assert [b["x"].shape[0] for b in batches] == [8, 8, 8]
    assert "mask" in batches[-1]
    got = np.concatenate([np.asarray(b["x"])[:, 0] for b in batches])
    np.testing.assert_array_equal(got[:20], np.arange(20, dtype=np.float32))


def test_from_tf_dataset_gated():
    tf = pytest.importorskip("tensorflow")
    from analytics_zoo_tpu.data import from_tf_dataset
    ds = tf.data.Dataset.from_tensor_slices(
        (np.ones((10, 3), np.float32), np.zeros(10, np.float32)))
    feed = from_tf_dataset(ds, batch_size=4)
    batches = list(feed.epoch(get_mesh(), 0))
    assert feed.num_rows == 10 and len(batches) == 3


def test_from_tf_dataset_missing_tf_raises():
    import sys
    if "tensorflow" in sys.modules:
        pytest.skip("tensorflow available; error path not reachable")
    from analytics_zoo_tpu.data import from_tf_dataset
    with pytest.raises(ImportError, match="tensorflow"):
        from_tf_dataset(object(), batch_size=4)