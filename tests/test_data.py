"""Data layer tests: XShards ops, readers, DataFeed sharding."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.data import (DataFeed, XShards, as_feed, read_csv,
                                    read_json, read_npz, shard_batch)


def _df(n=20, seed=0):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({"a": rng.normal(size=n), "b": rng.integers(0, 5, n),
                         "y": rng.integers(0, 2, n)})


class TestXShards:
    def test_partition_array(self):
        s = XShards.partition(np.arange(10), num_shards=3)
        assert s.num_partitions() == 3
        np.testing.assert_array_equal(s.concatenated(), np.arange(10))

    def test_partition_dict(self):
        s = XShards.partition({"x": np.ones((10, 2)), "y": np.zeros(10)}, 4)
        assert s.num_partitions() == 4
        assert len(s) == 10
        out = s.concatenated()
        assert out["x"].shape == (10, 2)

    def test_transform_shard(self):
        s = XShards.partition(np.arange(10), 2).transform_shard(lambda a: a * 2)
        np.testing.assert_array_equal(s.concatenated(), np.arange(10) * 2)

    def test_transform_with_args(self):
        s = XShards.partition(np.arange(4), 2).transform_shard(
            lambda a, k: a + k, 5)
        np.testing.assert_array_equal(s.concatenated(), np.arange(4) + 5)

    def test_repartition_pandas(self):
        s = XShards([_df(10), _df(10, 1)])
        r = s.repartition(5)
        assert r.num_partitions() == 5
        assert sum(len(d) for d in r.collect()) == 20

    def test_partition_by(self):
        s = XShards([_df(50)])
        parts = s.partition_by("b", num_partitions=3)
        assert parts.num_partitions() == 3
        seen = {}
        for i, df in enumerate(parts.collect()):
            for v in df["b"].unique():
                assert v not in seen, "key split across partitions"
                seen[v] = i

    def test_split(self):
        s = XShards([(np.ones(3), np.zeros(3)), (np.ones(2), np.zeros(2))])
        xs, ys = s.split()
        assert len(xs) == 5 and len(ys) == 5

    def test_to_numpy_dict(self):
        s = XShards([_df(10)]).to_numpy_dict(feature_cols=["a", "b"],
                                             label_cols=["y"])
        d = s.collect()[0]
        assert d["x"].shape == (10, 2) and d["y"].shape == (10,)


class TestReaders:
    def test_read_csv_glob(self, tmp_path):
        for i in range(3):
            _df(10, i).to_csv(tmp_path / f"part{i}.csv", index=False)
        s = read_csv(str(tmp_path / "*.csv"))
        assert s.num_partitions() == 3
        assert len(s) == 30

    def test_read_csv_dir_and_repartition(self, tmp_path):
        for i in range(4):
            _df(5, i).to_csv(tmp_path / f"p{i}.csv", index=False)
        s = read_csv(str(tmp_path), num_shards=2)
        assert s.num_partitions() == 2
        assert len(s) == 20

    def test_read_json(self, tmp_path):
        _df(8).to_json(tmp_path / "d.json", orient="records")
        s = read_json(str(tmp_path / "d.json"))
        assert len(s) == 8

    def test_read_npz(self, tmp_path):
        np.savez(tmp_path / "d.npz", x=np.ones((6, 2)), y=np.zeros(6))
        s = read_npz(str(tmp_path / "d.npz"))
        assert s.collect()[0]["x"].shape == (6, 2)

    def test_missing_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_csv(str(tmp_path / "none*.csv"))

    def test_extension_matching_is_case_insensitive(self, tmp_path):
        """.CSV / .JPG-style uppercase extensions were silently dropped
        from directory reads (ISSUE 7 satellite)."""
        _df(6, 0).to_csv(tmp_path / "lower.csv", index=False)
        _df(4, 1).to_csv(tmp_path / "UPPER.CSV", index=False)
        s = read_csv(str(tmp_path))
        assert s.num_partitions() == 2
        assert len(s) == 10

    def test_file_readahead_overlaps_and_counts_waits(self, tmp_path):
        from analytics_zoo_tpu.data import FileReadahead
        paths = []
        for i in range(4):
            p = tmp_path / f"f{i}.bin"
            p.write_bytes(bytes([i]) * 64)
            paths.append(str(p))
        ra = FileReadahead(depth=2)
        ra.hint(paths)
        import time
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline and not ra._cache:
            time.sleep(0.005)
        for i, p in enumerate(paths):
            assert ra.get(p) == bytes([i]) * 64
        # un-hinted miss reads inline and counts the blocked time
        miss = tmp_path / "miss.bin"
        miss.write_bytes(b"z" * 8)
        before = ra.wait_ms
        assert ra.get(str(miss)) == b"z" * 8
        assert ra.wait_ms >= before
        # a lost race must RETIRE the hint: no consumed path may linger
        # in (or later enter) the cache, or depth such entries would
        # park the reader forever
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with ra._cond:
                stale = set(ra._cache) & set(paths)
                idle = ra._reading is None and not ra._want
            if idle and not stale:
                break
            time.sleep(0.005)
        assert not stale, stale
        ra.close()


class TestDataFeed:
    def test_batches_are_sharded(self):
        mesh = init_orca_context("local")
        feed = DataFeed.from_arrays(np.ones((64, 4), np.float32),
                                    np.zeros(64, np.int32), batch_size=16)
        batches = list(feed.epoch(mesh, 0))
        assert len(batches) == 4
        b = batches[0]
        assert b["x"].shape == (16, 4)
        assert b["x"].sharding.is_fully_replicated is False
        # dim 0 split over the 8-device data axis
        assert b["x"].addressable_shards[0].data.shape == (2, 4)

    def test_shuffle_deterministic(self):
        mesh = init_orca_context("local")
        feed = DataFeed.from_arrays(np.arange(32, dtype=np.float32),
                                    batch_size=8, shuffle=True, seed=3)
        e1 = [np.asarray(b["x"]) for b in feed.epoch(mesh, 0)]
        e2 = [np.asarray(b["x"]) for b in feed.epoch(mesh, 0)]
        e3 = [np.asarray(b["x"]) for b in feed.epoch(mesh, 1)]
        np.testing.assert_array_equal(np.concatenate(e1), np.concatenate(e2))
        assert not np.array_equal(np.concatenate(e1), np.concatenate(e3))

    def test_row_mismatch_raises(self):
        with pytest.raises(ValueError):
            DataFeed({"x": np.ones(10), "y": np.ones(9)}, 2)

    def test_as_feed_forms(self):
        f1 = as_feed((np.ones(8), np.ones(8)), 4)
        f2 = as_feed({"x": np.ones(8)}, 4)
        f3 = as_feed(XShards.partition({"x": np.ones(8)}, 2), 4)
        assert f1.num_rows == f2.num_rows == f3.num_rows == 8
        assert as_feed(f1, 4) is f1

    def test_shard_batch_tree(self):
        mesh = init_orca_context("local")
        out = shard_batch({"x": np.ones((8, 3)), "y": np.ones(8)}, mesh)
        assert out["x"].shape == (8, 3) and out["y"].shape == (8,)

    def test_empty_batch_raises(self):
        mesh = init_orca_context("local")
        feed = DataFeed.from_arrays(np.ones((2, 2)), batch_size=8)
        with pytest.raises(ValueError):
            next(feed.epoch(mesh))


class TestStreamingResilience:
    """Loader-failure policies: bounded retries, skip-and-count, visible
    degradation counters (data/stream.py) — the SAME suite runs against
    both decode backends (ISSUE 7: ``workers="process"`` must pass the
    ordering/resilience/fault-injection contracts unchanged)."""

    @pytest.fixture(params=["thread", "process"])
    def backend(self, request):
        if request.param == "process":
            from analytics_zoo_tpu.data import shm_pool
            if not shm_pool.available():
                pytest.skip("process backend unavailable")
        return request.param

    def _mesh(self):
        from analytics_zoo_tpu.core import init_orca_context
        return init_orca_context("local")

    def test_transient_failure_retried_no_row_lost(self, backend):
        from analytics_zoo_tpu.data import StreamingDataFeed
        mesh = self._mesh()
        fails = {"n": 0}

        def flaky(i, rng=None):
            if i == 3 and fails["n"] < 2:
                fails["n"] += 1
                raise OSError("transient read")
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(8, flaky, batch_size=4, shuffle=False,
                                 num_workers=1, retries=2, workers=backend)
        rows = sorted(float(v) for b in feed.epoch(mesh, 0)
                      for v in np.asarray(b["x"])[:, 0])
        assert rows == [float(i) for i in range(8)]  # nothing lost
        assert feed.load_failures == 2
        assert feed.skipped_rows == 0

    def test_persistent_failure_skipped_and_counted(self, backend):
        from analytics_zoo_tpu.data import StreamingDataFeed
        mesh = self._mesh()

        def corrupt(i, rng=None):
            if i == 3:
                raise OSError("corrupt sample")
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(8, corrupt, batch_size=4, shuffle=False,
                                 num_workers=1, retries=1, on_error="skip",
                                 workers=backend)
        rows = sorted(float(v) for b in feed.epoch(mesh, 0)
                      for v in np.asarray(b["x"])[:, 0])
        # row 3 was substituted with its neighbor: batch shape intact,
        # degradation visible in the counter
        assert len(rows) == 8
        assert 3.0 not in rows and rows.count(4.0) == 2
        assert feed.skipped_rows == 1
        assert feed.load_failures == 2  # initial try + 1 retry

    def test_max_skipped_bounds_degradation(self, backend):
        from analytics_zoo_tpu.data import StreamingDataFeed
        mesh = self._mesh()

        def corrupt(i, rng=None):
            if i % 2 == 0 and i != 0:
                raise OSError("corrupt sample")
            return {"x": np.full((2,), float(i), np.float32)}

        feed = StreamingDataFeed(8, corrupt, batch_size=4, shuffle=False,
                                 num_workers=1, on_error="skip",
                                 max_skipped=1, workers=backend)
        with pytest.raises(RuntimeError, match="max_skipped"):
            list(feed.epoch(mesh, 0))

    def test_default_raise_policy_unchanged(self, backend):
        from analytics_zoo_tpu.data import StreamingDataFeed
        mesh = self._mesh()

        def bad(i, rng=None):
            if i == 5:
                raise ValueError("corrupt sample")
            return {"x": np.zeros((2,), np.float32)}

        feed = StreamingDataFeed(8, bad, batch_size=4, shuffle=False,
                                 num_workers=2, workers=backend)
        with pytest.raises(ValueError, match="corrupt sample"):
            list(feed.epoch(mesh, 0))

    def test_read_fail_injection_absorbed_from_workers(self, backend):
        """The armed ``feed.read_fail`` point fires in the decode worker
        (forked or threaded) and the parent registry's fired()/times
        accounting stays coherent either way."""
        from analytics_zoo_tpu.core import faults
        from analytics_zoo_tpu.data import StreamingDataFeed
        mesh = self._mesh()
        reg = faults.get_registry()
        feed = StreamingDataFeed(
            8, lambda i, rng=None: {"x": np.full((2,), float(i),
                                                 np.float32)},
            batch_size=4, shuffle=False, num_workers=1, retries=1,
            workers=backend)
        before = reg.fired("feed.read_fail")
        with reg.armed("feed.read_fail", times=1):
            batches = list(feed.epoch(mesh, 0))
        assert reg.fired("feed.read_fail") - before == 1
        assert feed.load_failures == 1
        assert feed.skipped_rows == 0
        rows = sorted(float(v) for b in batches
                      for v in np.asarray(b["x"])[:, 0])
        assert rows == [float(i) for i in range(8)]

    def test_policy_validated(self):
        from analytics_zoo_tpu.data import StreamingDataFeed
        with pytest.raises(ValueError, match="on_error"):
            StreamingDataFeed(8, lambda i, rng=None: {}, batch_size=4,
                              on_error="ignore")
        with pytest.raises(ValueError, match="retries"):
            StreamingDataFeed(8, lambda i, rng=None: {}, batch_size=4,
                              retries=-1)


class TestPrefetchIterator:
    """Background feed lookahead (the training half of the pipelined hot
    path): order, exception propagation, and mid-epoch shutdown."""

    def test_order_preserved_and_complete(self):
        from analytics_zoo_tpu.data import PrefetchIterator
        items = [np.full((3,), float(i)) for i in range(17)]
        got = list(PrefetchIterator(iter(items), depth=2))
        assert len(got) == 17
        for i, a in enumerate(got):
            np.testing.assert_array_equal(a, items[i])

    def test_producer_exception_reraises_in_consumer(self):
        from analytics_zoo_tpu.data import PrefetchIterator

        def gen():
            yield 1
            yield 2
            raise OSError("loader died")

        it = PrefetchIterator(gen(), depth=2)
        assert next(it) == 1 and next(it) == 2
        with pytest.raises(OSError, match="loader died"):
            next(it)
        # after the error the iterator is exhausted, not wedged
        assert next(it, None) is None

    def test_close_mid_epoch_stops_producer(self):
        import itertools
        import threading
        from analytics_zoo_tpu.data import PrefetchIterator
        produced = []

        def gen():
            for i in itertools.count():
                produced.append(i)
                yield i

        it = PrefetchIterator(gen(), depth=2)
        assert next(it) == 0
        it.close()
        n_threads = threading.active_count()
        it.close()  # idempotent
        assert threading.active_count() == n_threads
        # the producer stopped near the depth bound, not at infinity
        assert len(produced) <= 8
        with pytest.raises(StopIteration):
            next(it)

    def test_depth_validated(self):
        from analytics_zoo_tpu.data import PrefetchIterator
        with pytest.raises(ValueError, match="depth"):
            PrefetchIterator(iter([]), depth=0)

    def test_overlaps_slow_feed_with_slow_consumer(self):
        """With depth-2 double buffering, a feed taking F per batch and a
        consumer taking C per step run in ~max(F, C) per item, not F+C —
        the wall-clock proof that host feed work overlaps consumption."""
        import time as _t
        from analytics_zoo_tpu.data import PrefetchIterator

        def slow_feed(n=8, per=0.03):
            for i in range(n):
                _t.sleep(per)
                yield i

        # inline baseline: feed + consume serialize
        t0 = _t.monotonic()
        for _ in slow_feed():
            _t.sleep(0.03)
        inline = _t.monotonic() - t0

        t0 = _t.monotonic()
        it = PrefetchIterator(slow_feed(), depth=2)
        for _ in it:
            _t.sleep(0.03)
        overlapped = _t.monotonic() - t0
        # ~0.48s inline vs ~0.27s overlapped; generous margin for CI noise
        assert overlapped < inline * 0.8, (inline, overlapped)
