"""Sharded embedding engine (ROADMAP item 4 / recsys scale): deduped
gather, row sharding, sparse scatter-add gradients through the estimator,
and the structural guarantee that the backward pass never materializes a
dense [rows, dim] gradient."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from analytics_zoo_tpu.core import init_orca_context, metrics
from analytics_zoo_tpu.models import NeuralCF, WideAndDeep
from analytics_zoo_tpu.orca.learn import Estimator
from analytics_zoo_tpu.parallel import (ShardedEmbedding, dedup_lookup,
                                        embedding_row_rules, lookup_stats)
from analytics_zoo_tpu.parallel import embedding as emb


def _ratings(n=512, users=64, items=40, seed=42):
    rng = np.random.default_rng(seed)
    x = np.stack([rng.integers(0, users, n),
                  rng.integers(0, items, n)], 1).astype(np.int32)
    y = (rng.random(n) < 0.5).astype(np.int32)
    return x, y


def _sharded_ncf(users=64, items=40, **kw):
    return NeuralCF(user_count=users, item_count=items, class_num=2,
                    user_embed=8, item_embed=8, hidden_layers=(16, 8),
                    mf_embed=8, sharded_embeddings=True, **kw)


# -- lookup ------------------------------------------------------------------

def test_dedup_lookup_matches_plain_take():
    init_orca_context("local")
    m = ShardedEmbedding(50, 8, name="tbl")
    ids = jnp.array([[3, 7], [3, 3]], jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), ids)
    table = variables["params"]["sharded_embeddings"]  # registers at root
    out, _ = m.apply(variables, ids)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.take(table, ids, axis=0)),
                               atol=1e-6)


def test_dedup_lookup_masks_negative_ids():
    init_orca_context("local")
    table = jnp.asarray(np.random.default_rng(0).normal(size=(10, 4)),
                        jnp.float32)
    ids = jnp.array([[1, -1], [-1, -1]], jnp.int32)
    out = dedup_lookup(table, ids)
    np.testing.assert_allclose(np.asarray(out[0, 1]), np.zeros(4))
    np.testing.assert_allclose(np.asarray(out[1]), np.zeros((2, 4)))
    np.testing.assert_allclose(np.asarray(out[0, 0]),
                               np.asarray(table[1]), atol=1e-6)


def test_combiners_sum_mean_with_variable_multihot():
    init_orca_context("local")
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(20, 6)), jnp.float32)
    ids = jnp.array([[2, 5, 2], [7, -1, -1]], jnp.int32)
    s = dedup_lookup(table, ids, combiner="sum")
    m = dedup_lookup(table, ids, combiner="mean")
    np.testing.assert_allclose(np.asarray(s[0]),
                               np.asarray(2 * table[2] + table[5]),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(s[1]), np.asarray(table[7]),
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(m[0]),
                               np.asarray((2 * table[2] + table[5]) / 3),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(m[1]), np.asarray(table[7]),
                               atol=1e-6)  # mean over the 1 valid id


def test_dedup_lookup_rejects_bad_combiner():
    with pytest.raises(ValueError, match="combiner"):
        dedup_lookup(jnp.zeros((4, 2)), jnp.array([0]), combiner="max")
    with pytest.raises(ValueError, match="combiner"):
        ShardedEmbedding(4, 2, combiner="max")


# -- params split/merge + tap protocol ---------------------------------------

def test_split_merge_roundtrip():
    params = {"a": {"sharded_embeddings": np.ones((4, 2))},
              "b": {"kernel": np.zeros((2, 2))},
              "sharded_embeddings": np.full((3, 2), 2.0)}
    dense, tables = emb.split_sparse(params)
    assert set(tables) == {"a/sharded_embeddings", "sharded_embeddings"}
    assert "sharded_embeddings" not in dense and "a" in dense
    merged = emb.merge_sparse(dense, tables)
    assert (jax.tree_util.tree_structure(merged)
            == jax.tree_util.tree_structure(params))
    assert emb.sparse_paths(params) == ("a/sharded_embeddings",
                                        "sharded_embeddings")


def test_inject_tap_gradients_equal_dense_reference():
    """The tap-protocol row gradient scatter-added into the table must
    reproduce the dense-autodiff table update exactly."""
    init_orca_context("local")
    m = ShardedEmbedding(50, 8, name="tbl")
    ids = jnp.array([[3, 7], [3, 11]], jnp.int32)
    variables = m.init(jax.random.PRNGKey(0), ids)
    table = variables["params"]["sharded_embeddings"]

    def loss_with_taps(tbl, taps, x):
        with emb.inject_taps(taps) as uniqs:
            o, _ = m.apply({"params": {"sharded_embeddings": tbl}}, x)
            return jnp.sum(o ** 2), uniqs

    def sparse_step(tbl, x):
        shapes = emb.record_tap_shapes(lambda: m.apply(
            {"params": {"sharded_embeddings": tbl}}, x))
        taps = {k: jnp.zeros(s.shape, s.dtype) for k, s in shapes.items()}
        (_, uniqs), tap_grads = jax.value_and_grad(
            loss_with_taps, argnums=1, has_aux=True)(tbl, taps, x)
        (key,) = tap_grads
        assert emb.table_path_of(key) == "sharded_embeddings"
        return tbl.at[uniqs[key]].add(-0.1 * tap_grads[key])

    new_tbl = jax.jit(sparse_step)(table, ids)

    def dense_loss(tbl, x):
        o, _ = m.apply({"params": {"sharded_embeddings": tbl}}, x)
        return jnp.sum(o ** 2)

    ref = table - 0.1 * jax.grad(dense_loss)(table, ids)
    np.testing.assert_allclose(np.asarray(new_tbl), np.asarray(ref),
                               atol=1e-6)


# -- estimator training ------------------------------------------------------

def test_default_path_bit_identical_to_baseline():
    """sharded_embeddings=False must be bit-for-bit the pre-engine model:
    fixed-seed loss history equals the captured baseline."""
    init_orca_context("local")
    x, y = _ratings(users=50)
    m = NeuralCF(user_count=50, item_count=40, class_num=2, user_embed=8,
                 item_embed=8, hidden_layers=(16, 8), mf_embed=8)
    est = Estimator.from_keras(m, loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-2, seed=7)
    h = est.fit((x, y), epochs=3, batch_size=64, verbose=False)
    base = [0.6958699822, 0.6850370765, 0.6646105051]
    np.testing.assert_allclose(h["loss"], base, rtol=0, atol=1e-9)


def test_sharded_ncf_trains_with_per_device_row_shards():
    """A table too large to replicate: rows partition as rows/num_shards
    per device under embedding_row_rules, and the loss still goes down.
    nan_policy="skip_step" composes with the sparse path (its guard
    wraps the scatter-add update too)."""
    mesh = init_orca_context("local")
    ndev = mesh.devices.size
    users = 512 * ndev  # replication would cost ndev x this memory
    x, y = _ratings(n=256, users=users)
    est = Estimator.from_keras(_sharded_ncf(users=users),
                               loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-2,
                               seed=7, sharding=embedding_row_rules(),
                               nan_policy="skip_step")
    h = est.fit((x, y), epochs=2, batch_size=64, verbose=False)
    assert h["loss"][-1] < h["loss"][0]
    assert est.bad_steps == 0  # finite run: the guard never fired
    leaf = est._ts["params"]["mlp_user_embed"]["sharded_embeddings"]
    assert leaf.shape == (users, 8)
    assert leaf.addressable_shards[0].data.shape[0] == users // ndev
    # eval/predict run the plain (tap-free) lookup on the same params
    ev = est.evaluate((x, y), batch_size=64)
    assert np.isfinite(ev["loss"])
    assert np.asarray(est.predict(x[:16], batch_size=16)).shape == (16, 2)


def _table_shaped_prims(jaxpr, shape):
    """Primitive-name counts of every equation output at ``shape``,
    recursing into sub-jaxprs (pjit bodies, scan/while/cond branches)."""
    import collections
    prims = collections.Counter()

    def walk(j):
        for eqn in j.eqns:
            for v in eqn.outvars:
                aval = getattr(v, "aval", None)
                if (aval is not None and hasattr(aval, "shape")
                        and tuple(aval.shape) == shape):
                    prims[eqn.primitive.name] += 1
            for val in jax.tree_util.tree_leaves(
                    tuple(eqn.params.values()),
                    is_leaf=lambda x: hasattr(x, "eqns")
                    or hasattr(x, "jaxpr")):
                if hasattr(val, "jaxpr"):  # ClosedJaxpr
                    val = val.jaxpr
                if hasattr(val, "eqns"):
                    walk(val)

    walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)
    return prims


def _traced_table_prims(sharded: bool):
    """Primitive counts at table shape in the traced train step for an
    NCF whose table shapes collide with nothing else."""
    init_orca_context("local")
    users, items = 97, 89  # primes: no accidental shape collisions
    x, y = _ratings(n=128, users=users, items=items)
    m = NeuralCF(user_count=users, item_count=items, class_num=2,
                 user_embed=8, item_embed=8, hidden_layers=(16, 8),
                 mf_embed=8, sharded_embeddings=sharded)
    est = Estimator.from_keras(m, loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-2, seed=7)
    # init only -- make_jaxpr traces the step without compiling it, so a
    # fit (init + compile + steps) would pay for nothing this test reads
    est._ensure_initialized(jnp.asarray(x[:32]))
    batch = {"x": jnp.asarray(x[:32]), "y": jnp.asarray(y[:32])}
    jaxpr = jax.make_jaxpr(lambda ts, b: est._train_step(ts, b))(
        est._ts, batch)
    return _table_shaped_prims(jaxpr, (users, 8))


# equation outputs at table shape that do NOT materialize a new dense
# array: pjit results are the returned updated tables, stop_gradient is
# an identity alias on the forward lookup
_TABLE_ALIAS_PRIMS = {"pjit", "stop_gradient"}


def test_backward_never_materializes_dense_table_grad():
    """Structural guarantee, asserted on the traced train step: the
    sparse path's only [rows, dim] computations are the scatter-add
    table updates themselves (one per user table) — no dense gradient,
    no optimizer-moment arithmetic at table shape.  The dense reference
    (adam on nn.Embedding) does dozens of elementwise ops there."""
    sparse = _traced_table_prims(sharded=True)
    dense = _traced_table_prims(sharded=False)
    sparse_work = {k: v for k, v in sparse.items()
                   if k not in _TABLE_ALIAS_PRIMS}
    # two user-count tables (mlp_user_embed, mf_user_embed): one
    # scatter-add update each, nothing else
    assert sparse_work == {"scatter-add": 2}, sparse_work
    dense_math = sum(v for k, v in dense.items()
                     if k not in _TABLE_ALIAS_PRIMS | {"scatter-add"})
    assert dense_math > 10, dict(dense)  # adam's dense-grad moment math


def test_sharded_checkpoint_roundtrip(tmp_path):
    init_orca_context("local")
    x, y = _ratings(n=256)
    kw = dict(loss="sparse_categorical_crossentropy", optimizer="adam",
              learning_rate=1e-2, seed=7, sharding=embedding_row_rules())
    est = Estimator.from_keras(_sharded_ncf(), **kw)
    est.fit((x, y), epochs=1, batch_size=64, verbose=False)
    est.save(str(tmp_path / "m"))
    est2 = Estimator.from_keras(_sharded_ncf(), **kw)
    est2.load(str(tmp_path / "m"))
    for name in ("mlp_user_embed", "mf_item_embed"):
        np.testing.assert_allclose(
            np.asarray(est._ts["params"][name]["sharded_embeddings"]),
            np.asarray(est2._ts["params"][name]["sharded_embeddings"]))
    # restored table keeps its row sharding
    leaf = est2._ts["params"]["mlp_user_embed"]["sharded_embeddings"]
    assert leaf.addressable_shards[0].data.shape[0] == 64 // 8


def test_embedding_lr_decouples_table_step_size():
    """embedding_lr=0.0 freezes the tables (the supported alternative to
    frozen=) while the dense tower still trains."""
    init_orca_context("local")
    x, y = _ratings(n=256)
    est = Estimator.from_keras(_sharded_ncf(),
                               loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-2,
                               seed=7, embedding_lr=0.0)
    est.fit((x, y), epochs=1, batch_size=64, verbose=False)
    t0 = np.asarray(est._ts["params"]["mlp_user_embed"]["sharded_embeddings"])
    k0 = np.asarray(est._ts["params"]["mlp_0"]["kernel"])
    est.fit((x, y), epochs=1, batch_size=64, verbose=False)
    t1 = np.asarray(est._ts["params"]["mlp_user_embed"]["sharded_embeddings"])
    k1 = np.asarray(est._ts["params"]["mlp_0"]["kernel"])
    np.testing.assert_array_equal(t0, t1)
    assert not np.allclose(k0, k1)


def test_sparse_guardrails_raise_actionable_errors():
    init_orca_context("local")
    x, y = _ratings(n=128)
    for kw, pat in [
        (dict(grad_accum=2), "grad_accum"),
        (dict(grad_compression="int8"), "grad_compression"),
        (dict(frozen=["mlp_user_embed"]), "embedding_lr=0.0"),
    ]:
        est = Estimator.from_keras(
            _sharded_ncf(), loss="sparse_categorical_crossentropy",
            learning_rate=1e-2, seed=7, **kw)
        with pytest.raises(ValueError, match=pat):
            est.fit((x, y), epochs=1, batch_size=64, verbose=False)


def test_wide_and_deep_sharded_embeddings_flag():
    init_orca_context("local")
    rng = np.random.default_rng(0)
    n = 128
    x = np.concatenate([
        rng.random((n, 4), np.float32).astype(np.float32),
        np.stack([rng.integers(0, 24, n), rng.integers(0, 16, n)],
                 1).astype(np.float32),
        rng.normal(size=(n, 1)).astype(np.float32),
    ], axis=1)
    y = rng.integers(0, 2, n).astype(np.int32)
    m = WideAndDeep(class_num=2, wide_cross_dims=[4],
                    embed_in_dims=[24, 16], embed_out_dims=[8, 8],
                    continuous_cols=1, sharded_embeddings=True)
    est = Estimator.from_keras(m, loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2, seed=3)
    h = est.fit((x, y), epochs=1, batch_size=32, verbose=False)
    assert np.isfinite(h["loss"][-1])
    assert emb.sparse_paths(est._ts["params"]) == (
        "embed_0/sharded_embeddings", "embed_1/sharded_embeddings")


# -- accounting ---------------------------------------------------------------

def test_lookup_stats_counts_deduped_vs_naive():
    reg = metrics.get_registry()
    d, n = lookup_stats(np.array([1, 1, 2, 2, 2, -1]), dim=8)
    assert (d, n) == (2, 5)
    snap = reg.snapshot()
    assert snap["embed.gather_rows"] == 2
    assert snap["embed.gather_rows_naive"] == 5
    assert snap["embed.gather_bytes"] == 2 * 8 * 4
    assert snap["embed.gather_bytes_naive"] == 5 * 8 * 4
