"""End-to-end Estimator tests: the SURVEY.md §7 stage-3 milestone.

Covers: fit reduces loss (LeNet/MNIST-like), metrics, predict exactness,
save/load round-trip, XShards + DataFrame column paths, and the golden
data-parallel consistency check (§7 stage 4): same data+seed ⇒ same result
regardless of mesh layout, because the global batch is what defines the step.
"""

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context, stop_orca_context
from analytics_zoo_tpu.data import XShards
from analytics_zoo_tpu.orca.learn import Estimator, EveryEpoch


def make_blobs(n=256, dim=8, classes=4, seed=0):
    """Linearly separable clusters — tiny stand-in for MNIST."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, dim)) * 3
    y = rng.integers(0, classes, n)
    x = centers[y] + rng.normal(size=(n, dim)).astype(np.float32)
    return x.astype(np.float32), y.astype(np.int32)


def mlp(classes=4):
    return nn.Sequential([
        nn.Dense(32, activation="relu"),
        nn.Dense(classes),
    ])


def test_fit_reduces_loss_and_learns():
    init_orca_context("local")
    x, y = make_blobs()
    est = Estimator.from_keras(mlp(), loss="sparse_categorical_crossentropy",
                               optimizer="adam", learning_rate=1e-2,
                               metrics=["accuracy"])
    hist = est.fit((x, y), epochs=5, batch_size=64)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    res = est.evaluate((x, y), batch_size=64)
    assert res["accuracy"] > 0.9


def test_lenet_mnist_smoke():
    """LeNet on synthetic digits: the BASELINE LeNet/MNIST config at toy scale."""
    init_orca_context("local")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, 64).astype(np.int32)
    model = nn.Sequential([
        nn.Conv2D(6, 5, activation="relu"), nn.MaxPooling2D(2),
        nn.Conv2D(16, 5, padding="valid", activation="relu"),
        nn.MaxPooling2D(2), nn.Flatten(),
        nn.Dense(120, activation="relu"), nn.Dense(84, activation="relu"),
        nn.Dense(10),
    ])
    est = Estimator.from_keras(model, loss="sparse_categorical_crossentropy",
                               learning_rate=5e-3)
    hist = est.fit((x, y), epochs=3, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]  # memorizing noise: loss drops
    preds = est.predict(x, batch_size=32)
    assert preds.shape == (64, 10)


def test_predict_exact_rows_with_remainder():
    init_orca_context("local")
    x, y = make_blobs(n=70)  # not divisible by batch or 8 devices
    est = Estimator.from_keras(mlp(), loss="sparse_categorical_crossentropy")
    est.fit((x, y), epochs=1, batch_size=32)
    preds = est.predict(x, batch_size=32)
    assert preds.shape[0] == 70


def test_save_load_roundtrip(tmp_path):
    init_orca_context("local")
    x, y = make_blobs()
    est = Estimator.from_keras(mlp(), loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    est.fit((x, y), epochs=2, batch_size=64)
    p1 = est.predict(x)
    est.save(str(tmp_path / "m"))

    est2 = Estimator.from_keras(mlp(), loss="sparse_categorical_crossentropy",
                                learning_rate=1e-2)
    est2.load(str(tmp_path / "m"))
    p2 = est2.predict(x)
    np.testing.assert_allclose(p1, p2, rtol=1e-5)
    # resumed training continues from the same step count
    assert int(est2._ts["step"]) == int(est._ts["step"])


def test_checkpoint_trigger_writes(tmp_path):
    init_orca_context("local")
    x, y = make_blobs(n=128)
    est = Estimator.from_keras(mlp(), loss="sparse_categorical_crossentropy",
                               model_dir=str(tmp_path / "ckpt"))
    est.fit((x, y), epochs=1, batch_size=64, checkpoint_trigger=EveryEpoch())
    from analytics_zoo_tpu.core import checkpoint as ck
    assert ck.exists(str(tmp_path / "ckpt"))


def test_fit_from_xshards_dataframe_cols():
    import pandas as pd
    init_orca_context("local")
    x, y = make_blobs(n=120, dim=3)
    df = pd.DataFrame({"f1": x[:, 0], "f2": x[:, 1], "f3": x[:, 2], "label": y})
    shards = XShards([df.iloc[:60], df.iloc[60:]])
    est = Estimator.from_keras(mlp(), loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2, metrics=["accuracy"])
    est.fit(shards, epochs=3, batch_size=40,
            feature_cols=["f1", "f2", "f3"], label_cols=["label"])
    res = est.evaluate(shards, batch_size=40,
                       feature_cols=["f1", "f2", "f3"], label_cols=["label"])
    assert res["accuracy"] > 0.5


def test_dp_consistency_across_mesh_layouts():
    """Golden §7-stage-4 test: with identical global batches, training on a
    1-wide vs 8-wide data axis gives the same params (psum == single-device
    sum).  CPU f32 math is deterministic enough for a near-exact match."""
    x, y = make_blobs(n=64, seed=3)

    def run(mesh_shape):
        stop_orca_context()
        init_orca_context("local", mesh_shape=mesh_shape)
        est = Estimator.from_keras(
            mlp(), loss="sparse_categorical_crossentropy",
            optimizer="sgd", learning_rate=0.1, seed=7)
        est.fit((x, y), epochs=2, batch_size=32)
        return est.predict(x)

    p_wide = run({"data": 8})
    p_one = run({"data": 1})
    np.testing.assert_allclose(p_wide, p_one, rtol=2e-3, atol=2e-4)


def test_batchnorm_model_trains():
    """State (running stats) threads through fit and is used in eval."""
    init_orca_context("local")
    x, y = make_blobs(n=128)
    model = nn.Sequential([nn.Dense(16), nn.BatchNormalization(),
                           nn.Activation("relu"), nn.Dense(4)])
    est = Estimator.from_keras(model, loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    est.fit((x, y), epochs=2, batch_size=64)
    stats = est.get_model()["state"]
    leaves = [np.asarray(v) for v in
              __import__("jax").tree_util.tree_leaves(stats)]
    assert any(np.abs(l).sum() > 0 for l in leaves)
    preds = est.predict(x)
    assert preds.shape == (128, 4)


def test_evaluate_dataset_smaller_than_batch():
    # masked padded batches: a 2-row dataset evaluates exactly even with
    # batch_size 64 (previously raised "no batches")
    init_orca_context("local")
    est = Estimator.from_keras(mlp(), loss="mse")
    x = np.ones((2, 4), np.float32)
    y = np.zeros((2, 1), np.float32)
    est.fit((np.ones((8, 4), np.float32), np.zeros((8, 1), np.float32)),
            epochs=1, batch_size=8, verbose=False)
    res = est.evaluate((x, y), batch_size=64)
    pred = est.predict(x, batch_size=64)
    assert abs(res["loss"] - float(np.square(pred - y).mean())) < 1e-5


def test_save_uninitialized_raises(tmp_path):
    init_orca_context("local")
    est = Estimator.from_keras(mlp(), loss="mse")
    with pytest.raises(ValueError):
        est.save(str(tmp_path / "x"))


def test_evaluate_covers_remainder_rows(rng):
    """evaluate() must include rows beyond the last full batch (regression:
    code-review finding — previously silently dropped)."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    model = nn.Sequential([nn.Dense(1)])
    est = Estimator.from_keras(model, loss="mse", metrics=["mae"])
    x = rng.normal(size=(70, 4)).astype(np.float32)
    y = np.zeros((70, 1), np.float32)
    est.fit((x[:32], y[:32]), epochs=1, batch_size=32, verbose=False)
    res = est.evaluate((x, y), batch_size=32)
    # mae over ALL 70 rows: hand-compute from the model's own predictions
    pred = est.predict(x, batch_size=32)
    expect_mae = float(np.abs(pred - y).mean())
    assert abs(res["mae"] - expect_mae) < 1e-5
    expect_loss = float(np.square(pred - y).mean())
    assert abs(res["loss"] - expect_loss) < 1e-5


def test_profiler_trace_written(tmp_path, rng):
    """jax.profiler integration (SURVEY §5.1): fit with profile_dir writes
    a trace capture under the directory."""
    import os
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    model = nn.Sequential([nn.Dense(1)])
    prof = str(tmp_path / "prof")
    est = Estimator.from_keras(model, loss="mse", profile_dir=prof,
                               profile_steps=(1, 3))
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = np.zeros((64, 1), np.float32)
    est.fit((x, y), epochs=1, batch_size=16, verbose=False)
    assert not est._profiling
    found = [os.path.join(r, f) for r, _, fs in os.walk(prof) for f in fs]
    assert found, "no profiler trace files written"


def test_summary_readback(tmp_path, rng):
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.orca.learn import Estimator
    init_orca_context("local")
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               metrics=["mae"], log_dir=str(tmp_path),
                               app_name="t")
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = np.zeros((32, 1), np.float32)
    est.fit((x, y), epochs=3, batch_size=16, validation_data=(x, y),
            verbose=False)
    train = est.get_train_summary("loss")
    assert len(train) == 3 and all(np.isfinite(v) for _, v in train)
    val = est.get_validation_summary("mae")
    assert len(val) == 3


def test_evaluate_shuffled_drop_remainder_exact_coverage():
    """Regression (VERDICT r2 weak #7): a SHUFFLED drop_remainder feed now
    evaluates exactly — the dropped tail of the epoch permutation is
    covered by a padded+masked extra batch, so metrics equal the
    unshuffled full-coverage result."""
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.data import DataFeed
    from analytics_zoo_tpu.orca.learn import Estimator

    rng = np.random.default_rng(0)
    x = rng.normal(size=(37, 6)).astype(np.float32)   # 37 % 16 = 5 dropped
    y = rng.integers(0, 2, 37).astype(np.int32)
    est = Estimator.from_keras(
        nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(2)]),
        loss="sparse_categorical_crossentropy", metrics=["accuracy"])
    est.fit((x[:32], y[:32]), epochs=1, batch_size=16, verbose=False)

    shuffled = DataFeed({"x": x, "y": y}, 16, shuffle=True, seed=3,
                        drop_remainder=True)
    exact = est.evaluate((x, y), batch_size=16)
    got = est.evaluate(shuffled, batch_size=16)
    assert got["loss"] == pytest.approx(exact["loss"], rel=1e-5)
    assert got["accuracy"] == pytest.approx(exact["accuracy"], rel=1e-6)


def test_grad_accum_matches_full_batch_step():
    """grad_accum=N must produce EXACTLY the full-batch update: mean of
    equal micro-batch mean-gradients == full-batch mean gradient."""
    import analytics_zoo_tpu.nn as nn
    rng = np.random.default_rng(11)
    x = rng.normal(size=(16, 6)).astype(np.float32)
    y = rng.integers(0, 3, 16).astype(np.int32)

    def make(accum):
        init_orca_context("local")
        model = nn.Sequential([nn.Dense(16, activation="relu"),
                               nn.Dense(3)])
        est = Estimator.from_keras(
            model, loss="sparse_categorical_crossentropy", optimizer="sgd",
            learning_rate=0.1, grad_accum=accum)
        hist = est.fit((x, y), epochs=2, batch_size=16, verbose=False)
        return hist["loss"], est.get_model()

    import jax
    loss1, p1 = make(1)
    loss4, p4 = make(4)
    np.testing.assert_allclose(loss1, loss4, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p1),
                    jax.tree_util.tree_leaves(p4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_grad_accum_rejects_indivisible_batch():
    import analytics_zoo_tpu.nn as nn
    init_orca_context("local")
    est = Estimator.from_keras(nn.Sequential([nn.Dense(2)]),
                               loss="mse", optimizer="sgd",
                               learning_rate=0.1, grad_accum=3)
    x = np.zeros((8, 4), np.float32)
    y = np.zeros((8, 2), np.float32)
    with pytest.raises(ValueError, match="divisible"):
        est.fit((x, y), epochs=1, batch_size=8, verbose=False)


def test_fit_prefetch_matches_inline_bitwise():
    """fit(prefetch=2) must be a pure scheduling change: the same batches
    in the same order through the same compiled step — loss history
    identical to the inline prefetch=0 baseline (bisection contract)."""
    init_orca_context("local")
    x, y = make_blobs()

    def run(prefetch):
        est = Estimator.from_keras(
            mlp(), loss="sparse_categorical_crossentropy",
            optimizer="adam", learning_rate=1e-2, seed=3)
        return est.fit((x, y), epochs=3, batch_size=64, verbose=False,
                       prefetch=prefetch)

    inline = run(prefetch=0)
    prefetched = run(prefetch=2)
    assert inline["loss"] == prefetched["loss"]


def test_fit_prefetch_records_depth_gauge():
    from analytics_zoo_tpu.core import metrics
    init_orca_context("local")
    x, y = make_blobs()
    est = Estimator.from_keras(mlp(),
                               loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2)
    est.fit((x, y), epochs=1, batch_size=64, verbose=False, prefetch=2)
    snap = metrics.get_registry().snapshot()
    assert "train.prefetch_depth" in snap
    assert snap["train.prefetch_depth"]["max"] <= 2


def test_fit_prefetch_with_streaming_feed():
    """StreamingDataFeed composes with the estimator-level prefetcher:
    the stream's decode workers feed the prefetch thread, which feeds the
    step loop; row accounting stays exact."""
    from analytics_zoo_tpu.data import StreamingDataFeed
    init_orca_context("local")
    rng = np.random.default_rng(0)
    xs = rng.normal(size=(96, 8)).astype(np.float32)
    ys = (xs.sum(axis=1, keepdims=True) > 0).astype(np.float32)

    def load(i, rng=None):
        return {"x": xs[i], "y": ys[i]}

    feed = StreamingDataFeed(96, load, batch_size=32, shuffle=False,
                             num_workers=2)
    est = Estimator.from_keras(nn.Sequential([nn.Dense(1)]), loss="mse",
                               learning_rate=1e-2)
    hist = est.fit(feed, epochs=2, batch_size=32, verbose=False,
                   prefetch=2)
    assert len(hist["loss"]) == 2
