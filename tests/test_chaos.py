"""Deterministic chaos-sweep harness (ISSUE 14): seeded multi-fault
storms (``core/chaos.py``), the system-wide :class:`InvariantChecker`,
the four new injection points (``serving.slow_wire``,
``serving.net_partition``, ``controller.tick_fail``,
``registry.swap_fail``), and the hardening they shook out — the
controller's degraded-mode backoff and the swap-failure atomicity
guarantee.

The closing test is THE acceptance storm: all five fault classes over a
2-replica supervised pool with a 10k-row batch job in flight — zero
client-visible errors, a row-exact journal, every invariant green, and
a same-seed rerun reproducing the identical fault firing sequence.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.core import faults as faults_lib
from analytics_zoo_tpu.core import metrics as metrics_lib
from analytics_zoo_tpu.core.chaos import ChaosSchedule, InvariantChecker
from analytics_zoo_tpu.serving import (BatchScorer, ClusterServing,
                                       HysteresisPolicy,
                                       InProcessReplicaFactory, InputQueue,
                                       ModelRegistry, OutputQueue,
                                       ReplicaSet, RetryPolicy,
                                       ServingController)


class _Model:
    """Multiplies by ``factor`` — distinguishable outputs make stale
    post-swap predictions detectable."""

    def __init__(self, factor: float = 2.0):
        self.factor = factor

    def predict(self, x):
        return np.asarray(x, np.float32) * self.factor


def _serve(**kw) -> ClusterServing:
    kw.setdefault("batch_size", 8)
    kw.setdefault("batch_timeout_ms", 2)
    if "models" not in kw:
        kw.setdefault("model", _Model())
    return ClusterServing(port=0, **kw).start()


def _retry(**kw) -> RetryPolicy:
    kw.setdefault("max_attempts", 8)
    kw.setdefault("base_delay", 0.02)
    kw.setdefault("max_delay", 0.3)
    kw.setdefault("seed", 0)
    return RetryPolicy(**kw)


# -- the storm plan is pure seed ----------------------------------------------

def test_storm_plan_is_seed_deterministic():
    points = ["serving.slow_wire", "serving.replica_down",
              "serving.net_partition"]
    a = ChaosSchedule(seed=7, duration_s=12.0, points=points)
    b = ChaosSchedule(seed=7, duration_s=12.0, points=points)
    assert [e.to_dict() for e in a.plan] == [e.to_dict() for e in b.plan]
    assert a.describe() == b.describe()
    c = ChaosSchedule(seed=8, duration_s=12.0, points=points)
    assert [e.to_dict() for e in a.plan] != [e.to_dict() for e in c.plan]
    # every point gets scheduled (round-robin), events stay in-window
    assert {e.point for e in a.plan} == set(points)
    for e in a.plan:
        assert 0.0 <= e.t < 12.0
    # serialized storms: no two windows overlap
    s = ChaosSchedule(seed=3, duration_s=12.0, points=points,
                      max_concurrent=1)
    spans = sorted((e.t, e.t + e.duration_s) for e in s.plan)
    for (_, end), (start, _) in zip(spans, spans[1:]):
        assert start >= end


def test_storm_rejects_bad_arguments():
    with pytest.raises(ValueError):
        ChaosSchedule(seed=0, duration_s=0.0, points=["step.nan"])
    with pytest.raises(ValueError):
        ChaosSchedule(seed=0, duration_s=1.0, points=[])
    with pytest.raises(ValueError):
        ChaosSchedule(seed=0, duration_s=1.0, points=["no.such_point"])
    with pytest.raises(ValueError):
        ChaosSchedule(seed=0, duration_s=1.0, points=["step.nan"],
                      max_concurrent=0)


# -- fired-event log + schedule accounting ------------------------------------

@pytest.mark.faults
def test_fired_events_are_ordered_and_filterable():
    reg = faults_lib.get_registry()
    reg.reset()
    reg.enable("feed.stall", times=2)
    reg.enable("step.nan", times=1)
    assert reg.fire("feed.stall")
    assert reg.fire("step.nan")
    assert reg.fire("feed.stall")
    assert not reg.fire("feed.stall")  # budget spent: not logged
    assert reg.fired_events() == ["feed.stall", "step.nan", "feed.stall"]
    assert reg.fired_events(points=["step.nan"]) == ["step.nan"]
    reg.reset()
    assert reg.fired_events() == []


def test_register_point_is_thread_safe_and_idempotent():
    names = [f"chaostest.p{i % 4}" for i in range(32)]
    errs = []

    def worker(n):
        try:
            assert faults_lib.register_point(n) == n
        except Exception as e:  # noqa: BLE001 — collected
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(n,)) for n in names]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert {f"chaostest.p{i}" for i in range(4)} <= faults_lib.KNOWN_POINTS
    with pytest.raises(ValueError):
        faults_lib.register_point("")
    with pytest.raises(ValueError):
        faults_lib.register_point(None)
    # keep the runtime vocabulary pristine for later tests
    for i in range(4):
        faults_lib.KNOWN_POINTS.discard(f"chaostest.p{i}")


@pytest.mark.faults
def test_running_schedules_are_visible_until_stopped():
    reg = faults_lib.get_registry()
    reg.reset()
    storm = ChaosSchedule(seed=1, duration_s=60.0, points=["feed.stall"],
                          name="leakcheck")
    assert reg.schedule_state() == []
    storm.start()
    try:
        assert storm.running
        assert reg.running_schedules() == [storm]
        assert reg.schedule_state() == ["leakcheck"]
    finally:
        storm.stop()
    assert not storm.running
    assert reg.schedule_state() == []
    assert reg.armed_points() == []  # stop() disarmed the storm's points


# -- serving.slow_wire --------------------------------------------------------

@pytest.mark.faults
def test_slow_wire_adds_latency_but_never_corrupts():
    reg = faults_lib.get_registry()
    reg.reset()
    srv = _serve()
    try:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        x = np.ones((4,), np.float32)
        uid = iq.enqueue("warm", t=x)
        assert oq.query(uid, timeout=20.0) is not None
        # one request round trip crosses the wire 4 times (request
        # send/recv + reply send/recv); each armed fire adds `delay`
        with reg.armed("serving.slow_wire", times=4, delay=0.05):
            t0 = time.perf_counter()
            uid = iq.enqueue("jit", t=x)
            out = oq.query(uid, timeout=20.0)
            elapsed = time.perf_counter() - t0
        assert out is not None
        np.testing.assert_allclose(out, x * 2.0, rtol=1e-6)
        assert reg.fired("serving.slow_wire") == 4
        assert elapsed >= 0.15  # 4 x 50ms of injected jitter, some slop
        iq.close()
    finally:
        srv.stop()


# -- serving.net_partition ----------------------------------------------------

@pytest.mark.faults
def test_net_partition_severs_conns_but_replica_lives():
    reg = faults_lib.get_registry()
    reg.reset()
    srv = _serve()
    rs = ReplicaSet([(srv.host, srv.port)], retry=_retry(),
                    start_health=False)
    try:
        x = np.ones((4,), np.float32)
        assert rs.predict(x, deadline=10.0) is not None
        with reg.armed("serving.net_partition", times=1):
            out = rs.predict(x, deadline=15.0)
        # the partition severed the conn mid-request; the client's
        # reconnect + idempotent same-uuid replay absorbed it
        assert out is not None
        np.testing.assert_allclose(out, x * 2.0, rtol=1e-6)
        assert reg.fired("serving.net_partition") == 1
        st = srv.stats()
        # the PROCESS survived: listener up, state serving — only the
        # client sockets died (what distinguishes it from replica_down)
        assert st["state"] == "serving"
        assert rs.predict(x, deadline=10.0) is not None
    finally:
        rs.close()
        srv.stop()


# -- controller.tick_fail -> degraded mode (satellite 2) ----------------------

@pytest.mark.faults
def test_controller_backs_off_and_dumps_once_under_tick_storm(tmp_path):
    """>=3 consecutive tick failures: bounded exponential backoff plus
    EXACTLY ONE controller_degraded flight record naming the failing
    stage; one good tick restores the interval and zeroes the streak."""
    reg = faults_lib.get_registry()
    reg.reset()
    m = metrics_lib.get_registry()
    degraded0 = m.snapshot().get("controller.degraded", 0)
    srv = _serve()
    rs = ReplicaSet([(srv.host, srv.port)], start_health=False)
    ctl = ServingController(rs, InProcessReplicaFactory(_serve),
                            interval_s=0.02,
                            flightrec_dir=str(tmp_path))
    try:
        reg.enable("controller.tick_fail", times=5)
        ctl.start()
        deadline = time.monotonic() + 15.0
        # the storm: 5 failed ticks (backoff after the 3rd), then the
        # budget is spent and the next tick succeeds
        while time.monotonic() < deadline:
            if (reg.fired("controller.tick_fail") == 5
                    and ctl.consecutive_failures == 0
                    and m.snapshot().get("controller.ticks", 0) > 0):
                break
            time.sleep(0.02)
        assert reg.fired("controller.tick_fail") == 5
        assert ctl.consecutive_failures == 0  # recovered
    finally:
        reg.disable("controller.tick_fail")
        ctl.close()
        rs.close()
        srv.stop()
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("controller.degraded", 0) - degraded0 == 1
    assert snap.get("controller.errors", 0) >= 5
    dumps = [f for f in os.listdir(tmp_path) if "flightrec" in f]
    # ONE dump per degradation episode — not one per failed tick
    assert len(dumps) == 1, dumps
    rec = json.loads((tmp_path / dumps[0]).read_text())
    assert rec["reason"] == "controller_degraded"
    assert rec["context"]["stage"] == "observe"  # where raise_if sits
    assert rec["context"]["consecutive_failures"] == 3
    assert rec["context"]["backoff_s"] > 0.02  # backed off the interval


# -- registry.swap_fail -> atomicity (satellite 3) ----------------------------

@pytest.mark.faults
def test_swap_failure_leaves_old_version_active_and_uncounted(tmp_path):
    reg = faults_lib.get_registry()
    reg.reset()
    models = ModelRegistry()
    models.register("default", _Model(2.0), version="v1")
    srv = _serve(models=models)
    rs = ReplicaSet([(srv.host, srv.port)], retry=_retry(),
                    start_health=False)
    swaps0 = metrics_lib.get_registry().snapshot().get(
        "registry.swaps", 0)
    stop = threading.Event()
    errors: list = []
    x = np.ones((4,), np.float32)

    def client():  # in-flight traffic across the failed swap
        while not stop.is_set():
            try:
                out = rs.predict(x, deadline=10.0)
                if out is None:
                    errors.append("timeout")
                elif not np.allclose(out, x * 2.0):
                    errors.append(f"unexpected output {out[:2]}")
            except Exception as e:  # noqa: BLE001 — counted
                errors.append(f"{type(e).__name__}: {e}"[:200])

    threads = [threading.Thread(target=client) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)
        with reg.armed("registry.swap_fail", times=1):
            with pytest.raises(RuntimeError):
                models.swap("default", _Model(3.0))
        time.sleep(0.2)  # in-flight batches complete on the old model
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        rs.close()
        srv.stop()
    # atomicity: the failure hit BEFORE the flip — old version active,
    # still routable (all in-flight traffic answered by v1), and the
    # swap counter never moved
    assert models.active_version("default") == "v1"
    assert not errors, errors[:3]
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("registry.swaps", 0) == swaps0
    # the registry is not wedged: the next (un-faulted) swap lands
    v2 = models.swap("default", _Model(3.0), drain=False)
    assert models.active_version("default") == v2
    assert snap.get("registry.swaps", 0) + 1 == metrics_lib.get_registry(
        ).snapshot().get("registry.swaps", 0)


# -- the fault-point doc table is CI-enforced (satellite 5) -------------------

def test_fault_point_docs_match_code():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "dev", "check_fault_docs.py")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=60)
    assert proc.returncode == 0, proc.stdout


# -- bench harness knows the chaos config -------------------------------------

def test_bench_has_chaos_config():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    assert "chaos" in bench.CONFIGS
    assert callable(bench._BENCHES["chaos"])
    assert "chaos" in bench._BUDGET


# -- async checkpoint crash storms (ISSUE 15) ---------------------------------

CKPT_WORKER = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "ckpt_chaos_worker.py")


def _spawn_ckpt_worker(model_dir, mirror_dir):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, CKPT_WORKER, str(model_dir), str(mirror_dir)],
        env=env, cwd=repo, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)


def test_sigkill_mid_async_save_restores_consistent_generation(tmp_path):
    """THE crash-consistency acceptance (ISSUE 15): SIGKILL a trainer
    that is streaming async full+delta generations, at seeded offsets —
    the survivor must always restore a COMPLETE crc-clean generation
    whose every leaf (embedding rows included) is bit-identical to the
    synchronous mirror the worker wrote for that step."""
    import random

    import jax

    from analytics_zoo_tpu.core import checkpoint as ckpt_io
    from analytics_zoo_tpu.core import ckpt_manager as ckpt_mgr_lib

    rng = random.Random(20150815)
    for rep in range(2):
        model_dir = tmp_path / f"m{rep}"
        mirror_dir = tmp_path / f"mirror{rep}"
        proc = _spawn_ckpt_worker(model_dir, mirror_dir)
        try:
            # let >=2 trigger firings land: under the block in-flight
            # policy the 2nd TRIGGERED line implies the 1st generation's
            # manifest line is already durable — the kill can tear the
            # tail but never leave the directory unrestorable
            want = 2 + rng.randrange(0, 3)
            seen = 0
            deadline = time.time() + 240
            while seen < want:
                assert time.time() < deadline, "worker never triggered"
                line = proc.stdout.readline()
                assert line, "worker exited early"
                if "TRIGGERED" in line:
                    seen += 1
            time.sleep(rng.uniform(0.0, 0.05))  # land mid-write
            proc.kill()  # SIGKILL: no handlers, no flush, no goodbye
            proc.communicate(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate(timeout=60)

        errors, _warns = ckpt_mgr_lib.verify_path(str(model_dir))
        assert errors == [], errors
        assert InvariantChecker().check_manifest(str(model_dir)) == []
        tree, rec = ckpt_mgr_lib.restore_path(str(model_dir))
        mirror = str(mirror_dir / f"step_{rec['step']}")
        assert ckpt_io.exists(mirror), \
            f"restored step {rec['step']} has no mirror"
        want_tree = ckpt_io.restore(mirror)
        got = jax.tree_util.tree_leaves(
            {k: tree[k] for k in ("params", "state", "opt_state")})
        want = jax.tree_util.tree_leaves(
            {k: want_tree[k] for k in ("params", "state", "opt_state")})
        assert len(got) == len(want)
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
        assert int(np.asarray(tree["step"])) == int(rec["step"])


def test_async_ckpt_survives_write_fail_and_slow_write_storm(tmp_path):
    """``checkpoint.write_fail`` exhausting the writer's retry budget
    plus ``checkpoint.slow_write`` stalls, mid-async-fit: the failed
    generation must not poison the manifest (law 7), the next save is
    forced full, and a post-storm restore is bit-identical to the live
    train state."""
    import jax as jax_lib

    from analytics_zoo_tpu.core import init_orca_context
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.orca.learn import Estimator
    from analytics_zoo_tpu.orca.learn.trigger import SeveralIteration

    init_orca_context("local")

    def ncf():
        return NeuralCF(user_count=64, item_count=40, class_num=2,
                        user_embed=8, item_embed=8, hidden_layers=(16, 8),
                        mf_embed=8, sharded_embeddings=True)

    d = str(tmp_path / "m")
    rng = np.random.default_rng(3)
    x = np.stack([rng.integers(0, 64, 256),
                  rng.integers(0, 40, 256)], 1).astype(np.int32)
    y = (rng.random(256) < 0.5).astype(np.int32)
    kw = dict(loss="sparse_categorical_crossentropy", optimizer="adam",
              learning_rate=1e-2, seed=7)
    est = Estimator.from_keras(ncf(), model_dir=d, checkpoint_async=True,
                               checkpoint_inflight="block", **kw)
    # 4 injected write errors: 3 exhaust one save's retry budget (the
    # save FAILS), the 4th is absorbed by the next save's retries
    faults_lib.get_registry().enable("checkpoint.write_fail", times=4)
    faults_lib.get_registry().enable("checkpoint.slow_write", times=2,
                                     delay=0.02)
    est.fit((x, y), epochs=2, batch_size=64,
            checkpoint_trigger=SeveralIteration(2), verbose=False)
    est._ckpt_mgr.flush(raise_error=False)
    snap = metrics_lib.get_registry().snapshot()
    assert snap.get("ckpt.write_errors", 0) >= 1, snap
    assert est._ckpt_mgr.verify() == []
    assert InvariantChecker().check_manifest(d) == []
    # post-storm blocking save must land cleanly and restore exactly
    est.save()
    est2 = Estimator.from_keras(ncf(), model_dir=d,
                                checkpoint_async=True, **kw)
    est2.load(d)
    got = jax_lib.tree_util.tree_leaves(jax_lib.device_get(
        {k: est2._ts[k] for k in ("params", "state", "opt_state")}))
    want = jax_lib.tree_util.tree_leaves(jax_lib.device_get(
        {k: est._ts[k] for k in ("params", "state", "opt_state")}))
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
    assert int(np.asarray(est2._ts["step"])) == int(
        np.asarray(est._ts["step"]))


# -- THE acceptance storm -----------------------------------------------------

STORM_POINTS = ("serving.slow_wire", "serving.replica_down",
                "serving.net_partition", "registry.swap_fail",
                "controller.tick_fail")
STORM_SEED = 20140807
STORM_DURATION_S = 9.0


def _storm_run(tmp_path, run_id: str):
    """One full acceptance run: 2-replica supervised pool sharing one
    ModelRegistry, 4 closed-loop clients, a 10k-row journaled batch job,
    a swapper attempting a hot swap every 150ms, and a reviver standing
    in for the process supervisor — all under the seeded storm.
    Returns the evidence dict the caller asserts on."""
    reg = faults_lib.get_registry()
    reg.reset()  # a clean fired-event log: the replay evidence
    resources = InvariantChecker.baseline()
    models = ModelRegistry()
    models.register("default", _Model(2.0), version="v1")

    def new_server() -> ClusterServing:
        return _serve(models=models)

    servers = [new_server(), new_server()]
    rs = ReplicaSet([(s.host, s.port) for s in servers], retry=_retry(),
                    health_interval=0.1, breaker_reset_s=0.3)
    # autoscaling ON (the controller ticks — and fails ticks — through
    # the storm); the slack SLO keeps the pool from churning so the
    # fault timeline, not scaling, drives the run
    ctl = ServingController(
        rs, InProcessReplicaFactory(new_server),
        policy=HysteresisPolicy(slo_p99_ms=5000.0, min_replicas=1,
                                max_replicas=3, down_cooldown_s=600.0),
        interval_s=0.05, flightrec_dir=str(tmp_path / f"rec-{run_id}"))
    checker = InvariantChecker(servers=servers, router=rs,
                               interval_s=0.05)
    checker.watch_registry(models)
    storm = ChaosSchedule(
        seed=STORM_SEED, duration_s=STORM_DURATION_S, max_concurrent=1,
        points=list(STORM_POINTS),
        # pin the budget so the window always fits 3 failed ticks at
        # interval_s=0.05 even once backoff stretches the loop
        point_params={"controller.tick_fail": {"times": 3}})
    # the storm must exercise every fault class (seed chosen for that)
    assert {e.point for e in storm.plan} == set(STORM_POINTS)

    stop = threading.Event()
    errors: list = []
    expected = {"factor": 2.0}
    swaps = {"ok": 0, "injected": 0}

    def reviver():  # k8s stand-in: replace storm-killed replicas
        replaced: set = set()
        while not stop.wait(0.1):
            for s in list(servers):
                if id(s) in replaced:
                    continue
                try:
                    # kill() reports "stopped" (SIGKILL leaves no
                    # distinct lifecycle state) — nothing else stops a
                    # server mid-run here.
                    dead = s.stats().get("state") == "stopped"
                except Exception:  # noqa: BLE001 — treat as dead
                    dead = True
                if not dead:
                    continue
                replaced.add(id(s))
                try:
                    rs.remove_replica((s.host, s.port), drain=False)
                except Exception:  # noqa: BLE001 — already gone
                    pass
                ns = checker.add_server(new_server())
                servers.append(ns)
                try:
                    rs.add_replica((ns.host, ns.port))
                except Exception:  # noqa: BLE001 — pool mid-teardown
                    ns.stop()
                    servers.remove(ns)

    def swapper():  # the mid-storm upgrade the swap_fail window hits
        factor = 2.0
        while not stop.wait(0.15):
            nxt = 5.0 - factor  # alternate x2 <-> x3
            try:
                models.swap("default", _Model(nxt), drain=False,
                            keep_old=False)
            except RuntimeError:
                swaps["injected"] += 1  # the injected mid-warm abort
                continue
            factor = nxt
            expected["factor"] = factor
            swaps["ok"] += 1

    x = np.ones((8,), np.float32)

    def client():
        while not stop.is_set():
            try:
                out = rs.predict(x, deadline=20.0)
            except Exception as e:  # noqa: BLE001 — client-visible
                errors.append(f"{type(e).__name__}: {e}"[:200])
                checker.note_client_error(e)
                continue
            if out is None:
                errors.append("timeout")
                checker.note_client_error("timeout")
            elif not (np.allclose(out, x * 2.0)
                      or np.allclose(out, x * 3.0)):
                # neither live version produced this: a stale or torn
                # model served the request
                errors.append(f"stale/corrupt output {out[:2]}")

    rows = np.arange(10_000 * 4, dtype=np.float32).reshape(10_000, 4)
    job_dir = str(tmp_path / f"job-{run_id}")
    job: dict = {}

    def run_job():
        try:
            with BatchScorer(rs, job_dir, shard_size=250, max_inflight=4,
                             retry=_retry(max_attempts=8,
                                          base_delay=0.05, seed=1),
                             request_timeout=30.0) as sc:
                job["report"] = sc.score(rows)
        except Exception as e:  # noqa: BLE001 — recorded
            job["error"] = f"{type(e).__name__}: {e}"[:300]

    threads = [threading.Thread(target=f)
               for f in (reviver, swapper, client, client, client,
                         client)]
    jt = threading.Thread(target=run_job)
    try:
        ctl.start()
        checker.start()
        for t in threads:
            t.start()
        jt.start()
        with storm:
            assert storm.wait(timeout=STORM_DURATION_S + 20.0)
        jt.join(timeout=120.0)
        assert not jt.is_alive(), "batch job wedged under the storm"
        stop.set()
        for t in threads:
            t.join(timeout=15.0)
        time.sleep(0.5)  # quiesce: let final replies land
        # no stale version after the last flip: a fresh request must
        # serve the LAST successfully swapped model
        out = rs.predict(x, deadline=10.0)
        assert out is not None
        np.testing.assert_allclose(out, x * expected["factor"],
                                   rtol=1e-6)
        checker.check_quiescent()
        checker.check_registry()
        checker.check_batch_job(job_dir, len(rows))
    finally:
        stop.set()
        storm.stop()
        checker.stop()
        ctl.close()
        rs.close()
        for s in servers:
            s.stop()
    return {"storm": storm, "checker": checker, "errors": errors,
            "job": job, "swaps": swaps, "resources": resources,
            "fired": storm.fired_sequence()}


@pytest.mark.faults
def test_acceptance_seeded_storm_zero_errors_and_reproducible(tmp_path):
    """THE ISSUE-14 acceptance bar, run TWICE with the same seed: the
    storm (replica kill + net partition + slow wire + swap_fail +
    tick_fail) over 2 replicas with autoscaling on and a 10k-row batch
    job in flight completes with zero client-visible errors, a
    row-exact journal, and every invariant green — and the second run
    reproduces the first run's exact fault firing sequence."""
    runs = [_storm_run(tmp_path, run_id) for run_id in ("a", "b")]
    for r in runs:
        assert r["job"].get("error") is None, r["job"]
        assert r["job"]["report"].rows == 10_000
        assert not r["errors"], r["errors"][:5]
        # the storm actually bit: every fault class fired
        assert set(r["fired"]) == set(STORM_POINTS)
        assert r["swaps"]["injected"] >= 1  # swap_fail hit a live swap
        assert r["swaps"]["ok"] >= 1        # and real swaps landed too
        r["checker"].assert_ok()
        # teardown hygiene: no leaked threads/fds/shm vs the run's own
        # pre-topology baseline
        r["checker"].assert_teardown(r["resources"], fd_slack=8)
    # same seed -> identical ordered fault firing sequence (the
    # faults.fired event log IS the replay evidence)
    assert runs[0]["fired"] == runs[1]["fired"]
    assert runs[0]["fired"], "storm fired nothing"
