"""Hot-row embedding cache + end-to-end recsys serving: EmbedCache LRU
semantics, swap-driven invalidation, the CachedEmbeddingModel adapter,
and raw events -> FeaturePipeline -> sharded NCF behind ClusterServing ->
ranked top-k, including a hot swap under load."""

import pickle
import threading

import numpy as np
import pytest

from analytics_zoo_tpu.core import init_orca_context, metrics
from analytics_zoo_tpu.friesian import FeaturePipeline, StringIndex
from analytics_zoo_tpu.models import NeuralCF
from analytics_zoo_tpu.orca.learn import Estimator
from analytics_zoo_tpu.serving import (CachedEmbeddingModel, ClusterServing,
                                       EmbedCache, InferenceModel,
                                       InputQueue, ModelRegistry,
                                       OutputQueue)

USERS, ITEMS = 64, 40


@pytest.fixture(scope="module")
def recsys_parts():
    """One small sharded-embedding NCF, trained once and split for
    serving: (host tables, tail-input column spec, loaded tail, a probe
    request, and the full-model logits for that request's pairs)."""
    init_orca_context("local")
    rng = np.random.default_rng(0)
    n = 512
    x = np.stack([rng.integers(0, USERS, n),
                  rng.integers(0, ITEMS, n)], 1).astype(np.int32)
    y = (rng.random(n) < 0.5).astype(np.int32)
    ncf = NeuralCF(user_count=USERS, item_count=ITEMS, class_num=2,
                   user_embed=8, item_embed=8, hidden_layers=(16, 8),
                   mf_embed=8, sharded_embeddings=True)
    est = Estimator.from_keras(ncf, loss="sparse_categorical_crossentropy",
                               learning_rate=1e-2, seed=3)
    est.fit((x, y), epochs=1, batch_size=64, verbose=False)
    tables, tail_mod, tail_vars = ncf.serving_split(
        {"params": est._ts["params"]})
    im = InferenceModel().load(tail_mod, tail_vars)
    req = np.array([[3, 1, 2, 5, 7]], np.int64)  # user 3, 4 candidates
    pairs = np.stack([np.full(4, 3), np.array([1, 2, 5, 7])],
                     1).astype(np.int32)
    logits = np.asarray(est.predict(pairs, batch_size=4))
    return {"tables": tables, "columns": ncf.embedding_columns(),
            "im": im, "req": req, "logits": logits}


def _rank_from_logits(logits, items):
    z = logits - logits.max(axis=-1, keepdims=True)
    p = np.exp(z)
    pos = 1.0 - p[:, 0] / p.sum(axis=-1)
    return items[np.argsort(-pos, kind="stable")]


# -- EmbedCache ---------------------------------------------------------------

def test_embed_cache_lru_eviction_and_metrics():
    reg = metrics.get_registry()
    c = EmbedCache(capacity=3)
    c.insert("m", "v1", "t", [1, 2, 3], np.eye(3, 4, dtype=np.float32))
    hits, missing = c.lookup("m", "v1", "t", [1, 9])
    assert list(hits) == [1] and missing == [9]
    # id 1 was refreshed: inserting two more evicts 2 then 3, not 1
    c.insert("m", "v1", "t", [4, 5], np.zeros((2, 4), np.float32))
    assert len(c) == 3
    hits, missing = c.lookup("m", "v1", "t", [1, 2, 3, 4, 5])
    assert sorted(hits) == [1, 4, 5] and missing == [2, 3]
    snap = reg.snapshot()
    assert snap["embed.cache_hits"] == 1 + 3
    assert snap["embed.cache_misses"] == 1 + 2
    assert snap["embed.cache_evictions"] == 2
    assert snap["embed.cache_size"]["value"] == 3
    with pytest.raises(ValueError, match="capacity"):
        EmbedCache(capacity=0)


def test_embed_cache_invalidate_scopes():
    c = EmbedCache(capacity=100)
    for model, ver in [("a", "v1"), ("a", "v2"), ("b", "v1")]:
        c.insert(model, ver, "t", [0, 1], np.zeros((2, 2), np.float32))
    assert c.invalidate("a", "v1") == 2
    assert len(c) == 4
    assert c.invalidate("a") == 2          # all remaining versions of a
    assert c.invalidate() == 2             # whole cache
    assert len(c) == 0
    assert metrics.get_registry().snapshot()["embed.cache_size"]["value"] == 0


def test_embed_cache_attach_swap_and_unload_invalidation():
    class _Stub:
        def predict(self, x):
            return np.asarray(x)

    c = EmbedCache(capacity=100)
    reg = ModelRegistry()
    c.attach(reg)
    reg.register("m", _Stub(), version="v1")
    c.insert("m", "v1", "t", [0, 1, 2], np.zeros((3, 2), np.float32))
    c.insert("other", "v1", "t", [0], np.zeros((1, 2), np.float32))
    reg.swap("m", _Stub(), version="v2", warm=False)
    # the flip dropped v1's rows; unrelated models keep theirs
    assert c.invalidate("m", "v1") == 0
    assert len(c) == 1
    c.insert("m", "v2", "t", [5], np.zeros((1, 2), np.float32))
    reg.swap("m", _Stub(), version="v3", warm=False, keep_old=False)
    assert c.invalidate("m", "v2") == 0    # swap AND unload both fired
    c.detach(reg)
    c.insert("m", "v3", "t", [7], np.zeros((1, 2), np.float32))
    reg.swap("m", _Stub(), version="v4", warm=False)
    assert c.invalidate("m", "v3") == 1    # detached: nothing auto-dropped


def test_embed_cache_fences_swapped_out_version():
    """The registry drains old-version batches AFTER the swap hooks run,
    so a batch finishing mid-drain races the invalidation: its cache
    insert must be refused, not land as a resurrected stale row (the
    chaos sweeps shook this out as a lost hot-swap invalidation)."""
    class _Stub:
        def predict(self, x):
            return np.asarray(x)

    reg = metrics.get_registry()
    base = reg.snapshot().get("embed.cache_fenced_inserts", 0)
    c = EmbedCache(capacity=100)
    mreg = ModelRegistry()
    c.attach(mreg)
    mreg.register("m", _Stub(), version="v1")
    c.insert("m", "v1", "t", [0, 1], np.zeros((2, 2), np.float32))
    mreg.swap("m", _Stub(), version="v2", warm=False)
    # the straggler: an in-flight v1 batch completes after the flip
    c.insert("m", "v1", "t", [0, 1], np.zeros((2, 2), np.float32))
    assert c.invalidate("m", "v1") == 0
    assert reg.snapshot()["embed.cache_fenced_inserts"] == base + 2
    # the new version caches normally
    c.insert("m", "v2", "t", [0], np.zeros((1, 2), np.float32))
    assert len(c) == 1
    # rollback: re-promoting v1 unfences it
    mreg.promote("m", "v1", warm=False)
    c.insert("m", "v1", "t", [3], np.zeros((1, 2), np.float32))
    hits, _ = c.lookup("m", "v1", "t", [3])
    assert list(hits) == [3]


# -- CachedEmbeddingModel -----------------------------------------------------

def test_cached_adapter_ranks_like_full_model(recsys_parts):
    p = recsys_parts
    adapter = CachedEmbeddingModel(p["tables"], p["columns"], p["im"],
                                   cache=EmbedCache(capacity=1000))
    ranked = adapter.predict(p["req"])
    expect = _rank_from_logits(p["logits"], p["req"][0, 1:])
    np.testing.assert_array_equal(ranked[0], expect)


def test_cached_adapter_meters_hits_and_dedup(recsys_parts):
    p = recsys_parts
    reg = metrics.get_registry()
    adapter = CachedEmbeddingModel(p["tables"], p["columns"], p["im"],
                                   cache=EmbedCache(capacity=1000))
    adapter.predict(p["req"])
    snap = reg.snapshot()
    # 4 tables x (1 unique user or 4 unique items): all cold misses
    assert snap["embed.cache_misses"] == 10
    assert snap["embed.cache_hits"] == 0
    # dedup accounting: user column repeats 4x per pair
    assert snap["embed.gather_rows"] < snap["embed.gather_rows_naive"]
    adapter.predict(p["req"])              # same request: all hot
    snap = reg.snapshot()
    assert snap["embed.cache_misses"] == 10
    assert snap["embed.cache_hits"] == 10


def test_cached_adapter_without_cache_and_input_validation(recsys_parts):
    p = recsys_parts
    plain = CachedEmbeddingModel(p["tables"], p["columns"], p["im"],
                                 cache=None)
    cached = CachedEmbeddingModel(p["tables"], p["columns"], p["im"],
                                  cache=EmbedCache(capacity=1000))
    np.testing.assert_array_equal(plain.predict(p["req"]),
                                  cached.predict(p["req"]))
    with pytest.raises(ValueError, match="user"):
        CachedEmbeddingModel(p["tables"], [("t", "timestamp")], p["im"])
    with pytest.raises(ValueError, match=r"\[B, 1 \+ k\]"):
        plain.predict(np.array([3], np.int64))


# -- end-to-end: events in, ranked ids out ------------------------------------

def _event_pipeline(k):
    uix = {f"u{i}": i for i in range(1, USERS)}
    iix = {f"i{i}": i for i in range(1, ITEMS)}
    pipe = (FeaturePipeline().encode_string(StringIndex("user", uix))
            .encode_string(StringIndex("item", iix)))
    return pipe, pipe.as_server_transform(["user"] + ["item"] * k,
                                          dtype=np.int64)


def test_server_pipeline_raw_events_to_ranked_ids(recsys_parts):
    """ClusterServing(pipelines=): clients send raw string events; the
    registered FeaturePipeline encodes them server-side and the reply is
    the ranked candidate ids."""
    p = recsys_parts
    adapter = CachedEmbeddingModel(p["tables"], p["columns"], p["im"],
                                   cache=EmbedCache(capacity=1000))
    pipe, tf = _event_pipeline(k=4)
    # pipelines survive pickling (ship with server config)
    tf = pickle.loads(pickle.dumps(tf))
    with ClusterServing(models={"recsys": adapter},
                        pipelines={"recsys": tf},
                        batch_size=4, batch_timeout_ms=2) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        ev = np.array(["u3", "i1", "i2", "i5", "i7"], dtype="<U8")
        out = oq.query(iq.enqueue("c0", model="recsys", t=ev),
                       timeout=30.0)
        iq.close()
    expect = _rank_from_logits(p["logits"], p["req"][0, 1:])
    np.testing.assert_array_equal(out, expect)


def test_hot_swap_under_load_zero_stale_rows_zero_failures(recsys_parts):
    """The acceptance path: raw events flow while the model hot-swaps.
    Every reply must be EXACTLY one version's ranking (a stale cached
    row would blend versions and produce a third ordering), no request
    may fail, and the flip must drop the outgoing version's cache rows."""
    p = recsys_parts
    cache = EmbedCache(capacity=10_000)
    v1 = CachedEmbeddingModel(p["tables"], p["columns"], p["im"],
                              cache=cache, version="v1")
    tables2 = {name: -np.asarray(t) for name, t in p["tables"].items()}
    v2 = CachedEmbeddingModel(tables2, p["columns"], p["im"],
                              cache=cache, version="v2")
    # uncached references for the two expected rankings
    expect_v1 = CachedEmbeddingModel(
        p["tables"], p["columns"], p["im"]).predict(p["req"])[0]
    expect_v2 = CachedEmbeddingModel(
        tables2, p["columns"], p["im"]).predict(p["req"])[0]
    assert not np.array_equal(expect_v1, expect_v2)

    reg = ModelRegistry()
    cache.attach(reg)
    reg.register("recsys", v1, version="v1")
    _, tf = _event_pipeline(k=4)
    ev = np.array(["u3", "i1", "i2", "i5", "i7"], dtype="<U8")
    replies, errors = [], []
    stop = threading.Event()

    def client():
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        i = 0
        try:
            while not stop.is_set() and i < 400:
                uid = iq.enqueue(f"r{i}", model="recsys", t=ev)
                replies.append(np.asarray(oq.query(uid, timeout=30.0)))
                i += 1
        except Exception as e:  # noqa: BLE001 - any failure fails the test
            errors.append(e)
        finally:
            iq.close()

    with ClusterServing(models=reg, pipelines={"recsys": tf},
                        batch_size=4, batch_timeout_ms=2,
                        inference_workers=2) as srv:
        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        # let v1 serve (and populate the cache), then flip under load
        deadline = 30.0
        import time
        t0 = time.monotonic()
        while not replies and time.monotonic() - t0 < deadline:
            time.sleep(0.01)
        reg.swap("recsys", v2, version="v2", warm=False)
        # keep serving until v2 rankings flow
        while (not any(np.array_equal(r, expect_v2) for r in replies[-6:])
               and time.monotonic() - t0 < deadline and not errors):
            time.sleep(0.02)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        # a post-swap request must rank with v2 rows only
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        final = np.asarray(oq.query(iq.enqueue("fin", model="recsys",
                                               t=ev), timeout=30.0))
        iq.close()

    assert not errors, errors
    assert replies
    bad = [r for r in replies
           if not (np.array_equal(r, expect_v1)
                   or np.array_equal(r, expect_v2))]
    assert not bad, f"stale/blended rankings: {bad[:3]}"
    np.testing.assert_array_equal(final, expect_v2)
    assert any(np.array_equal(r, expect_v2) for r in replies)
    # the flip dropped every v1 row at swap time
    assert cache.invalidate("recsys", "v1") == 0
