"""Serving stack end-to-end: InferenceModel, ClusterServing over loopback,
client queues, error paths, backpressure, and the HTTP frontend.

Reference test strategy (SURVEY.md §4.3): serving pre/post-processing and
engine specs ran on a Flink MiniCluster + local Redis.  The analog here is
the real server on a loopback port with real sockets and threads.
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import analytics_zoo_tpu.nn as nn
from analytics_zoo_tpu.core import init_orca_context
from analytics_zoo_tpu.serving import (ClusterServing, HTTPFrontend,
                                       InferenceModel, InputQueue,
                                       OutputQueue)
from analytics_zoo_tpu.serving import protocol


def _linear_model():
    init_orca_context("local")

    class M(nn.Module):
        def forward(self, scope, x):
            return scope.child(nn.Dense(3), x, name="fc")

    m = M()
    variables = m.init(__import__("jax").random.PRNGKey(0),
                       np.zeros((1, 4), np.float32))
    return m, variables


@pytest.fixture(scope="module")
def inference_model():
    m, variables = _linear_model()
    return InferenceModel(batch_buckets=(1, 4, 8)).load(m, variables)


# -- InferenceModel alone -----------------------------------------------------

def test_inference_model_bucket_padding(inference_model):
    x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
    out = inference_model.predict(x)
    assert out.shape == (3, 3)
    # per-row result must not depend on bucket padding
    row0 = inference_model.predict(x[:1])
    np.testing.assert_allclose(out[0], row0[0], rtol=1e-5)


def test_inference_model_chunking(inference_model):
    x = np.random.default_rng(1).normal(size=(19, 4)).astype(np.float32)
    out = inference_model.predict(x)          # 19 > largest bucket (8)
    assert out.shape == (19, 3)
    np.testing.assert_allclose(out[:4], inference_model.predict(x[:4]),
                               rtol=1e-5)


# -- ClusterServing round-trips ----------------------------------------------

def test_serving_round_trip(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        x = np.arange(4, dtype=np.float32)
        uid = iq.enqueue("t", t=x)
        out = oq.query(uid, timeout=20.0)
        assert out is not None and out.shape == (3,)
        expect = inference_model.predict(x[None])[0]
        np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_serving_concurrent_mixed_shapes(inference_model):
    """Many clients, two different feature shapes, all answered correctly."""
    with ClusterServing(inference_model, batch_size=8,
                        batch_timeout_ms=20) as srv:
        results = {}
        errors = []

        def client(i):
            try:
                iq = InputQueue(srv.host, srv.port)
                oq = OutputQueue(input_queue=iq)
                x = np.full((4,), float(i), np.float32)
                uid = iq.enqueue(f"c{i}", t=x)
                out = oq.query(uid, timeout=30.0)
                results[i] = out
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 12
        for i, out in results.items():
            expect = inference_model.predict(
                np.full((1, 4), float(i), np.float32))[0]
            np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_serving_survives_header_only_frame(inference_model):
    """ADVICE r1 (high): a header-only frame must get an error reply and must
    NOT kill the batcher thread for everyone else."""
    import socket
    with ClusterServing(inference_model, batch_size=2) as srv:
        raw = socket.create_connection((srv.host, srv.port), timeout=10)
        try:
            protocol.send_frame(raw, protocol.encode({"uuid": "bad-1"}))
            reply = protocol.recv_frame(raw)
            header, arr = protocol.decode(reply)
            assert header["uuid"] == "bad-1" and "error" in header
        finally:
            raw.close()
        # the server must still answer a valid request afterwards
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("ok", t=np.ones(4, np.float32))
        assert oq.query(uid, timeout=20.0) is not None


class _SlowModel:
    """Stub standing in for InferenceModel: slow + optionally failing."""

    def __init__(self, delay=0.0, fail=False):
        self.delay = delay
        self.fail = fail

    def predict(self, x):
        if self.delay:
            time.sleep(self.delay)
        if self.fail:
            raise ValueError("boom")
        return np.asarray(x) * 2.0


def test_serving_error_reply_reaches_client():
    with ClusterServing(_SlowModel(fail=True), batch_size=2) as srv:
        iq = InputQueue(srv.host, srv.port)
        oq = OutputQueue(input_queue=iq)
        uid = iq.enqueue("t", t=np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            oq.query(uid, timeout=20.0)
        # batcher survives a failing model too
        uid2 = iq.enqueue("t2", t=np.ones(4, np.float32))
        with pytest.raises(RuntimeError, match="boom"):
            oq.query(uid2, timeout=20.0)


def test_serving_backpressure_queue_full():
    """With a 1-slot queue, a slow model, and a tiny push timeout, floods get
    explicit 'queue full' error replies instead of silent drops.  Retries
    are disabled so the raw server-side rejection reaches the caller
    (the default client retries these — tests/test_robustness.py)."""
    from analytics_zoo_tpu.serving.client import RetryPolicy
    with ClusterServing(_SlowModel(delay=0.3), batch_size=1,
                        queue_items=1, push_timeout=0.05) as srv:
        iq = InputQueue(srv.host, srv.port,
                        retry=RetryPolicy(max_attempts=1))
        oq = OutputQueue(input_queue=iq)
        uids = [iq.enqueue(f"f{i}", t=np.ones(2, np.float32))
                for i in range(8)]
        outcomes = {"ok": 0, "full": 0}
        for uid in uids:
            try:
                out = oq.query(uid, timeout=30.0)
                if out is not None:
                    outcomes["ok"] += 1
            except RuntimeError as e:
                assert "queue full" in str(e)
                outcomes["full"] += 1
        assert outcomes["ok"] >= 1     # service still makes progress
        assert outcomes["full"] >= 1   # and sheds load explicitly


def test_native_queue_empty_payload():
    """ADVICE r1 (low): a zero-length payload is a valid item, not a
    timeout."""
    from analytics_zoo_tpu.native import NativeQueue
    q = NativeQueue(max_items=4)
    assert q.push(b"", tag=7)
    item = q.pop(timeout=1.0)
    assert item is not None
    payload, tag = item
    assert payload == b"" and tag == 7


# -- HTTP frontend ------------------------------------------------------------

def test_http_frontend(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            with urllib.request.urlopen(url + "/health", timeout=10) as r:
                assert json.load(r)["status"] == "ok"
            req = urllib.request.Request(
                url + "/predict",
                data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                pred = json.load(r)["predictions"]
            expect = inference_model.predict(
                np.asarray([[1, 2, 3, 4]], np.float32))
            np.testing.assert_allclose(np.asarray(pred), expect, rtol=1e-4)


def test_http_frontend_bad_request(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}/predict"
            req = urllib.request.Request(
                url, data=b'{"wrong": 1}',
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400


def test_http_frontend_reconnects_after_backend_restart(inference_model):
    """A backend restart must not permanently kill the HTTP frontend."""
    srv = ClusterServing(inference_model, batch_size=4).start()
    port = srv.port
    fe = HTTPFrontend(srv.host, port).start()
    try:
        x = np.ones((1, 4), np.float32)
        assert fe.predict(x) is not None
        srv.stop()
        deadline = time.time() + 10
        while True:  # wait for the OS to release the port
            try:
                srv = ClusterServing(inference_model, port=port,
                                     batch_size=4).start()
                break
            except OSError:
                if time.time() > deadline:
                    raise
                time.sleep(0.1)
        out = fe.predict(x)  # reconnect happens inside predict
        assert out is not None
        np.testing.assert_allclose(np.squeeze(out),
                                   np.squeeze(inference_model.predict(x)),
                                   rtol=1e-5)
    finally:
        fe.stop()
        srv.stop()


def test_serving_and_frontend_stats(inference_model):
    with ClusterServing(inference_model, batch_size=4) as srv:
        with HTTPFrontend(srv.host, srv.port) as fe:
            url = f"http://{fe.host}:{fe.port}"
            for _ in range(3):
                req = urllib.request.Request(
                    url + "/predict",
                    data=json.dumps({"instances": [[1, 2, 3, 4]]}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=30):
                    pass
            with urllib.request.urlopen(url + "/stats", timeout=10) as r:
                fstats = json.load(r)
            assert fstats["requests"] == 3 and fstats["timeouts"] == 0
        s = srv.stats()
        assert s["requests"] == 3 and s["replies"] == 3
        assert s["batches"] >= 1 and s["errors"] == 0
        assert 1.0 <= s["mean_batch_size"] <= 4.0


def test_inference_model_bf16_serving_dtype():
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    m = nn.Sequential([nn.Dense(8, activation="relu"), nn.Dense(3)])
    v = m.init(jax.random.PRNGKey(0), np.ones((1, 4), np.float32))
    f32 = InferenceModel().load(m, v)
    bf16 = InferenceModel().load(m, v, dtype=jnp.bfloat16)
    x = np.random.default_rng(0).normal(size=(4, 4)).astype(np.float32)
    a, b = f32.predict(x), bf16.predict(x)
    np.testing.assert_allclose(a, b, rtol=2e-2, atol=2e-2)  # bf16 tolerance
    assert not np.allclose(a, b, rtol=1e-7, atol=0)  # actually lower precision


def test_update_model_hot_swap():
    import jax
    import analytics_zoo_tpu.nn as nn

    def make(bias_val):
        m = nn.Sequential([nn.Lambda(lambda x: x * 0.0 + bias_val)])
        v = m.init(jax.random.PRNGKey(0), np.ones((1, 4), np.float32))
        return InferenceModel().load(m, v)

    with ClusterServing(make(1.0), batch_size=4) as srv:
        q = InputQueue(srv.host, srv.port)
        out_q = OutputQueue(input_queue=q)
        uid = q.enqueue("a", t=np.ones(4, np.float32))
        before = out_q.query(uid, timeout=30)
        np.testing.assert_allclose(before, np.ones(4), rtol=1e-6)
        srv.update_model(make(2.0))  # hot-swap on the SAME connection
        uid2 = q.enqueue("b", t=np.ones(4, np.float32))
        after = out_q.query(uid2, timeout=30)
        np.testing.assert_allclose(after, np.full(4, 2.0), rtol=1e-6)
        q.close()


def test_inference_model_int8_weight_quantization():
    """Weight-only int8 serving (reference: doLoadOpenVINOInt8): large
    float params are stored int8 + per-channel scales (4x smaller), and
    predictions stay close to the f32 model."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import (InferenceModel,
                                                           _Q_MARKER)

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(256, activation="relu"),
                           nn.Dense(128, activation="relu"),
                           nn.Dense(10)])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 64)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8")
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)
    # int8 weights + bf16 activations: small but nonzero error
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.08

    # big kernels really stored int8; small leaves (biases) stay float
    p = q._variables["params"]
    layer0 = p[next(iter(p))]  # first Dense layer's params
    k0 = layer0["kernel"]
    assert isinstance(k0, dict) and _Q_MARKER in k0
    assert k0["q"].dtype == jnp.int8
    assert not isinstance(layer0["bias"], dict)


def test_inference_model_int8_calibrated_activations():
    """Calibrated int8 (reference: OpenVINO INT8 calibration): a
    calibration batch freezes static per-tensor activation scales; Dense
    matmuls then run int8 x int8 -> int32 with per-channel rescale.
    Accuracy must stay close to f32, and the activation scales must
    actually come from the calibration pass."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(256, activation="relu"),
                           nn.Dense(128, activation="relu"),
                           nn.Dense(10)])
    rng = np.random.default_rng(3)
    calib = rng.normal(size=(32, 64)).astype(np.float32)
    x = rng.normal(size=(16, 64)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))

    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    # one scale per Dense layer, recorded during the calibration forward
    assert q._quant_ctx is not None and len(q._quant_ctx.amax) == 3
    assert all(a > 0 for a in q._quant_ctx.amax.values())
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)
    # int8 weights AND int8 activations: bounded accuracy delta vs f32
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.15
    # ranking (the serving-relevant signal) preserved on most rows
    agree = np.mean(out_q.argmax(1) == out_ref.argmax(1))
    assert agree >= 0.8


def test_inference_model_int8_calibrated_with_lstm():
    """Regression (r4 review): calibrated int8 must leave NON-Dense 2-D
    kernels (LSTM input/recurrent kernels) dequantized — only nn.Dense
    can consume the int8 dict form."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.LSTM(64), nn.Dense(16, activation="relu"),
                           nn.Dense(4)])
    rng = np.random.default_rng(5)
    calib = rng.normal(size=(8, 12, 16)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))
    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    x = rng.normal(size=(4, 12, 16)).astype(np.float32)
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)  # must not crash
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.2


def test_inference_model_reload_and_int8_dtype_spellings():
    """Regression (r3 review): reloading clears stale executables, and
    jnp.int8/np.int8 route to weight-only quantization (NOT a float->int
    cast that zeroes weights)."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(128, activation="relu"),
                           nn.Dense(4)])
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 64)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    im = InferenceModel()
    im.load(model, variables)
    ref = np.asarray(im.predict(x), np.float32)
    # reload with a different variable STRUCTURE (int8 markers) — must
    # recompile, not crash on the stale executable
    im.load(model, variables, dtype=jnp.int8)
    out = np.asarray(im.predict(x), np.float32)
    assert not np.allclose(out, 0.0)  # int8 CAST would zero the weights
    denom = np.maximum(np.abs(ref), 1.0)
    assert np.max(np.abs(out - ref) / denom) < 0.08


def test_calibrate_without_int8_raises():
    """Regression (r4 review): a calibration batch with a non-int8 dtype
    must error, not be silently ignored."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel
    init_orca_context("local")
    m = nn.Sequential([nn.Dense(4)])
    x = np.zeros((2, 3), np.float32)
    v = m.init(jax.random.PRNGKey(0), jnp.asarray(x))
    with pytest.raises(ValueError, match="calibrate"):
        InferenceModel().load(m, v, calibrate=x)
    with pytest.raises(ValueError, match="calibrate"):
        InferenceModel().load(m, v, dtype=jnp.bfloat16, calibrate=x)


def test_calibrator_rejects_traced_forward():
    """Regression (r4 advisor): running the calibration forward under
    jit must fail with an actionable message, not an opaque
    TracerError deep inside float()."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.nn.quant import Calibrator

    calib = Calibrator()

    def f(x):
        calib.observe(("dense",), x)
        return x

    with pytest.raises(RuntimeError, match="UNJITTED"):
        jax.jit(f)(jnp.ones((2, 2)))


def test_inference_model_int8_calibrated_conv():
    """Calibrated int8 for CNNs (reference: OpenVINO INT8 calibrated
    whole CNNs): plain Conv2D inputs get static activation scales and
    run as int8 x int8 -> int32 convs; accuracy stays bounded vs f32 and
    the conv kernels really stay int8 through the serving path."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([
        nn.Conv2D(32, 3, activation="relu"),
        nn.Conv2D(64, 3, strides=2, activation="relu"),
        nn.GlobalAveragePooling2D(),
        nn.Dense(10)])
    rng = np.random.default_rng(7)
    calib = rng.normal(size=(16, 16, 16, 3)).astype(np.float32)
    x = rng.normal(size=(8, 16, 16, 3)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))

    ref = InferenceModel().load(model, variables)
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    # both convs AND the dense observed during calibration
    assert q._quant_ctx is not None and len(q._quant_ctx.amax) == 3
    out_ref = np.asarray(ref.predict(x), np.float32)
    out_q = np.asarray(q.predict(x), np.float32)
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.2
    agree = np.mean(out_q.argmax(1) == out_ref.argmax(1))
    assert agree >= 0.75, agree


def test_ws_conv_stays_weight_only_under_calibration():
    """ScaledWSConv2D must NOT take the activation-quantized path (its
    weight standardization needs the float kernel): calibration must
    skip it and serving must still produce finite, close-to-f32 output."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    # kernel 3*3*24*64 = 13,824 elements: ABOVE _Q_MIN_SIZE, so it
    # really is stored int8 and the WS conv must dequantize the dict
    # (a sub-threshold kernel would stay float and test nothing)
    model = nn.Sequential([
        nn.ScaledWSConv2D(64, 3, activation="relu"),
        nn.GlobalAveragePooling2D(),
        nn.Dense(8)])
    rng = np.random.default_rng(8)
    calib = rng.normal(size=(8, 12, 12, 24)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(calib))
    q = InferenceModel().load(model, variables, dtype="int8",
                              calibrate=calib)
    # only the Dense observed — the WS conv opted out
    assert len(q._quant_ctx.amax) == 1
    ref = InferenceModel().load(model, variables)
    out_q = np.asarray(q.predict(calib), np.float32)
    out_ref = np.asarray(ref.predict(calib), np.float32)
    assert np.all(np.isfinite(out_q))
    denom = np.maximum(np.abs(out_ref), 1.0)
    assert np.max(np.abs(out_q - out_ref) / denom) < 0.2


def test_save_load_executables_roundtrip(tmp_path):
    """Serialized AOT artifacts (reference: OpenVINO IR) round-trip: a
    fresh InferenceModel loads them, skips tracing, and predicts the
    same values; a config mismatch (different precision) ignores them."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    model = nn.Sequential([nn.Dense(32, activation="relu"), nn.Dense(4)])
    rng = np.random.default_rng(9)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.asarray(x))

    src = InferenceModel().load(model, variables)
    want = np.asarray(src.predict(x))
    n = src.save_executables(str(tmp_path / "aot"))
    assert n == 1  # one (shape, dtype) bucket compiled

    dst = InferenceModel().load(model, variables)
    assert dst.load_executables(str(tmp_path / "aot")) == 1
    got = np.asarray(dst.predict(x))  # served via the deserialized artifact
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # precision mismatch -> artifacts ignored, fresh compile still works
    other = InferenceModel().load(model, variables, dtype=jnp.bfloat16)
    assert other.load_executables(str(tmp_path / "aot")) == 0
    assert np.asarray(other.predict(x)).shape == want.shape


def test_load_executables_rejects_stale_model_code(tmp_path):
    """A model-code edit that leaves the variable tree identical must
    NOT silently serve the stale artifact: the traced-computation hash
    (manifest "jaxpr") catches it; verify=False trusts the artifact."""
    import jax
    import jax.numpy as jnp
    import analytics_zoo_tpu.nn as nn
    from analytics_zoo_tpu.serving.inference_model import InferenceModel

    init_orca_context("local")
    rng = np.random.default_rng(10)
    x = rng.normal(size=(4, 8)).astype(np.float32)
    relu_net = nn.Sequential([nn.Dense(16, activation="relu"),
                              nn.Dense(4)])
    gelu_net = nn.Sequential([nn.Dense(16, activation="gelu"),
                              nn.Dense(4)])  # same param tree, new math
    variables = relu_net.init(jax.random.PRNGKey(0), jnp.asarray(x))

    src = InferenceModel().load(relu_net, variables)
    src.predict(x)
    assert src.save_executables(str(tmp_path / "aot")) == 1

    stale = InferenceModel().load(gelu_net, variables)
    assert stale.load_executables(str(tmp_path / "aot")) == 0
    # and the unverified fast path loads it (caller's responsibility)
    assert stale.load_executables(str(tmp_path / "aot"),
                                  verify=False) == 1
